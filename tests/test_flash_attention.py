"""Pallas flash-attention kernel vs the reference oracle (interpret
mode on the CPU mesh; identical code path runs compiled on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.flash_attention import flash_attention
from edl_tpu.ops.ring_attention import reference_attention


def qkv(seed, B=2, T=128, H=2, D=64, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = qkv(0)
    want = reference_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_odd_block_sizes():
    # T=96 forces non-default divisor blocks (96 % 128 != 0)
    q, k, v = qkv(1, T=96)
    want = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_gradients_match():
    q, k, v = qkv(2, T=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_flash_bf16():
    q, k, v = qkv(3, dtype=jnp.bfloat16)
    want = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=3e-2, rtol=3e-2
    )


def test_flash_under_jit_and_vmapless_batching():
    q, k, v = qkv(4, B=4, T=64)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    got = f(q, k, v)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_backward_multi_tile_scratch_accumulation():
    """Gradients with num_i > 1 Q tiles: exercises the merged backward's
    cross-grid-step dK/dV scratch (i==0 zero-init, += across Q tiles,
    flush at i == num_i - 1), with causal + kv_mask composed."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from edl_tpu.ops.flash_attention import flash_attention
    from edl_tpu.ops.ring_attention import reference_attention

    rng = np.random.RandomState(3)
    B, T, H, D = 2, 256, 2, 32
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    lens = rng.randint(T // 2, T, size=B)
    kv_mask = jnp.asarray(np.arange(T)[None, :] < lens[:, None])

    # block 64 on T=256 -> 4 Q tiles per (b, h): scratch accumulates
    # across grid steps instead of living within one.
    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, causal=True, kv_mask=kv_mask, block_q=64, block_k=64
        )
        return (out.astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, causal=True, kv_mask=kv_mask)
        return (out.astype(jnp.float32) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, ("dq", "dk", "dv")):
        err = float(jnp.max(jnp.abs(a - b)))
        assert err < 1e-3, f"{name} mismatch: {err}"


def test_flash_attention_with_lse_matches_oracle():
    """(o, lse) variant: lse values exact vs logsumexp, and gradients
    flow through BOTH outputs (the lse cotangent folds into the
    backward kernel's delta) — the contract ring_attention's
    normalized-partial merge depends on."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.ops.flash_attention import flash_attention_with_lse

    B, T, H, D = 2, 64, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    scale = 1.0 / D**0.5

    def oracle(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        return o, lse

    o_f, lse_f = flash_attention_with_lse(q, k, v, causal=True)
    o_r, lse_r = oracle(q, k, v)
    assert float(jnp.max(jnp.abs(o_f - o_r))) < 1e-5
    assert float(jnp.max(jnp.abs(lse_f - lse_r))) < 1e-5

    def loss(attn):
        def f(q, k, v):
            o, lse = attn(q, k, v)
            return (o * v).sum() + jnp.sin(lse).sum()  # uses BOTH outputs
        return f

    gf = jax.grad(loss(lambda *a: flash_attention_with_lse(*a, causal=True)),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(oracle), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_flash_attention_with_lse_kv_mask_gradients():
    """The glse+mask combined backward path (both optional kernel slots
    live) — guards the adapter's argument ordering."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.ops.flash_attention import flash_attention_with_lse

    B, T, H, D = 2, 32, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    kv_mask = jnp.arange(T)[None, :] < jnp.array([[T - 5], [T - 9]])[..., 0][:, None]
    scale = 1.0 / D**0.5

    def oracle(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        s = jnp.where(kv_mask[:, None, None, :], s, -1e30)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        return o, lse

    def loss(attn):
        def f(q, k, v):
            o, lse = attn(q, k, v)
            return (o * v).sum() + jnp.cos(lse).sum()
        return f

    gf = jax.grad(
        loss(lambda *a: flash_attention_with_lse(*a, kv_mask=kv_mask)),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(loss(oracle), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_streamk_backward_matches_merged():
    """The streaming-K backward (the T > 16384 fallback; VMEM use
    independent of T) must produce the same gradients as the merged
    kernel on every masking variant, including the differentiable-lse
    path the ring combiner uses."""
    import importlib

    import jax
    import jax.numpy as jnp
    import numpy as np

    fa = importlib.import_module("edl_tpu.ops.flash_attention")
    rng = np.random.RandomState(0)
    B, T, H, D = 2, 64, 2, 16
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    mask = jnp.asarray(rng.rand(B, T) > 0.2)

    try:
        for causal, use_mask in [
            (False, False), (True, False), (False, True), (True, True)
        ]:
            kv = mask if use_mask else None

            def loss(q, k, v, impl):
                fa._BWD_IMPL_OVERRIDE = impl
                o = fa.flash_attention(
                    q, k, v, causal=causal, kv_mask=kv,
                    block_q=16, block_k=16, interpret=True,
                )
                return jnp.sum(o * o * 0.37)

            gm = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, "merged")
            gs = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, "streamk")
            for a, b, name in zip(gm, gs, "qkv"):
                np.testing.assert_allclose(
                    a, b, rtol=2e-5, atol=2e-5,
                    err_msg=f"{causal=} {use_mask=} d{name}",
                )

        def loss_lse(q, k, v, impl):
            fa._BWD_IMPL_OVERRIDE = impl
            o, lse = fa.flash_attention_with_lse(
                q, k, v, causal=True, block_q=16, block_k=16,
                interpret=True,
            )
            return jnp.sum(o * o * 0.1) + jnp.sum(jnp.sin(lse))

        gm = jax.grad(loss_lse, argnums=(0, 1, 2))(q, k, v, "merged")
        gs = jax.grad(loss_lse, argnums=(0, 1, 2))(q, k, v, "streamk")
        for a, b, name in zip(gm, gs, "qkv"):
            np.testing.assert_allclose(
                a, b, rtol=2e-5, atol=2e-5, err_msg=f"lse d{name}"
            )
    finally:
        fa._BWD_IMPL_OVERRIDE = None


def test_streamk_dispatch_thresholds():
    """The merged kernel (forward-size tiles + raised VMEM limit)
    serves up to T=16384; beyond it the streaming-K defaults
    (256 x 2048) apply."""
    import importlib

    fa = importlib.import_module("edl_tpu.ops.flash_attention")
    import jax.numpy as jnp

    for t in (2048, 4096, 16384):
        q = jnp.zeros((1, t, 1, 16), jnp.bfloat16)
        prep = fa._prep(q, q, True, None, None, None, None, None, None, True)
        _, _, _, bq, bk, bwd_q, bwd_k, _ = prep
        assert (bwd_q, bwd_k) == (bq, bk) == (512, 512), (t, prep)
    q32k = jnp.zeros((1, 32768, 1, 16), jnp.bfloat16)
    prep = fa._prep(q32k, q32k, True, None, None, None, None, None, None, True)
    _, _, _, _, _, bwd_q, bwd_k, _ = prep
    # block_k scales with T past the merged ceiling so the dQ-partials
    # buffer stays bounded at <= 8 K blocks' worth.
    assert (bwd_q, bwd_k) == (256, 32768 // 8)
    # VMEM policy: default limit at short T, scaled + capped beyond.
    assert fa._vmem_limit(2048, 64) is None
    assert fa._vmem_limit(4096, 64) == 16 * 1024 * 1024 + 4 * 4096 * 64 * 12
    assert fa._vmem_limit(1 << 20, 64) == 100 * 1024 * 1024
