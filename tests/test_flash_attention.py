"""Pallas flash-attention kernel vs the reference oracle (interpret
mode on the CPU mesh; identical code path runs compiled on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.flash_attention import flash_attention
from edl_tpu.ops.ring_attention import reference_attention


def qkv(seed, B=2, T=128, H=2, D=64, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = qkv(0)
    want = reference_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_odd_block_sizes():
    # T=96 forces non-default divisor blocks (96 % 128 != 0)
    q, k, v = qkv(1, T=96)
    want = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_gradients_match():
    q, k, v = qkv(2, T=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_flash_bf16():
    q, k, v = qkv(3, dtype=jnp.bfloat16)
    want = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=3e-2, rtol=3e-2
    )


def test_flash_under_jit_and_vmapless_batching():
    q, k, v = qkv(4, B=4, T=64)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    got = f(q, k, v)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_backward_multi_tile_scratch_accumulation():
    """Gradients with num_i > 1 Q tiles: exercises the merged backward's
    cross-grid-step dK/dV scratch (i==0 zero-init, += across Q tiles,
    flush at i == num_i - 1), with causal + kv_mask composed."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from edl_tpu.ops.flash_attention import flash_attention
    from edl_tpu.ops.ring_attention import reference_attention

    rng = np.random.RandomState(3)
    B, T, H, D = 2, 256, 2, 32
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    lens = rng.randint(T // 2, T, size=B)
    kv_mask = jnp.asarray(np.arange(T)[None, :] < lens[:, None])

    # block 64 on T=256 -> 4 Q tiles per (b, h): scratch accumulates
    # across grid steps instead of living within one.
    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, causal=True, kv_mask=kv_mask, block_q=64, block_k=64
        )
        return (out.astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, causal=True, kv_mask=kv_mask)
        return (out.astype(jnp.float32) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, ("dq", "dk", "dv")):
        err = float(jnp.max(jnp.abs(a - b)))
        assert err < 1e-3, f"{name} mismatch: {err}"
