"""Pallas flash-attention kernel vs the reference oracle (interpret
mode on the CPU mesh; identical code path runs compiled on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.flash_attention import flash_attention
from edl_tpu.ops.ring_attention import reference_attention


def qkv(seed, B=2, T=128, H=2, D=64, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = qkv(0)
    want = reference_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_odd_block_sizes():
    # T=96 forces non-default divisor blocks (96 % 128 != 0)
    q, k, v = qkv(1, T=96)
    want = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_gradients_match():
    q, k, v = qkv(2, T=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_flash_bf16():
    q, k, v = qkv(3, dtype=jnp.bfloat16)
    want = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=3e-2, rtol=3e-2
    )


def test_flash_under_jit_and_vmapless_batching():
    q, k, v = qkv(4, B=4, T=64)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    got = f(q, k, v)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_backward_multi_tile_scratch_accumulation():
    """Gradients with num_i > 1 Q tiles: exercises the merged backward's
    cross-grid-step dK/dV scratch (i==0 zero-init, += across Q tiles,
    flush at i == num_i - 1), with causal + kv_mask composed."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from edl_tpu.ops.flash_attention import flash_attention
    from edl_tpu.ops.ring_attention import reference_attention

    rng = np.random.RandomState(3)
    B, T, H, D = 2, 256, 2, 32
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    lens = rng.randint(T // 2, T, size=B)
    kv_mask = jnp.asarray(np.arange(T)[None, :] < lens[:, None])

    # block 64 on T=256 -> 4 Q tiles per (b, h): scratch accumulates
    # across grid steps instead of living within one.
    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, causal=True, kv_mask=kv_mask, block_q=64, block_k=64
        )
        return (out.astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, causal=True, kv_mask=kv_mask)
        return (out.astype(jnp.float32) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, ("dq", "dk", "dv")):
        err = float(jnp.max(jnp.abs(a - b)))
        assert err < 1e-3, f"{name} mismatch: {err}"


def test_flash_attention_with_lse_matches_oracle():
    """(o, lse) variant: lse values exact vs logsumexp, and gradients
    flow through BOTH outputs (the lse cotangent folds into the
    backward kernel's delta) — the contract ring_attention's
    normalized-partial merge depends on."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.ops.flash_attention import flash_attention_with_lse

    B, T, H, D = 2, 64, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    scale = 1.0 / D**0.5

    def oracle(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        return o, lse

    o_f, lse_f = flash_attention_with_lse(q, k, v, causal=True)
    o_r, lse_r = oracle(q, k, v)
    assert float(jnp.max(jnp.abs(o_f - o_r))) < 1e-5
    assert float(jnp.max(jnp.abs(lse_f - lse_r))) < 1e-5

    def loss(attn):
        def f(q, k, v):
            o, lse = attn(q, k, v)
            return (o * v).sum() + jnp.sin(lse).sum()  # uses BOTH outputs
        return f

    gf = jax.grad(loss(lambda *a: flash_attention_with_lse(*a, causal=True)),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(oracle), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_flash_attention_with_lse_kv_mask_gradients():
    """The glse+mask combined backward path (both optional kernel slots
    live) — guards the adapter's argument ordering."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.ops.flash_attention import flash_attention_with_lse

    B, T, H, D = 2, 32, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    kv_mask = jnp.arange(T)[None, :] < jnp.array([[T - 5], [T - 9]])[..., 0][:, None]
    scale = 1.0 / D**0.5

    def oracle(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        s = jnp.where(kv_mask[:, None, None, :], s, -1e30)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        return o, lse

    def loss(attn):
        def f(q, k, v):
            o, lse = attn(q, k, v)
            return (o * v).sum() + jnp.cos(lse).sum()
        return f

    gf = jax.grad(
        loss(lambda *a: flash_attention_with_lse(*a, kv_mask=kv_mask)),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(loss(oracle), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_bwd_block_default_shrinks_with_context():
    """VMEM-aware backward tiles: the forward's 512 default up to
    T=2048, 256 beyond (measured v5e ceiling — see _default_bwd_block)."""
    from edl_tpu.ops.flash_attention import _default_bwd_block

    assert _default_bwd_block(512, 2048) == 512
    assert _default_bwd_block(512, 4096) == 256
    assert _default_bwd_block(128, 4096) == 128  # explicit small stays
