"""L2 jobparser + L4 controller/lifecycle tests, plus the coordinator
HTTP service round trip."""

import pytest

from edl_tpu.autoscaler.scaler import Autoscaler
from edl_tpu.cluster.cluster import Cluster
from edl_tpu.cluster.kube import FakeKube, NodeInfo
from edl_tpu.controller.controller import Controller
from edl_tpu.controller.jobparser import (
    JOB_LABEL,
    parse_to_coordinator,
    parse_to_trainer,
    pod_env,
)
from edl_tpu.controller.lifecycle import JobLifecycle
from edl_tpu.resource.training_job import JobState, TrainingJob


def make_job(name="demo", mn=1, mx=4, topo="v5e-4"):
    return TrainingJob.from_manifest(
        {
            "apiVersion": "edl.tpu.dev/v1",
            "kind": "TrainingJob",
            "metadata": {"name": name},
            "spec": {
                "fault_tolerant": mn < mx,
                "trainer": {
                    "entrypoint": "mnist",
                    "min_instance": mn,
                    "max_instance": mx,
                    "slice_topology": topo,
                    "resources": {"requests": {"cpu": "1", "memory": "2Gi"}},
                },
            },
        }
    ).validate()


def tpu_nodes(n=4, chips=4):
    return [
        NodeInfo(name=f"pool-{i}", cpu_milli=8000, memory_mega=32768, tpu_chips=chips)
        for i in range(n)
    ]


# ---- jobparser --------------------------------------------------------------


def test_parse_to_trainer_shape():
    job = make_job()
    m = parse_to_trainer(job)
    assert m["kind"] == "Job"
    assert m["metadata"]["name"] == "demo-trainer"
    assert m["spec"]["parallelism"] == 1
    tmpl = m["spec"]["template"]["spec"]
    assert tmpl["restartPolicy"] == "Never"  # ref pkg/jobparser.go:153
    c = tmpl["containers"][0]
    assert c["resources"]["limits"]["google.com/tpu"] == "4"
    assert tmpl["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x2"
    assert m["metadata"]["labels"][JOB_LABEL] == "demo"


def test_pod_env_contract():
    job = make_job()
    env = {e["name"]: e.get("value") for e in pod_env(job)}
    assert env["EDL_JOB_NAME"] == "demo"
    assert env["EDL_COORDINATOR_ADDR"] == "demo-coordinator:7164"
    assert env["EDL_ENTRYPOINT"] == "mnist"
    assert env["EDL_DATA_DIR"] == ""  # spec.dataset_dir passthrough
    assert env["EDL_MIN_INSTANCE"] == "1"
    assert env["EDL_MAX_INSTANCE"] == "4"
    assert env["EDL_FAULT_TOLERANT"] == "1"
    # rank/world deliberately absent: membership facts live in the
    # coordinator, not env (the reference's TRAINERS env was wrong under
    # elasticity, ref pkg/jobparser.go:281-285)
    assert "EDL_RANK" not in env and "EDL_WORLD" not in env


def test_parse_to_coordinator_is_deployment_plus_service():
    job = make_job()
    dep, svc = parse_to_coordinator(job)
    assert dep["kind"] == "Deployment" and dep["spec"]["replicas"] == 1
    assert svc["kind"] == "Service"
    assert svc["spec"]["ports"][0]["port"] == 7164
    cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
    assert "edl_tpu.runtime.coord_service" in cmd


def test_cpu_job_has_no_tpu_selector():
    job = make_job(topo="cpu", mn=1, mx=2)
    m = parse_to_trainer(job)
    tmpl = m["spec"]["template"]["spec"]
    assert tmpl["nodeSelector"] == {}
    assert "google.com/tpu" not in m["spec"]["template"]["spec"]["containers"][0][
        "resources"
    ]["limits"]


# ---- lifecycle --------------------------------------------------------------


def test_lifecycle_ensure_creates_both_objects():
    kube = FakeKube(tpu_nodes())
    lc = JobLifecycle(Cluster(kube), sleep=lambda s: None)
    job = make_job()
    assert lc.ensure(job)
    assert kube.get_workload("demo-trainer") is not None
    assert kube.get_workload("demo-coordinator") is not None
    # idempotent
    assert lc.ensure(job)


def test_lifecycle_rollback_on_partial_failure():
    kube = FakeKube(tpu_nodes())
    cluster = Cluster(kube)
    lc = JobLifecycle(cluster, sleep=lambda s: None)
    job = make_job()

    real_create = kube.create_workload
    calls = {"n": 0}

    def failing_create(w):
        calls["n"] += 1
        if w.name.endswith("-trainer"):
            raise RuntimeError("boom")
        return real_create(w)

    kube.create_workload = failing_create
    assert not lc.ensure(job)
    # the coordinator created in the same attempt was rolled back
    assert kube.get_workload("demo-coordinator") is None


def test_lifecycle_complete_keeps_trainer():
    kube = FakeKube(tpu_nodes())
    lc = JobLifecycle(Cluster(kube), sleep=lambda s: None)
    job = make_job()
    lc.ensure(job)
    lc.complete(job)
    assert kube.get_workload("demo-coordinator") is None
    assert kube.get_workload("demo-trainer") is not None
    lc.destroy(job)
    assert kube.get_workload("demo-trainer") is None


# ---- controller -------------------------------------------------------------


def test_controller_wires_creation_and_scaling():
    kube = FakeKube(tpu_nodes(4))
    cluster = Cluster(kube)
    ctrl = Controller(cluster, clock=lambda: 100.0)
    job = make_job(mn=1, mx=4)
    ctrl.on_add(job)
    assert kube.get_workload("demo-trainer") is not None  # wired (ref TODO fixed)
    for _ in range(5):
        ctrl.run_once()
    assert cluster.get_trainer_workload(job).parallelism == 4
    st = ctrl.job_statuses()[0]
    assert st["state"] in ("Running", "Scaling")
    assert st["parallelism"] == 4


def test_controller_status_state_machine():
    clock = {"t": 100.0}
    kube = FakeKube(tpu_nodes(1))
    cluster = Cluster(kube)
    ctrl = Controller(cluster, clock=lambda: clock["t"])
    job = make_job(mn=1, mx=1)
    ctrl.on_add(job)
    assert job.status.state == JobState.CREATED
    clock["t"] = 107.5
    ctrl.reconcile_status()
    assert job.status.state == JobState.RUNNING
    assert job.status.started_at == 107.5
    assert job.status.pending_seconds() == 7.5
    ctrl.mark_succeeded("demo")
    assert job.status.state == JobState.SUCCEED
    assert kube.get_workload("demo-coordinator") is None  # complete() ran
    # the finished job left the autoscaler's managed set
    ctrl.autoscaler._drain_events()
    assert "demo" not in ctrl.autoscaler.jobs


def test_controller_delete_tears_down():
    kube = FakeKube(tpu_nodes())
    ctrl = Controller(Cluster(kube))
    job = make_job()
    ctrl.on_add(job)
    ctrl.on_delete(job)
    assert kube.get_workload("demo-trainer") is None
    assert kube.get_workload("demo-coordinator") is None
    assert ctrl.jobs == {}


def test_controller_failed_creation_marks_failed():
    kube = FakeKube(tpu_nodes())
    cluster = Cluster(kube)
    ctrl = Controller(cluster, lifecycle=_AlwaysFailLifecycle(cluster))
    job = make_job()
    ctrl.on_add(job)
    assert job.status.state == JobState.FAILED


class _AlwaysFailLifecycle(JobLifecycle):
    def ensure(self, job):
        return False


# ---- coordinator service round trip ----------------------------------------


def test_coord_service_http_roundtrip():
    from edl_tpu.runtime.coord_service import CoordinatorServer, HTTPCoordinator
    from edl_tpu.runtime.coordinator import LocalCoordinator

    server = CoordinatorServer(
        LocalCoordinator(target_world=2, max_world=4), host="127.0.0.1", port=0
    ).start()
    try:
        c = HTTPCoordinator(f"127.0.0.1:{server.port}")
        p1 = c.register("a")
        assert p1.world_size == 1 and p1.members == ("a",)
        c.register("b")
        c.heartbeat("a")
        plan = c.plan()
        assert plan.world_size == 2
        c.ack_generation("a", plan.generation)
        c.set_target_world(1)
        assert c.plan().world_size == 1
        c.report_checkpoint(40)
        assert c.plan().restore_step == -1  # restore_step fixed at plan build
        c.deregister("b")
        assert c.members() == ["a"]
        assert c.evict_dead() == []
        with pytest.raises(Exception):
            c.heartbeat("ghost")
    finally:
        server.stop()


def test_coord_service_rejects_bad_target():
    from edl_tpu.runtime.coord_service import CoordinatorServer, HTTPCoordinator
    from edl_tpu.runtime.coordinator import LocalCoordinator

    server = CoordinatorServer(
        LocalCoordinator(target_world=1), host="127.0.0.1", port=0
    ).start()
    try:
        c = HTTPCoordinator(f"127.0.0.1:{server.port}")
        with pytest.raises(Exception):
            c.set_target_world(0)
    finally:
        server.stop()


def test_coordinator_command_carries_legal_sizes():
    """The deployed coordinator must quantize worlds exactly like the
    local path (review finding: legal sizes were dropped)."""
    job = TrainingJob.from_manifest(
        {
            "apiVersion": "edl.tpu.dev/v1",
            "kind": "TrainingJob",
            "metadata": {"name": "q"},
            "spec": {
                "fault_tolerant": True,
                "global_batch_size": 96,
                "trainer": {"min_instance": 1, "max_instance": 8,
                            "slice_topology": "v5e-4"},
            },
        }
    ).validate()
    dep, _ = parse_to_coordinator(job)
    cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
    i = cmd.index("--legal-sizes")
    assert cmd[i + 1] == "1,2,3,4,6,8"


def test_spec_update_rerenders_manifests():
    """An image change in the CR reaches the running workload, and the
    actuated parallelism survives the refresh (VERDICT r2 weak #9)."""
    kube = FakeKube(tpu_nodes())
    cluster = Cluster(kube)
    ctrl = Controller(cluster, Autoscaler(cluster))
    job = make_job("upd", mn=1, mx=4)
    ctrl.on_add(job)
    # autoscaler actuated a larger world meanwhile
    cluster.update_parallelism(job, 3)
    assert kube.get_workload("upd-trainer").parallelism == 3

    newer = make_job("upd", mn=1, mx=4)
    newer.spec.image = "edl-tpu/trainer:v2"
    ctrl.on_update(newer)
    w = kube.get_workload("upd-trainer")
    assert w is not None and w.parallelism == 3  # plan preserved
    # FakeKube keeps manifests for services only; assert via the render
    # path: a no-op update (same spec) must NOT re-apply (fingerprint
    # equality -> no refresh), which we observe via resource_version.
    rv = w.resource_version
    ctrl.on_update(newer)
    assert kube.get_workload("upd-trainer").resource_version == rv


def test_tick_kube_calls_constant_in_job_count(capsys):
    """A control tick must cost O(1) kubectl subprocesses regardless of
    how many jobs the controller manages (VERDICT r3 weak-4: per-job
    `kubectl get job` blows the 5s tick at cluster scope)."""
    kube = FakeKube(tpu_nodes(60, chips=4))
    calls = {"get_workload": 0, "lists": 0}
    real_get, real_listw = kube.get_workload, kube.list_workloads
    real_listp, real_listn = kube.list_pods, kube.list_nodes

    def count(key, fn):
        def wrapped(*a, **k):
            calls[key] += 1
            return fn(*a, **k)
        return wrapped

    kube.get_workload = count("get_workload", real_get)
    kube.list_workloads = count("lists", real_listw)
    kube.list_pods = count("lists", real_listp)
    kube.list_nodes = count("lists", real_listn)

    cluster = Cluster(kube)
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coords = {}

    def factory(job):
        return coords.setdefault(
            job.name, LocalCoordinator(target_world=1, max_world=1)
        )

    ctrl = Controller(
        cluster,
        Autoscaler(cluster, coord_client_factory=factory),
        coord_client_factory=factory,
    )
    for i in range(50):
        ctrl.on_add(make_job(f"j{i:02d}", mn=1, mx=1))

    calls["get_workload"] = calls["lists"] = 0
    ctrl.run_once()
    # per-job gets are gone; the tick's listing traffic is constant
    assert calls["get_workload"] == 0, calls
    assert calls["lists"] <= 8, calls


def test_dead_coordinator_logged_once_per_outage(capsys):
    """A RUNNING job whose coordinator is unreachable must show up in
    logs (VERDICT r3 weak-5: the silent `except: pass` made a bad
    Service invisible) — once per outage, not once per tick."""
    kube = FakeKube(tpu_nodes())

    def dead_factory(job):
        raise ConnectionError("no route to coordinator")

    cluster = Cluster(kube)
    ctrl = Controller(
        cluster,
        Autoscaler(cluster, coord_client_factory=dead_factory),
        coord_client_factory=dead_factory,
    )
    ctrl.on_add(make_job("deadco", mn=1, mx=1))
    ctrl.run_once()
    err = capsys.readouterr().err
    assert "deadco" in err and "handshake" in err
    ctrl.run_once()  # same outage: no duplicate log
    assert "deadco" not in capsys.readouterr().err


def test_watcher_fires_on_update_for_annotation_change():
    """Informer fidelity (VERDICT r3 weak-8): an annotation-only edit
    must fire on_update, like labels and spec changes do."""
    from edl_tpu.controller.watch import TrainingJobWatcher

    manifest = make_job("ann").to_manifest()
    manifests = [manifest]

    class Recorder:
        def __init__(self):
            self.events = []
            self.jobs = {}

        def on_add(self, job):
            self.events.append(("add", job.name))
            self.jobs[job.name] = job

        def on_update(self, job):
            self.events.append(("update", job.name))

        def on_delete(self, job):
            self.events.append(("delete", job.name))

        def gc_orphans(self, names):
            pass

    rec = Recorder()
    watcher = TrainingJobWatcher(lambda: manifests, rec)
    assert watcher.poll_once() == 1  # add
    assert watcher.poll_once() == 0  # steady state: no spurious updates
    manifest["metadata"]["annotations"] = {"edl.tpu.dev/note": "v2"}
    assert watcher.poll_once() == 1
    assert rec.events[-1] == ("update", "ann")


# ---- fleet-market wiring (ROADMAP item 2 residue) ---------------------------


def make_priority_job(name, pri, mn=1, mx=2):
    job = make_job(name=name, mn=mn, mx=mx)
    job.spec.priority = pri
    return job


def test_controller_auto_attaches_fleet_when_two_jobs_carry_priority():
    """>= 2 live jobs with spec.priority: the deployed controller
    constructs the chip-market arbiter itself and rides it on the
    autoscaler tick; market jobs leave the single-cluster lane while
    non-priority jobs stay in it."""
    kube = FakeKube(tpu_nodes(8))
    cluster = Cluster(kube)
    ctrl = Controller(cluster, clock=lambda: 100.0)
    ctrl.on_add(make_priority_job("hi", 10))
    ctrl.run_once()
    # One prioritized job is not a market.
    assert getattr(ctrl.autoscaler, "fleet_arbiter", None) is None

    ctrl.on_add(make_priority_job("lo", 1))
    ctrl.on_add(make_job(name="plain", mn=1, mx=2))
    ctrl.run_once()
    arbiter = ctrl.autoscaler.fleet_arbiter
    assert arbiter is not None
    assert {b.name for b in arbiter.trainers} == {"hi", "lo"}
    # Bidder bounds/priority came from the validated spec.
    hi = next(b for b in arbiter.trainers if b.name == "hi")
    assert hi.priority == 10 and (hi.min_units, hi.max_units) == (1, 2)
    # Market jobs left the single-cluster lane; the plain job stayed.
    ctrl.autoscaler._drain_events()
    assert "hi" not in ctrl.autoscaler.jobs
    assert "lo" not in ctrl.autoscaler.jobs
    assert "plain" in ctrl.autoscaler.jobs
    # The live-inventory callable parks non-fleet usage opaquely.
    inv = ctrl._fleet_inventory()
    assert inv.total_chips == 32  # 8 nodes x 4 chips


def test_controller_fleet_bidder_sync_add_and_remove():
    kube = FakeKube(tpu_nodes(8))
    cluster = Cluster(kube)
    ctrl = Controller(cluster, clock=lambda: 100.0)
    ctrl.on_add(make_priority_job("a", 5))
    ctrl.on_add(make_priority_job("b", 3))
    ctrl.run_once()
    arbiter = ctrl.autoscaler.fleet_arbiter
    assert {b.name for b in arbiter.trainers} == {"a", "b"}

    # A job gaining priority later joins the market...
    ctrl.on_add(make_priority_job("c", 7))
    ctrl.run_once()
    assert {b.name for b in arbiter.trainers} == {"a", "b", "c"}
    ctrl.autoscaler._drain_events()
    assert "c" not in ctrl.autoscaler.jobs
    # ...and a deleted job leaves it.
    ctrl.on_delete(ctrl.jobs["b"])
    ctrl.run_once()
    assert {b.name for b in arbiter.trainers} == {"a", "c"}
    assert ctrl._fleet_managed == {"a", "c"}


def test_controller_respects_explicitly_attached_arbiter():
    """An arbiter attached by hand (tests / custom markets) is reused:
    the controller only syncs ITS jobs into it, never re-attaches."""
    from edl_tpu.fleet import FleetArbiter, TrainingBidder, attach_fleet

    kube = FakeKube(tpu_nodes(8))
    cluster = Cluster(kube)
    ctrl = Controller(cluster, clock=lambda: 100.0)
    arbiter = FleetArbiter(
        8,
        trainers=[
            TrainingBidder("external", None, min_units=1, max_units=1)
        ],
    )
    attach_fleet(ctrl.autoscaler, arbiter)
    ctrl.on_add(make_priority_job("x", 2))
    ctrl.on_add(make_priority_job("y", 4))
    ctrl.run_once()  # must NOT raise "already attached"
    assert ctrl.autoscaler.fleet_arbiter is arbiter
    assert {b.name for b in arbiter.trainers} == {"external", "x", "y"}


def test_controller_market_jobs_survive_watch_updates():
    """A watch update on a market-owned job must NOT re-enroll it in
    the single-cluster lane (two planners would fight over one
    workload)."""
    kube = FakeKube(tpu_nodes(8))
    cluster = Cluster(kube)
    ctrl = Controller(cluster, clock=lambda: 100.0)
    ctrl.on_add(make_priority_job("a", 5))
    ctrl.on_add(make_priority_job("b", 3))
    ctrl.run_once()
    assert ctrl.autoscaler.fleet_arbiter is not None
    # Annotation-style update (same spec, new object) on a market job.
    ctrl.on_update(make_priority_job("a", 5))
    ctrl.autoscaler._drain_events()
    assert "a" not in ctrl.autoscaler.jobs
    ctrl.run_once()  # and the next tick keeps both planners disjoint
    ctrl.autoscaler._drain_events()
    assert "a" not in ctrl.autoscaler.jobs


def test_controller_priority_removed_job_returns_to_single_lane():
    """A live job whose spec.priority is edited away leaves the market
    AND re-enters the single-cluster lane — owned by neither planner,
    it would never scale again."""
    kube = FakeKube(tpu_nodes(8))
    cluster = Cluster(kube)
    ctrl = Controller(cluster, clock=lambda: 100.0)
    ctrl.on_add(make_priority_job("a", 5))
    ctrl.on_add(make_priority_job("b", 3))
    ctrl.run_once()
    arbiter = ctrl.autoscaler.fleet_arbiter
    assert {bd.name for bd in arbiter.trainers} == {"a", "b"}

    ctrl.on_update(make_job(name="b", mn=1, mx=2))  # priority -> 0
    ctrl.run_once()
    assert {bd.name for bd in arbiter.trainers} == {"a"}
    assert ctrl._fleet_managed == {"a"}
    ctrl.autoscaler._drain_events()
    assert "b" in ctrl.autoscaler.jobs
