"""Deterministic data-sharding tests (runtime/data.py)."""

import numpy as np

from edl_tpu.runtime.data import ShardedDataIterator


def _ds(n=128):
    return {"x": np.arange(n, dtype=np.float32)[:, None]}


def test_large_and_negative_seeds_do_not_overflow():
    """seed*1_000_003+epoch must wrap mod 2**32, not crash under
    numpy 2.x strict uint32 conversion (review finding)."""
    for seed in (4295, 2**31, -1, -12345):
        it = ShardedDataIterator(_ds(), global_batch_size=32, seed=seed)
        idx = it.global_indices(0)
        assert len(idx) == 32
        # determinism: same seed -> same indices
        it2 = ShardedDataIterator(_ds(), global_batch_size=32, seed=seed)
        np.testing.assert_array_equal(idx, it2.global_indices(0))


def test_rank_slices_partition_global_batch():
    it = ShardedDataIterator(_ds(), global_batch_size=64, seed=7)
    whole = it.global_indices(3)
    got = np.concatenate(
        [it.host_batch(3, world=4, rank=r)["x"][:, 0] for r in range(4)]
    )
    np.testing.assert_array_equal(got, _ds()["x"][whole][:, 0])


def test_resize_consistency_across_world_sizes():
    """The same step's global batch is identical at every world size."""
    it = ShardedDataIterator(_ds(), global_batch_size=32, seed=1)
    for w in (1, 2, 4, 8):
        got = np.concatenate(
            [it.host_batch(5, world=w, rank=r)["x"] for r in range(w)]
        )
        np.testing.assert_array_equal(got, _ds()["x"][it.global_indices(5)])


# ---- file-backed array stores (runtime/datasets.py) -------------------------


def test_array_store_round_trip_mmap(tmp_path):
    from edl_tpu.runtime.datasets import load_array_store, save_array_store

    arrays = {
        "x": np.random.RandomState(0).randn(64, 3).astype(np.float32),
        "y": np.arange(64, dtype=np.int32),
    }
    save_array_store(str(tmp_path / "s"), arrays)
    loaded = load_array_store(str(tmp_path / "s"))
    assert isinstance(loaded["x"], np.memmap)  # real bytes from disk
    for k in arrays:
        np.testing.assert_array_equal(np.asarray(loaded[k]), arrays[k])


def test_array_store_rejects_non_store_and_drift(tmp_path):
    import pytest

    from edl_tpu.runtime.datasets import load_array_store, save_array_store

    with pytest.raises(FileNotFoundError):
        load_array_store(str(tmp_path / "nope"))
    p = str(tmp_path / "s")
    save_array_store(p, {"x": np.zeros((8, 2), np.float32)})
    # drift: overwrite the file behind the manifest's back
    np.save(tmp_path / "s" / "x.npy", np.zeros((9, 2), np.float32))
    with pytest.raises(ValueError, match="drifted"):
        load_array_store(p)


def test_array_store_rejects_ragged_and_empty(tmp_path):
    import pytest

    from edl_tpu.runtime.datasets import save_array_store

    with pytest.raises(ValueError):
        save_array_store(str(tmp_path / "e"), {})
    with pytest.raises(ValueError, match="leading dim"):
        save_array_store(
            str(tmp_path / "r"),
            {"a": np.zeros(4), "b": np.zeros(5)},
        )


def test_mmap_iterator_matches_in_memory_batches(tmp_path):
    """The determinism core is byte-source invariant: a memmapped store
    yields the identical (seed, step, world, rank) batches the
    in-memory arrays do — so a resize replays the same stream whether
    data lives in RAM or on disk."""
    from edl_tpu.runtime.datasets import load_array_store, save_array_store

    arrays = {"x": np.random.RandomState(1).randn(256, 4).astype(np.float32)}
    save_array_store(str(tmp_path / "s"), arrays)
    mem = ShardedDataIterator(arrays, global_batch_size=32, seed=9)
    disk = ShardedDataIterator(
        load_array_store(str(tmp_path / "s")), global_batch_size=32, seed=9
    )
    for step in (0, 3, 17):
        for world, rank in ((1, 0), (2, 1), (4, 3)):
            np.testing.assert_array_equal(
                mem.host_batch(step, world, rank)["x"],
                disk.host_batch(step, world, rank)["x"],
            )


def test_validate_for_model_fails_fast_on_feature_mismatch(tmp_path):
    import pytest

    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.datasets import (
        load_array_store,
        stage_synthetic,
        validate_for_model,
    )

    fit = get_model("fit_a_line")
    stage_synthetic(str(tmp_path / "s"), fit.synth_batch, 64, seed=0)
    store = load_array_store(str(tmp_path / "s"))
    validate_for_model(store, fit)  # matching model: fine
    with pytest.raises(ValueError, match="lacks features"):
        validate_for_model(store, get_model("mnist"))


def test_validate_for_model_catches_shape_and_dtype_drift(tmp_path):
    import pytest

    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.datasets import validate_for_model

    fit = get_model("fit_a_line")
    ref = fit.synth_batch(np.random.RandomState(0), 8)
    bad_shape = {k: (v[:, :-1] if v.ndim == 2 else v) for k, v in ref.items()}
    with pytest.raises(ValueError, match="per-example shape"):
        validate_for_model(bad_shape, fit)
    bad_dtype = {k: v.astype(np.float64) for k, v in ref.items()}
    with pytest.raises(ValueError, match="dtype"):
        validate_for_model(bad_dtype, fit)


def test_restage_crash_leaves_loudly_broken_store(tmp_path):
    """Re-staging removes the old manifest before writing arrays, so a
    crash mid-restage cannot leave an old manifest validating a mix of
    old and new bytes."""
    import os

    import pytest

    from edl_tpu.runtime.datasets import (
        MANIFEST,
        load_array_store,
        save_array_store,
    )

    p = str(tmp_path / "s")
    save_array_store(p, {"x": np.zeros((8, 2), np.float32)})

    real_replace = os.replace

    def crash_before_manifest(src, dst):
        if dst.endswith(MANIFEST):
            raise RuntimeError("crash mid-restage")
        return real_replace(src, dst)

    os.replace = crash_before_manifest
    try:
        with pytest.raises(RuntimeError):
            save_array_store(p, {"x": np.ones((8, 2), np.float32)})
    finally:
        os.replace = real_replace
    with pytest.raises(FileNotFoundError):  # loud, not a silent mix
        load_array_store(p)
