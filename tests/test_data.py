"""Deterministic data-sharding tests (runtime/data.py)."""

import numpy as np

from edl_tpu.runtime.data import ShardedDataIterator


def _ds(n=128):
    return {"x": np.arange(n, dtype=np.float32)[:, None]}


def test_large_and_negative_seeds_do_not_overflow():
    """seed*1_000_003+epoch must wrap mod 2**32, not crash under
    numpy 2.x strict uint32 conversion (review finding)."""
    for seed in (4295, 2**31, -1, -12345):
        it = ShardedDataIterator(_ds(), global_batch_size=32, seed=seed)
        idx = it.global_indices(0)
        assert len(idx) == 32
        # determinism: same seed -> same indices
        it2 = ShardedDataIterator(_ds(), global_batch_size=32, seed=seed)
        np.testing.assert_array_equal(idx, it2.global_indices(0))


def test_rank_slices_partition_global_batch():
    it = ShardedDataIterator(_ds(), global_batch_size=64, seed=7)
    whole = it.global_indices(3)
    got = np.concatenate(
        [it.host_batch(3, world=4, rank=r)["x"][:, 0] for r in range(4)]
    )
    np.testing.assert_array_equal(got, _ds()["x"][whole][:, 0])


def test_resize_consistency_across_world_sizes():
    """The same step's global batch is identical at every world size."""
    it = ShardedDataIterator(_ds(), global_batch_size=32, seed=1)
    for w in (1, 2, 4, 8):
        got = np.concatenate(
            [it.host_batch(5, world=w, rank=r)["x"] for r in range(w)]
        )
        np.testing.assert_array_equal(got, _ds()["x"][it.global_indices(5)])
