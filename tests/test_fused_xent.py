"""Fused Pallas vocab cross entropy vs the chunked jnp oracle.

Runs in Pallas interpret mode on the CPU mesh — the identical kernel
code path the TPU compiles (tests/conftest.py pins the platform)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.fused_xent import fused_vocab_xent
from edl_tpu.ops.losses import tied_vocab_xent


@pytest.mark.parametrize("vocab", [300, 512])  # non-multiple + multiple of tile
def test_fused_xent_matches_oracle(vocab):
    rng = np.random.RandomState(0)
    B, T, D = 2, 24, 64
    y = jnp.asarray(rng.randn(B, T, D) * 0.5, jnp.float32)
    E = jnp.asarray(rng.randn(vocab, D) * 0.5, jnp.float32)
    labels = jnp.asarray(rng.randint(0, vocab, size=(B, T)), jnp.int32)
    valid = jnp.asarray(rng.rand(B, T) > 0.2)

    l1, a1 = fused_vocab_xent(
        y, E, labels, valid, block_rows=16, block_vocab=128
    )
    l2, a2 = tied_vocab_xent(y, E, labels, valid)
    assert abs(float(l1) - float(l2)) < 0.05
    assert abs(float(a1) - float(a2)) < 1e-5

    g1 = jax.grad(
        lambda y, E: fused_vocab_xent(
            y, E, labels, valid, block_rows=16, block_vocab=128
        )[0],
        argnums=(0, 1),
    )(y, E)
    g2 = jax.grad(
        lambda y, E: tied_vocab_xent(y, E, labels, valid)[0], argnums=(0, 1)
    )(y, E)
    for a, b in zip(g1, g2):
        rel = float(
            jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))
        ) / (float(jnp.max(jnp.abs(b))) + 1e-9)
        assert rel < 0.05


def test_fused_xent_accuracy_counts_argmax_hits():
    """Rows whose label IS the argmax must count; invalid rows must not."""
    D, V = 32, 128
    # Embedding row v has a spike at feature v % D scaled by v — craft y
    # to point exactly at a chosen row.
    rng = np.random.RandomState(1)
    E = jnp.asarray(rng.randn(V, D) * 0.1, jnp.float32)
    target = 7
    y_row = E[target] * 100.0  # dot maximized at row `target`
    y = jnp.stack([y_row, y_row])[None]  # [1, 2, D]
    labels = jnp.asarray([[target, target]], jnp.int32)
    valid = jnp.asarray([[True, False]])
    _, acc = fused_vocab_xent(
        y, E, labels, valid, block_rows=8, block_vocab=64
    )
    assert float(acc) == 1.0  # 1 valid row, predicted correctly


def test_fused_xent_ignores_padding_rows():
    """Padded (invalid) rows contribute neither loss nor gradient."""
    rng = np.random.RandomState(2)
    B, T, D, V = 1, 8, 16, 64
    y = jnp.asarray(rng.randn(B, T, D), jnp.float32)
    E = jnp.asarray(rng.randn(V, D), jnp.float32)
    labels = jnp.asarray(rng.randint(0, V, size=(B, T)), jnp.int32)
    valid_all = jnp.ones((B, T), bool)
    valid_half = jnp.asarray(np.arange(T)[None, :] < 4)

    l_half, _ = fused_vocab_xent(
        y, E, labels, valid_half, block_rows=8, block_vocab=64
    )
    l_manual, _ = fused_vocab_xent(
        y[:, :4], E, labels[:, :4], valid_all[:, :4],
        block_rows=8, block_vocab=64,
    )
    assert abs(float(l_half) - float(l_manual)) < 1e-3
