"""Shard-only host checkpoints (ISSUE 19): cluster-memory state so
host DRAM never caps model size.

The contract under test: a dp×fsdp member's ``HostDRAMStore`` holds
only its own GSPMD slice plus K ring-buddy shards — never a full
leaf, never the full state — and every downstream consumer (flush,
spill, restore, serving hot swap, tp staging) operates at shard
granularity:

- flush trims the transient full copy down to resident shards, and
  spills per-rank shard files whose UNION is the durable checkpoint;
- ``EDL_FABRIC_K`` is enforced: an under-replicated flush is counted
  (``edl_fabric_underreplicated_total``) + journaled, and a
  coverage-below-K agreement degrades loudly to the newest fully
  covered step (the killed-buddy discipline);
- a joiner restores with NO member holding full state, wire- and
  memory-accounted;
- serving swaps stage device slices straight from shard bytes
  (``stage_slice_from_shards``), bit-identical to the retired
  per-leaf ``x[idx]`` staging.
"""

import os
import threading
import zlib

import numpy as np
import pytest

import jax

from edl_tpu import telemetry
from edl_tpu.checkpoint import fabric as fab
from edl_tpu.checkpoint import transfer as tx
from edl_tpu.checkpoint.hostdram import (
    HostCheckpoint,
    HostDRAMStore,
    newest_covered_shard_step,
    scan_shard_spills,
)


def source_leaves(seed=0):
    rng = np.random.RandomState(seed)
    return [
        rng.randn(64, 32).astype(np.float32),
        rng.randn(257, 16).astype(np.float32),
        np.asarray(rng.randint(0, 100), np.int32).reshape(()),
        rng.randn(4000).astype(np.float64),
    ]


def build_layout(leaves, world, k=1, shard_bytes=1024):
    return fab.ShardLayout.build(
        [l.nbytes for l in leaves],
        world,
        k=k,
        shard_bytes=shard_bytes,
        rows=fab.leaf_rows(leaves),
    )


def shard_bytes_of(layout, leaves, s):
    return bytes(
        fab.byte_view(leaves[s.leaf])[s.offset : s.offset + s.length]
    )


def seed_resident(resident, layout, leaves, step, indices):
    """Adopt ``indices`` into a replica store from source leaves."""
    for i in indices:
        s = layout.shards[i]
        data = np.frombuffer(
            shard_bytes_of(layout, leaves, s), np.uint8
        ).copy()
        resident.put(step, s.leaf, s.offset, s.length, data, zlib.crc32(data))


def wanted_nbytes(layout, rank):
    return sum(layout.shards[s].length for s in layout.wanted(rank))


def run_world(member_fns, timeout=60):
    world = tx.LoopbackWorld(len(member_fns))
    results = [None] * len(member_fns)
    errors = [None] * len(member_fns)

    def runner(rank, fn):
        try:
            results[rank] = fn(world.fabric(rank))
        except BaseException as e:  # noqa: BLE001 - re-raised below
            errors[rank] = e

    threads = [
        threading.Thread(target=runner, args=(r, fn), daemon=True)
        for r, fn in enumerate(member_fns)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
        assert not t.is_alive(), "member thread hung"
    return results, errors


class _St:
    """Minimal flushable state (flush_sync reads ``.step`` + leaves)."""

    def __init__(self, leaves, step):
        self.step = step
        self.leaves = list(leaves)

    def tree_flatten(self):
        return self.leaves, self.step

    @classmethod
    def tree_unflatten(cls, step, leaves):
        return cls(leaves, step)


jax.tree_util.register_pytree_node(
    _St, _St.tree_flatten, _St.tree_unflatten
)


# ---- staging primitive -----------------------------------------------------


def test_stage_slice_from_shards_bit_identity():
    """``stage_slice_from_shards`` == ``x[idx]`` bit-for-bit, for row
    slices (fsdp), trailing-axis slices (tp columns), combined slices,
    and whole-leaf (byte-range) shards — the regression gate for
    retiring the per-leaf index-slice staging."""
    leaves = source_leaves(7)
    layout = build_layout(leaves, 4, shard_bytes=1024)

    def src_for(leaf_arr):
        return lambda sh: fab.byte_view(leaf_arr)[
            sh.offset : sh.offset + sh.length
        ]

    cases = [
        (0, (slice(0, 32), slice(None))),          # fsdp row half
        (0, (slice(None), slice(0, 16))),          # tp column half
        (0, (slice(16, 48), slice(16, 32))),       # both axes
        (1, (slice(0, 129), slice(None))),         # odd row split
        (1, (slice(129, 257), slice(8, 16))),
        (2, ()),                                   # 0-d leaf
        (3, (slice(1000, 3000),)),                 # 1-d row leaf
    ]
    for leaf, idx in cases:
        x = leaves[leaf]
        got = fab.stage_slice_from_shards(
            layout, leaf, x, idx, src_for(x)
        )
        want = x[idx] if idx != () else x
        assert got.tobytes() == np.ascontiguousarray(want).tobytes(), (
            leaf,
            idx,
        )


# ---- the store: flush trims, spills shard, cold-starts from shards ---------


def _shard_only_store(tmp_path, rank, world, k=1, shard_bytes=512):
    st = HostDRAMStore(spill_dir=str(tmp_path))
    st.shard_only = True
    st.bind_fabric(
        rank,
        world,
        k=k,
        shard_bytes=shard_bytes,
        resident=fab.ShardReplicaStore(keep_steps=2),
    )
    return st


def test_shard_only_flush_trims_full_copy_and_spills_shards(tmp_path):
    """After a world=4 collective flush: no member's DRAM holds the
    full state (the transient copy is trimmed to resident shards, each
    bounded by own-slice + K-buddy bytes), the durable dir holds ONLY
    per-rank shard files, and their union re-assembles bit-identically
    for a full-copy consumer."""
    leaves = source_leaves(19)
    total = sum(l.nbytes for l in leaves)
    world = 4
    stores = [
        _shard_only_store(tmp_path, r, world) for r in range(world)
    ]
    layout = stores[0]._fab_layout(leaves)
    for r, st in enumerate(stores):
        ckpt, bg = st.flush_sync(_St(leaves, 11), generation=2)
        if bg is not None:
            bg.join()
        # Full copy trimmed: the store no longer serves it ...
        assert st.latest() is None
        # ... and residency is the (1 + K)/world contract, not the
        # state.
        assert st.resident_nbytes() == wanted_nbytes(layout, r)
        assert st.resident_nbytes() < total

    names = sorted(os.listdir(tmp_path))
    assert names, "shard-only flush must spill"
    assert all(".shard-r" in n for n in names), names
    assert set(scan_shard_spills(str(tmp_path))) == {11}
    found = newest_covered_shard_step(str(tmp_path))
    assert found is not None and found[0] == 11
    assert sorted(found[1]) == list(range(world))

    # A full-copy consumer (plain store, e.g. pre-shard-only serving)
    # assembles the union bit-identically.
    template = _St(
        [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves], 0
    )
    full = HostDRAMStore(spill_dir=str(tmp_path)).load_from_disk(template)
    assert int(full.step) == 11
    for got, want in zip(full.leaves, leaves):
        np.testing.assert_array_equal(got, want)
    assert full.verify()


def test_shard_only_cold_start_seeds_only_wanted(tmp_path):
    """A shard-only member cold-starting from the durable dir seeds
    its resident store with EXACTLY its wanted ranges — never the
    union — so a whole-fleet cold start still holds (1+K)/world of the
    state per host."""
    leaves = source_leaves(23)
    world = 4
    for r in range(world):
        st = _shard_only_store(tmp_path, r, world)
        _, bg = st.flush_sync(_St(leaves, 5), generation=1)
        if bg is not None:
            bg.join()

    joiner = _shard_only_store(tmp_path, 2, world)
    layout = joiner._fab_layout(leaves)
    template = _St(
        [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves], 0
    )
    seeded = joiner.load_shards_from_disk(template)
    assert seeded is not None and seeded["step"] == 5
    assert seeded["bytes"] == wanted_nbytes(layout, 2)
    assert joiner.resident_nbytes() == wanted_nbytes(layout, 2)
    assert joiner.latest() is None  # no full state materialized
    # Wrong-model schema fails loudly, never silently restarts at 0.
    bad = _St(
        [jax.ShapeDtypeStruct((3, 3), np.float32)], 0
    )
    with pytest.raises(RuntimeError, match="leaf schema|granularity"):
        joiner.load_shards_from_disk(bad)


# ---- collective shard-resident restore -------------------------------------


def test_joiner_restore_no_member_holds_full_state():
    """A fresh joiner restores from shard-only peers: every member
    ends holding exactly own-slice + K-buddy bytes, the joiner's wire
    bytes equal its wanted ranges, and NO process ever assembles the
    full state (resident bytes < total everywhere)."""
    leaves = source_leaves(29)
    template = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]
    total = sum(l.nbytes for l in leaves)
    W = 5
    layout = build_layout(leaves, W, shard_bytes=1024)
    residents = [fab.ShardReplicaStore(keep_steps=2) for _ in range(W)]
    for r in range(W - 1):  # rank W-1 is the empty joiner
        seed_resident(residents[r], layout, leaves, 9, layout.wanted(r))

    def member(r):
        return lambda f: fab.shard_restore(
            f,
            template,
            residents[r],
            rows=fab.leaf_rows(leaves),
            k=1,
            shard_bytes=1024,
        )

    results, errors = run_world([member(r) for r in range(W)])
    assert all(e is None for e in errors), errors
    joiner = results[W - 1]
    assert joiner.stats.mode == "fabric"
    assert joiner.stats.step == 9
    want_b = wanted_nbytes(layout, W - 1)
    assert joiner.stats.bytes_received == want_b
    assert 0 < want_b < total
    for r in range(W):
        held = residents[r].nbytes()
        assert held == wanted_nbytes(layout, r)
        assert held < total, f"rank {r} holds full state"
        # bit-identity of every resident shard against the source
        for s_idx in layout.wanted(r):
            s = layout.shards[s_idx]
            got = residents[r].get(9, s.leaf, s.offset, s.length)
            assert bytes(got) == shard_bytes_of(layout, leaves, s)
    # The union of residents covers every shard (the durability story).
    covered = set()
    for r in range(W):
        covered.update(layout.wanted(r))
    assert covered == set(range(len(layout.shards)))


def test_killed_buddy_degrades_to_newest_covered_step():
    """Coverage below K degrades LOUDLY and world-consistently: a
    shard whose every holder died leaves the newest step uncoverable —
    all members raise, drop that step, and the retry converges on the
    newest fully covered one (never a silent partial restore)."""
    leaves = source_leaves(31)
    template = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]
    W = 2
    layout = build_layout(leaves, W, shard_bytes=1024)
    residents = [fab.ShardReplicaStore(keep_steps=2) for _ in range(W)]
    # Step 7: fully covered between the survivors.
    for r in range(W):
        seed_resident(residents[r], layout, leaves, 7, layout.wanted(r))
    # Step 8: the killed third member was the only holder of rank-0's
    # last owned shard — survivors hold everything BUT that one.
    newer = [l + (1 if l.dtype.kind == "f" else 0) for l in leaves]
    missing = layout.owned_by(0)[-1].index
    for r in range(W):
        seed_resident(
            residents[r],
            layout,
            newer,
            8,
            [i for i in layout.wanted(r) if i != missing],
        )

    def member(r):
        return lambda f: fab.shard_restore(
            f,
            template,
            residents[r],
            rows=fab.leaf_rows(leaves),
            k=1,
            shard_bytes=1024,
        )

    with telemetry.scoped():
        _, errors = run_world([member(r) for r in range(W)])
        # Round 1: every member degrades (no partial winners).
        assert all(
            isinstance(e, tx.TransferError) for e in errors
        ), errors
        for r in range(W):
            assert residents[r].newest_step() == 7, "step 8 not dropped"
        # Round 2 (the caller's hold-and-retry): converges at step 7.
        results, errors = run_world([member(r) for r in range(W)])
        assert all(e is None for e in errors), errors
        for r, res in enumerate(results):
            assert res.stats.step == 7
            for s_idx in layout.wanted(r):
                s = layout.shards[s_idx]
                got = residents[r].get(7, s.leaf, s.offset, s.length)
                assert bytes(got) == shard_bytes_of(layout, leaves, s)


def test_underreplicated_flush_counted_and_journaled():
    """EDL_FABRIC_K enforcement at the flush path: an owned shard that
    cannot reach its ring buddy (dead peer / lost address) is counted
    into ``edl_fabric_underreplicated_total`` and journaled as
    ``fabric.underreplicated`` — a replication-contract violation, not
    an advisory log line."""
    from edl_tpu.runtime.elastic import ElasticTrainer

    leaves = source_leaves(37)
    _, treedef = jax.tree_util.tree_flatten(list(leaves))
    ckpt = HostCheckpoint(
        step=40, generation=3, leaves=list(leaves), treedef=treedef
    )
    with telemetry.scoped():
        t = object.__new__(ElasticTrainer)
        t.fabric_replicas = 1
        t.fabric_shard_bytes = 1024
        t.transfer_chunk_bytes = 1024
        t.transfer_timeout = 2.0
        t.shard_only = False
        t.store = HostDRAMStore()
        t.recorder = telemetry.get_recorder()
        t._fabric_replication = None
        # Rank 0's buddy (rank 1) is dead (connection refused): every
        # offer to it must be accounted as under-replication.
        t._fabric_stage_b(
            ckpt, world=2, rank=0, peers={1: ("127.0.0.1", 1)}
        )
        th = t._fabric_replication
        assert th is not None
        th.join(10)
        assert not th.is_alive()
        layout = t._fabric_layout(ckpt.leaves, world=2)
        owned = len(layout.owned_by(0))
        reg = telemetry.get_registry()
        got = reg.counter("edl_fabric_underreplicated_total").value()
        assert got == owned, (got, owned)
        events = t.recorder.events()
        under = [
            e for e in events if e.kind == "fabric.underreplicated"
        ]
        assert under, [e.kind for e in events]
        assert under[-1].data["shards"] == owned
        assert under[-1].data["k"] == 1


# ---- serving: swap + tp staging from shard granularity ---------------------


def _line_model_state(g, step):
    import jax.numpy as jnp
    import optax

    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.train import TrainState

    model = get_model("fit_a_line")
    params = {
        "w": jnp.full((13,), g, jnp.float32),
        "b": jnp.asarray(g, jnp.float32),
    }
    opt = optax.adam(1e-3)
    return model, opt, TrainState(
        step=jnp.asarray(step, jnp.int32),
        params=params,
        opt_state=opt.init(params),
    )


def test_serving_swaps_from_shard_only_spills(tmp_path):
    """A serving replica pointed at a shard-only durable dir (no full
    spill anywhere) loads AND hot-swaps by staging device slices
    straight from the per-rank shard files — params bit-identical, and
    the optimizer half of the state never read."""
    from edl_tpu.serving import InferenceEngine

    model, opt, state7 = _line_model_state(1.0, 7)

    def train_flush(state):
        for rank in range(2):
            st = _shard_only_store(tmp_path, rank, 2, shard_bytes=64)
            _, bg = st.flush_sync(state, generation=1)
            if bg is not None:
                bg.join()

    train_flush(state7)
    assert all(
        ".shard-r" in n for n in os.listdir(tmp_path)
    ), "precondition: shard-only durable dir"

    eng = InferenceEngine(
        model,
        HostDRAMStore(spill_dir=str(tmp_path)),
        devices=jax.devices()[:1],
        max_batch=4,
        optimizer=opt,
    )
    eng.spill_poll_interval = 0.0
    assert eng.load()
    assert eng.weights_step == 7
    got = jax.tree_util.tree_leaves(eng._weights.params)
    want = jax.tree_util.tree_leaves(state7.params)
    for a, b in zip(got, want):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()

    # Nothing new: no swap.
    assert eng.refresh() is False
    # Training writes step 9 shard spills -> the poll stages and swaps.
    _, _, state9 = _line_model_state(2.0, 9)
    train_flush(state9)
    assert eng.refresh() is True
    assert eng.weights_step == 9
    got = jax.tree_util.tree_leaves(eng._weights.params)
    want = jax.tree_util.tree_leaves(state9.params)
    for a, b in zip(got, want):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_serving_rejects_torn_shard_spill(tmp_path):
    """A bit-rotted shard file fails its per-shard CRC at staging: the
    swap is REJECTED (counted + journaled) and the engine keeps the
    old weights."""
    from edl_tpu.serving import InferenceEngine

    model, opt, state7 = _line_model_state(1.0, 7)
    for rank in range(2):
        st = _shard_only_store(tmp_path, rank, 2, shard_bytes=64)
        _, bg = st.flush_sync(state7, generation=1)
        if bg is not None:
            bg.join()
    eng = InferenceEngine(
        model,
        HostDRAMStore(spill_dir=str(tmp_path)),
        devices=jax.devices()[:1],
        max_batch=4,
        optimizer=opt,
    )
    eng.spill_poll_interval = 0.0
    assert eng.load() and eng.weights_step == 7

    _, _, state9 = _line_model_state(2.0, 9)
    for rank in range(2):
        st = _shard_only_store(tmp_path, rank, 2, shard_bytes=64)
        _, bg = st.flush_sync(state9, generation=1)
        if bg is not None:
            bg.join()
    # Rot the step-9 spills: every manifest digest stops matching its
    # payload (equivalent to torn payload bytes, but deterministic —
    # a flipped payload byte could land in an opt_state shard the
    # params-only staging never reads).
    import json

    for n in os.listdir(tmp_path):
        if n.startswith("ckpt-000000000009") and n.endswith(".json"):
            p = os.path.join(tmp_path, n)
            man = json.load(open(p))
            man["digests"] = [int(d) ^ 1 for d in man["digests"]]
            json.dump(man, open(p, "w"))

    rej = eng.telemetry.counter("edl_serve_swap_rejected_total")
    before = rej.value()
    assert eng.refresh() is False
    assert eng.weights_step == 7  # old weights kept
    assert rej.value() >= before + 1


def test_tp_staging_bit_identical_to_index_slices():
    """The tp=2 hot swap staged via ``stage_slice_from_shards`` (row-
    aligned ShardLayout slices) places byte-identical per-device
    shards to the retired ``x[idx]`` staging — verified at the device
    buffer level for every param leaf."""
    pytest.importorskip("optax")
    from tests.test_tp_serving import _build_engine

    _, store, engine = _build_engine("transformer_lm", tp=2)
    host = store.latest_verified()
    # Reconstruct the host-side params the swap staged from.
    state = jax.tree_util.tree_unflatten(host.treedef, host.leaves)
    host_params = jax.tree_util.tree_leaves(state.params)
    placed = jax.tree_util.tree_leaves(engine._weights.params)
    assert len(host_params) == len(placed)
    checked_sliced = 0
    for hp, arr in zip(host_params, placed):
        for sh in arr.addressable_shards:
            want = np.ascontiguousarray(np.asarray(hp)[sh.index])
            got = np.asarray(sh.data)
            assert got.tobytes() == want.tobytes()
            if want.shape != hp.shape:
                checked_sliced += 1
    assert checked_sliced > 0, "tp=2 engine staged no sliced leaf"
