"""Tensor-parallel decode serving (ISSUE 18).

The serving plane shards over a ``tp`` axis of the serving mesh with
the SAME GSPMD rules training uses for the qkv/out kernels: attention
heads (and the MoE FFN hidden dims) split across tp, the KV pools'
head axis splits with them, and everything host-side — block tables,
free list, refcounts, prefix hashing, the migration wire format —
stays tp-invariant.  These tests pin the acceptance criteria:

- tp=2 decodes BIT-IDENTICALLY to tp=1 per LM family, with zero
  steady-state compiles at the backend_compile seam;
- per-device KV/weight bytes shrink with tp (the capacity claim);
- max_batch lifts to the DP EXTENT (devices / tp), not the device
  count (the satellite-1 regression);
- the prefix cache and live migration keep working on tp>=2 — shared
  (refcount > 1) prefix blocks export as host copies and land private
  on the dest, and a sequence migrates BETWEEN tp shapes because the
  exported blocks carry full heads.
"""

import json
import urllib.request

import jax
import numpy as np
import pytest

from edl_tpu import telemetry
from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.models.base import get_model
from edl_tpu.serving import (
    ContinuousBatcher,
    DecodeEngine,
    MigrationReceiver,
    ServingServer,
    TokenContinuousBatcher,
    migrate_out,
)
from tests.test_decode_serving import _lm_state, _reference_decode
from tests.test_serving_migrate import _wait


def _build_engine(name="transformer_lm", tp=1, ndev=None, step=1, seed=1,
                  **kw):
    model = get_model(name, tiny=True)
    store = HostDRAMStore()
    store.save_async(_lm_state(model, step, seed), generation=0)
    store.wait()
    engine = DecodeEngine(
        model,
        store,
        devices=jax.devices()[: (ndev if ndev is not None else tp)],
        max_batch=1,
        max_seqs=4,
        block_tokens=16,
        tp=tp,
        **kw,
    )
    assert engine.load()
    engine.warm()
    return model, store, engine


def _greedy(engine, prompt, n, count_compiles=False):
    """Prefill + n-1 decode steps on one sequence, straight through the
    engine (no batcher): returns (tokens, steady_state_compiles)."""
    import jax._src.compiler as _compiler

    w = engine.current_weights()
    tab = np.asarray(engine.pool.alloc(engine.blocks_per_seq), np.int32)
    try:
        out = [int(engine.prefill(w, prompt, tab))]
        ln = np.asarray([len(prompt)], np.int32)
        real = _compiler.backend_compile
        count = {"n": 0}

        def counting(*a, **k):
            count["n"] += 1
            return real(*a, **k)

        _compiler.backend_compile = counting
        try:
            while len(out) < n:
                ids = engine.decode_step(
                    w, np.asarray([out[-1]], np.int32), ln, tab[None]
                )
                out.append(int(ids[0]))
                ln = ln + 1
        finally:
            _compiler.backend_compile = real
    finally:
        engine.pool.free([b for b in tab.tolist() if b != 0])
    return out, count["n"]


# -- the acceptance criterion: bit-identity + 0 compiles, per family ----------


@pytest.mark.parametrize(
    "name", ["transformer_lm", "moe_lm", "longcontext_lm"]
)
def test_decode_bit_identical_across_tp_per_family(name):
    """tp=2 must produce the SAME greedy tokens as tp=1 from the same
    spilled state, and the steady decode loop must perform zero XLA
    compiles on both shapes."""
    prompt = np.arange(3, 3 + 9, dtype=np.int32)
    _, _, e1 = _build_engine(name, tp=1)
    t1, c1 = _greedy(e1, prompt, 12)
    _, _, e2 = _build_engine(name, tp=2)
    t2, c2 = _greedy(e2, prompt, 12)
    assert t1 == t2, f"{name}: tp=2 tokens diverged from tp=1"
    assert c1 == 0 and c2 == 0, (name, c1, c2)
    assert e1.pool.used_blocks == 0 and e2.pool.used_blocks == 0


def test_per_device_bytes_shrink_with_tp():
    """The capacity claim, as byte math: the KV pool's per-device bytes
    HALVE at tp=2 (the head axis shards exactly), and the weight shard
    lands between 1/2 and 0.6x the full state (tp-sharded kernels at
    1/2, layernorm/bias/position leaves replicated)."""
    _, _, e1 = _build_engine(tp=1)
    _, _, e2 = _build_engine(tp=2)
    assert e2.kv_pool_bytes_per_device() * 2 == e1.kv_pool_bytes_per_device()
    full = e2.weight_full_bytes()
    shard = e2.weight_shard_bytes_per_device()
    assert full == e1.weight_shard_bytes_per_device()
    assert 0.5 * full <= shard <= 0.6 * full, (shard, full)


def test_mesh_shape_and_heads_divisibility():
    """tp must divide the device count, and the model's head count must
    divide tp (a head never splits)."""
    model = get_model("transformer_lm", tiny=True)
    store = HostDRAMStore()
    store.save_async(_lm_state(model, 1, 1), generation=0)
    store.wait()
    with pytest.raises(ValueError, match="tp"):
        DecodeEngine(
            model, store, devices=jax.devices()[:3], max_batch=1,
            max_seqs=4, block_tokens=16, tp=2,
        )
    with pytest.raises(ValueError, match="heads"):
        # tiny transformer_lm has 4 heads; 4 % 3 != 0
        DecodeEngine(
            model, store, devices=jax.devices()[:3], max_batch=1,
            max_seqs=4, block_tokens=16, tp=3,
        )


def test_max_batch_lifts_to_dp_extent_not_device_count():
    """Satellite-1 regression: the single-shot max-batch floor is the
    DP EXTENT (devices / tp — each replica row spans tp devices), not
    ``len(devices)``.  Pre-fix, a 4-device tp=2 engine lifted
    max_batch to 4 and single-shot batches sharded 4-wide over a
    2-replica mesh."""
    model = get_model("transformer_lm", tiny=True)
    store = HostDRAMStore()
    store.save_async(_lm_state(model, 1, 1), generation=0)
    store.wait()
    engine = DecodeEngine(
        model, store, devices=jax.devices()[:4], max_batch=1,
        max_seqs=4, block_tokens=16, tp=2,
    )
    assert engine.dp == 2 and engine.tp == 2
    assert engine.max_batch == 2, "floor must be dp extent, not n_devices"
    # and at tp=1 the old behavior holds: floor == device count
    engine1 = DecodeEngine(
        model, store, devices=jax.devices()[:4], max_batch=1,
        max_seqs=4, block_tokens=16,
    )
    assert engine1.max_batch == 4


# -- prefix cache on tp>=2 ----------------------------------------------------


def test_prefix_warm_admission_bit_identical_on_tp2():
    """ISSUE 18 satellite: the prefix cache's host-side hashing and
    refcounts never see the tp split — a warm (reused-block) admission
    on a tp=2 engine decodes bit-identically to its own cold prefill
    AND to the single-device reference."""
    model, _, engine = _build_engine(tp=2, max_chunk_tokens=16)
    with telemetry.scoped():
        batcher = TokenContinuousBatcher(engine, refresh=False).start()
        try:
            rng = np.random.RandomState(1)
            prompt = model.synth_batch(rng, 1)["tokens"][0, :40]
            gen = lambda: batcher.submit_generate(
                {"tokens": list(prompt)}, max_new_tokens=4, deadline_s=60.0
            ).result(timeout=60)
            cold_t, cold_m = gen()
            warm_t, warm_m = gen()
            assert cold_m["reused_blocks"] == 0
            assert warm_m["reused_blocks"] == 2, "(40-1)//16 blocks claimed"
            assert warm_t == cold_t
            w = engine.current_weights()
            ref = _reference_decode(model, w.params, list(prompt), 4, engine)
            assert warm_t == ref, "tp=2 reused-block decode impure"
        finally:
            batcher.stop()
    assert engine.pool.used_blocks == 0


# -- live migration on tp>=2 --------------------------------------------------


def test_migration_between_tp_shapes_bit_identical():
    """The KV wire format is tp-INVARIANT (export gathers every shard
    to full-head host blocks): a sequence decoding on a tp=1 source
    migrates mid-generation to a tp=2 survivor and finishes
    bit-identically to the unmigrated reference."""
    model, _, src = _build_engine(tp=1)
    _, _, dst = _build_engine(tp=2)
    with telemetry.scoped():
        src_b = TokenContinuousBatcher(src, refresh=False).start()
        dst_b = TokenContinuousBatcher(dst, refresh=False).start()
        recv = MigrationReceiver(dst, dst_b, replica_id="dst").start()
        try:
            prompt, n = list(range(1, 9)), 24
            t = src_b.submit_generate(
                {"tokens": prompt}, max_new_tokens=n, deadline_s=60.0
            )
            _wait(lambda: len(t.tokens) >= 5, what="5 tokens pre-migration")
            src_b.close_admission()
            s = migrate_out(
                src, src_b, f"tcp://127.0.0.1:{recv.port}", replica_id="src"
            )
            assert s["migrated"] == 1 and s["failed"] == 0
            tokens, meta = t.result(timeout=30)
            ref = _reference_decode(
                model, src.current_weights().params, prompt, n, src
            )
            assert tokens == ref, "tokens diverged across the tp hop"
            assert meta.get("migrated") is True
            assert dst_b.stats["prefills"] == 0, "survivor re-prefilled"
        finally:
            src_b.stop()
            dst_b.stop()
            recv.stop()
        assert src.pool.used_blocks == 0
        assert dst.pool.used_blocks == 0


def test_migration_tp2_shared_prefix_copies_land_private():
    """Shared (refcount > 1) prefix blocks on a tp=2 source export as
    host COPIES — the source keeps them parked + claimable — and the
    granted blocks land PRIVATE on the tp=2 dest (nothing published
    into its index)."""
    model, _, src = _build_engine(tp=2)
    _, _, dst = _build_engine(tp=2)
    src.pool.drop_published()
    dst.pool.drop_published()
    with telemetry.scoped():
        src_b = TokenContinuousBatcher(src, refresh=False).start()
        dst_b = TokenContinuousBatcher(dst, refresh=False).start()
        recv = MigrationReceiver(dst, dst_b, replica_id="dst").start()
        try:
            shared = list(range(1, 33))  # 32 tokens = 2 full blocks
            pa = shared + [101, 102, 103, 104]
            pb = shared + [111, 112, 113, 114]
            pc = shared + [121, 122, 123, 124]
            src_b.submit_generate(
                {"tokens": pa}, max_new_tokens=2, deadline_s=60.0
            ).result(timeout=60)
            tb = src_b.submit_generate(
                {"tokens": pb}, max_new_tokens=10, deadline_s=60.0
            )
            tc = src_b.submit_generate(
                {"tokens": pc}, max_new_tokens=10, deadline_s=60.0
            )
            _wait(
                lambda: len(tb.tokens) >= 2 and len(tc.tokens) >= 2,
                what="both claimants decoding pre-migration",
            )
            assert tb.reused_blocks == 2 and tc.reused_blocks == 2
            sblocks = list(tb.blocks[:2])
            assert all(src.pool.refcount(b) == 2 for b in sblocks)
            src_b.close_admission()
            s = migrate_out(src, src_b, f"tcp://127.0.0.1:{recv.port}")
            assert s["migrated"] == 2 and s["failed"] == 0
            w = src.current_weights()
            toks_b, meta_b = tb.result(timeout=30)
            toks_c, meta_c = tc.result(timeout=30)
            assert toks_b == _reference_decode(model, w.params, pb, 10, src)
            assert toks_c == _reference_decode(model, w.params, pc, 10, src)
            assert meta_b["reused_blocks"] == 2
            assert meta_c["reused_blocks"] == 2
            # source keeps the shared run cached + claimable
            assert all(src.pool.refcount(b) == 0 for b in sblocks)
            assert src.pool.cached_blocks == 2
            run, skip = src_b.prefix.claim(np.asarray(pb, dtype=np.int32))
            assert list(run) == sblocks and skip == 32
            src.pool.free(list(run))
            # dest grants landed private
            assert len(dst_b.prefix) == 0
            assert dst.pool.cached_blocks == 0
        finally:
            src_b.stop()
            dst_b.stop()
            recv.stop()
        assert src.pool.used_blocks == 0
        assert dst.pool.used_blocks == 0


# -- the observability surface ------------------------------------------------


def test_healthz_reports_mesh_and_per_device_bytes():
    """/healthz carries the serving mesh shape and the per-device
    weight/KV byte footprints (satellite 4)."""
    _, _, engine = _build_engine(tp=2)
    batcher = ContinuousBatcher(engine).start()
    gen_batcher = TokenContinuousBatcher(engine, refresh=False).start()
    server = ServingServer(
        batcher, host="127.0.0.1", gen_batcher=gen_batcher
    ).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/healthz", timeout=5
        ) as h:
            health = json.loads(h.read())
        assert health["mesh"] == {"dp": 1, "tp": 2}
        assert (
            health["weight_shard_bytes_per_device"]
            == engine.weight_shard_bytes_per_device()
        )
        assert (
            health["decode"]["kv_pool_bytes_per_device"]
            == engine.kv_pool_bytes_per_device()
        )
        assert health["decode"]["kv_pool_bytes_per_device"] > 0
    finally:
        server.stop()
        gen_batcher.stop()
        batcher.stop()
