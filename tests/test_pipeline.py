"""Pipeline parallelism (parallel/pipeline.py): GPipe microbatch
schedule over the pp axis must be numerically identical to sequential
stage application — forward AND gradients — and compose with dp."""

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.parallel.mesh import MeshSpec, build_mesh
from edl_tpu.parallel.pipeline import pipeline_apply


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w1"]) @ p["w2"] + h


def _stack(rng, stages, d, f):
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (stages, d, f)) * 0.3,
        "w2": jax.random.normal(k2, (stages, f, d)) * 0.3,
    }


def _sequential(params, x):
    h = x
    for s in range(params["w1"].shape[0]):
        h = _stage_fn(jax.tree.map(lambda p: p[s], params), h)
    return h


def test_pipeline_matches_sequential_fwd_and_grad():
    mesh = build_mesh(MeshSpec.create(pp=4))
    S, d, f, B, M = 4, 8, 16, 12, 6
    params = _stack(jax.random.PRNGKey(0), S, d, f)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, d))

    out_p = pipeline_apply(_stage_fn, params, x, mesh, num_microbatches=M)
    out_s = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_s),
                               rtol=2e-5, atol=2e-5)

    def loss_p(params, x):
        return jnp.sum(
            pipeline_apply(_stage_fn, params, x, mesh, num_microbatches=M)
            ** 2
        )

    def loss_s(params, x):
        return jnp.sum(_sequential(params, x) ** 2)

    gp = jax.grad(loss_p)(params, x)
    gs = jax.grad(loss_s)(params, x)
    for k in ("w1", "w2"):
        np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(gs[k]),
                                   rtol=2e-4, atol=2e-4)


def test_pipeline_composes_with_dp():
    mesh = build_mesh(MeshSpec.create(dp=2, pp=4))
    S, d, f, B, M = 4, 8, 16, 16, 4  # mb = 4, dp-sharded 2-way
    params = _stack(jax.random.PRNGKey(2), S, d, f)
    x = jax.random.normal(jax.random.PRNGKey(3), (B, d))

    out_p = jax.jit(
        lambda p, x: pipeline_apply(_stage_fn, p, x, mesh, num_microbatches=M)
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(out_p), np.asarray(_sequential(params, x)),
        rtol=2e-5, atol=2e-5,
    )


def test_pipeline_single_stage_is_sequential():
    mesh = build_mesh(MeshSpec.create(dp=2))  # no pp axis
    params = _stack(jax.random.PRNGKey(4), 3, 8, 16)
    x = jax.random.normal(jax.random.PRNGKey(5), (8, 8))
    out = pipeline_apply(_stage_fn, params, x, mesh, num_microbatches=4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, x)), rtol=1e-6
    )


def test_pipeline_rejects_stage_mesh_mismatch():
    import pytest

    mesh = build_mesh(MeshSpec.create(pp=4))
    params = _stack(jax.random.PRNGKey(0), 8, 8, 16)  # 8 stages, pp=4
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8))
    with pytest.raises(ValueError, match="must match"):
        pipeline_apply(_stage_fn, params, x, mesh, num_microbatches=4)
    # no-pp mesh still validates microbatch divisibility
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(
            _stage_fn,
            params,
            x,
            build_mesh(MeshSpec.create(dp=2)),
            num_microbatches=5,
        )


def test_pipeline_mixed_precision_carries_stage_dtype():
    """bf16 activations with f32 stage math: the carry takes the stage
    OUTPUT dtype (like the sequential stack's inter-stage dtype)."""
    mesh = build_mesh(MeshSpec.create(pp=4))
    params = _stack(jax.random.PRNGKey(0), 4, 8, 16)  # f32 params
    x = jax.random.normal(
        jax.random.PRNGKey(1), (8, 8)
    ).astype(jnp.bfloat16)
    out = pipeline_apply(_stage_fn, params, x, mesh, num_microbatches=4)
    assert out.dtype == jnp.float32
    ref = _sequential(params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2
    )


# ---- the pipeline_lm model family ------------------------------------------


def test_pipeline_lm_matches_sequential_model():
    """pipeline_lm on a pp4 mesh computes the same loss as the same
    params applied sequentially (pp_mesh=None), and a full train step
    runs on dp2 x pp4 with the stage dim sharded over pp."""
    import optax

    from edl_tpu.models import get_model
    from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
    from edl_tpu.runtime.train import Trainer

    mesh = build_mesh(MeshSpec.create(dp=2, pp=4))
    piped = get_model("pipeline_lm", tiny=True, pp_mesh=mesh)
    seq = get_model("pipeline_lm", tiny=True, num_stages=4)  # sequential, same layout
    rng = jax.random.PRNGKey(0)
    params = seq.init_params(rng)
    batch = {
        k: jnp.asarray(v)
        for k, v in seq.synth_batch(np.random.RandomState(0), 8).items()
    }
    with mesh:
        l_piped, _ = jax.jit(piped.loss_fn)(params, batch, rng)
    l_seq, _ = seq.loss_fn(params, batch, rng)
    np.testing.assert_allclose(
        float(l_piped), float(l_seq), rtol=2e-3
    )

    tr = Trainer(piped, optax.adam(1e-3), mesh)
    state = tr.init_state()
    blk_leaf = jax.tree_util.tree_leaves(state.params["blocks"])[0]
    assert blk_leaf.shape[0] == 4  # stages
    assert blk_leaf.addressable_shards[0].data.shape[0] == 1  # pp-sharded
    data = ShardedDataIterator(
        synthetic_dataset(piped.synth_batch, 64), global_batch_size=8
    )
    state, metrics = tr.step(state, data.device_batch(0, mesh))
    assert np.isfinite(float(metrics["loss"]))


def test_1f1b_matches_gpipe_loss_and_grads(devices8):
    """VERDICT r4 #9 equality criterion: the 1F1B schedule's loss and
    gradients match GPipe-under-AD on a dp2 x pp4 mesh (same math,
    different schedule; tolerance covers bf16 cotangent hop
    reassociation)."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.models.base import get_model
    from edl_tpu.parallel.mesh import MeshSpec, build_mesh
    from edl_tpu.runtime.data import synthetic_dataset

    mesh = build_mesh(MeshSpec.create(dp=2, pp=4), devices8)
    g = get_model("pipeline_lm", tiny=True, pp_mesh=mesh, num_microbatches=4)
    f = get_model(
        "pipeline_lm", tiny=True, pp_mesh=mesh, num_microbatches=4,
        schedule="1f1b",
    )
    params = g.init_params(jax.random.key(0))
    batch = {
        k: jnp.asarray(v)
        for k, v in synthetic_dataset(g.synth_batch, 8).items()
    }
    with mesh:
        lg, _ = jax.jit(lambda p, b: g.loss_fn(p, b, None))(params, batch)
        lf, _ = jax.jit(lambda p, b: f.loss_fn(p, b, None))(params, batch)
        gg = jax.jit(jax.grad(lambda p, b: g.loss_fn(p, b, None)[0]))(
            params, batch
        )
        gf = jax.jit(jax.grad(lambda p, b: f.loss_fn(p, b, None)[0]))(
            params, batch
        )
    assert abs(float(lg) - float(lf)) < 1e-3 * max(1.0, abs(float(lg)))
    flat_f = {
        jax.tree_util.keystr(p): l
        for p, l in jax.tree_util.tree_leaves_with_path(gf)
    }
    for p, leaf_g in jax.tree_util.tree_leaves_with_path(gg):
        leaf_f = flat_f[jax.tree_util.keystr(p)]
        a = jnp.asarray(leaf_g, jnp.float32)
        b = jnp.asarray(leaf_f, jnp.float32)
        err = float(jnp.max(jnp.abs(a - b)))
        scale = float(jnp.max(jnp.abs(a))) + 1e-8
        assert err / scale < 3e-2, (
            f"{jax.tree_util.keystr(p)}: rel err {err / scale}"
        )


def test_1f1b_peak_memory_below_gpipe(devices8):
    """VERDICT r4 #9 memory criterion: at M >> S the un-differentiated
    1F1B schedule's compiled temp memory is a small fraction of
    GPipe-under-AD's (O(S) ring buffer vs O(M) saved scan ticks).
    Measured at M=16, S=4: ~1.8MB vs ~19.7MB."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.models.base import get_model
    from edl_tpu.parallel.mesh import MeshSpec, build_mesh
    from edl_tpu.runtime.data import synthetic_dataset

    mesh = build_mesh(MeshSpec.create(dp=2, pp=4), devices8)
    temps = {}
    for sched in ("gpipe", "1f1b"):
        m = get_model(
            "pipeline_lm", tiny=True, pp_mesh=mesh, num_microbatches=16,
            schedule=sched,
        )
        params = m.init_params(jax.random.key(0))
        batch = {
            k: jnp.asarray(v)
            for k, v in synthetic_dataset(m.synth_batch, 32).items()
        }
        with mesh:
            compiled = (
                jax.jit(jax.grad(lambda p, b: m.loss_fn(p, b, None)[0]))
                .lower(params, batch)
                .compile()
            )
        temps[sched] = compiled.memory_analysis().temp_size_in_bytes
    assert temps["1f1b"] < temps["gpipe"] / 3, temps


def test_1f1b_trains(devices8):
    """Optimizer steps through the 1F1B schedule descend."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from edl_tpu.models.base import get_model
    from edl_tpu.parallel.mesh import MeshSpec, build_mesh
    from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
    from edl_tpu.runtime.train import Trainer

    mesh = build_mesh(MeshSpec.create(dp=2, pp=4), devices8)
    m = get_model(
        "pipeline_lm", tiny=True, pp_mesh=mesh, num_microbatches=4,
        schedule="1f1b",
    )
    tr = Trainer(m, optax.adam(1e-2), mesh)
    state = tr.init_state()
    data = ShardedDataIterator(
        synthetic_dataset(m.synth_batch, 32), global_batch_size=8
    )
    losses = []
    for s in range(6):
        state, metrics = tr.step(state, data.device_batch(s, mesh))
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses
