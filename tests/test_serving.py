"""Elastic inference serving (edl_tpu.serving): engine, continuous
batcher, HTTP front, hot-swap-under-chaos, and the autoscaler's
serving lane.

Key guarantees under test (ISSUE 10 acceptance):
- steady-state request path performs ZERO XLA compiles (asserted at
  the backend_compile seam, same as warm resizes);
- a checkpoint hot-swap completes with zero failed/dropped requests
  and no request ever observes mixed-generation (torn) weights;
- a torn/corrupted candidate checkpoint is REJECTED by
  ``latest_verified`` and the engine keeps serving the old weights;
- a joining replica warms its bucketed forwards BEFORE taking traffic.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu import telemetry
from edl_tpu.chaos.schedule import FaultEvent, FaultSchedule
from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.models.base import get_model
from edl_tpu.runtime.train import TrainState
from edl_tpu.serving import (
    ContinuousBatcher,
    DeadlineExceededError,
    InferenceEngine,
    QueueFullError,
    ServingReplica,
    ServingServer,
)


def _line_state(g: float) -> TrainState:
    """fit_a_line TrainState whose params are a pure function of the
    'generation' scalar ``g``: pred(x) = g * sum(x) + g.  Makes every
    output row attributable to exactly one weight generation — the
    torn-weights detector the soak asserts with."""
    params = {
        "w": jnp.full((13,), g, jnp.float32),
        "b": jnp.asarray(g, jnp.float32),
    }
    opt = optax.adam(1e-3)
    return TrainState(
        step=jnp.asarray(int(g), jnp.int32),
        params=params,
        opt_state=opt.init(params),
    )


def _line_engine(store=None, max_batch=4, **kw) -> InferenceEngine:
    return InferenceEngine(
        get_model("fit_a_line"),
        store,
        devices=jax.devices()[:1],
        max_batch=max_batch,
        **kw,
    )


@pytest.fixture(scope="module")
def mnist_serving():
    """One warmed mnist engine + the TrainState it serves (shared: the
    bucket compiles are the expensive part)."""
    model = get_model("mnist")
    params = model.init_params(jax.random.key(0))
    opt = optax.adam(1e-3)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=opt.init(params),
    )
    store = HostDRAMStore()
    store.save_async(state, generation=0)
    store.wait()
    engine = InferenceEngine(
        model, store, devices=jax.devices()[:1], max_batch=8
    )
    assert engine.load()
    engine.warm()
    return engine, state


# -- forward-only apply path (ModelDef.predict_fn) --------------------------


def test_every_registered_model_declares_predict():
    from edl_tpu.models.base import registered_models

    for name in registered_models():
        m = (
            get_model(name, tiny=True)
            if name not in ("fit_a_line", "mnist")
            else get_model(name)
        )
        assert m.predict_fn is not None, name
        assert m.predict_inputs, name
        assert set(m.predict_inputs) <= set(
            m.synth_batch(np.random.RandomState(0), 1)
        ), name


def test_predict_matches_loss_path_logits_mnist(mnist_serving):
    engine, state = mnist_serving
    batch = get_model("mnist").synth_batch(np.random.RandomState(3), 5)
    arrays, n = engine.coerce_inputs({"image": batch["image"]})
    out, meta = engine.predict(arrays)
    assert n == 5 and out["logits"].shape == (5, 10)
    direct = engine.model.predict_fn(
        jax.device_get(state.params), {"image": batch["image"]}
    )
    np.testing.assert_allclose(
        out["logits"], np.asarray(direct["logits"]), atol=1e-4
    )
    np.testing.assert_array_equal(out["label"], np.asarray(direct["label"]))


def test_pipeline_lm_serves_through_gpipe_forward_grad_free():
    """The 1F1B schedule is train-only (ADVICE r5): its ModelDef's
    predict path MUST route through the GPipe forward — grad-free, no
    backward sub-ticks — even on a 1f1b-schedule instance."""
    model = get_model("pipeline_lm", tiny=True, schedule="1f1b")
    params = model.init_params(jax.random.key(0))
    batch = model.synth_batch(np.random.RandomState(0), 2)
    out = model.predict_fn(params, {"tokens": batch["tokens"]})
    assert out["tokens"].shape == (2, 64)  # tiny L = 64
    # And identical params under the gpipe schedule predict identically
    # (same forward — the schedule flag only affects training).
    gp = get_model("pipeline_lm", tiny=True, schedule="gpipe")
    out2 = gp.predict_fn(params, {"tokens": batch["tokens"]})
    np.testing.assert_array_equal(
        np.asarray(out["tokens"]), np.asarray(out2["tokens"])
    )


def test_transformer_lm_predict_accepts_corpus_shaped_rows():
    model = get_model("transformer_lm", tiny=True)
    params = model.init_params(jax.random.key(0))
    batch = model.synth_batch(np.random.RandomState(0), 2)  # L+1 rows
    out = model.predict_fn(params, {"tokens": batch["tokens"]})
    assert out["tokens"].shape == (2, 64)


def test_engine_pads_short_token_rows_to_the_schema():
    """The serving schema is probed from the training corpus (L+1
    rows: context + shifted label); a NATURAL L-token next-token
    request must serve without the client faking a dummy position —
    the engine right-pads integer token rows with the LM pad id 0."""
    model = get_model("transformer_lm", tiny=True)
    store = HostDRAMStore()
    params = model.init_params(jax.random.key(0))
    opt = optax.adam(1e-3)
    store.save_async(
        TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt.init(params),
        )
    )
    store.wait()
    engine = InferenceEngine(
        model, store, devices=jax.devices()[:1], max_batch=1
    )
    assert engine.load()
    engine.warm()
    corpus = model.synth_batch(np.random.RandomState(0), 1)["tokens"]
    a65, _ = engine.coerce_inputs({"tokens": corpus})  # L+1 = schema
    a64, _ = engine.coerce_inputs({"tokens": corpus[:, :64]})  # natural
    assert a64["tokens"].shape == a65["tokens"].shape == (1, 65)
    out65, _ = engine.predict(a65)
    out64, _ = engine.predict(a64)
    # predict slices to the first L positions: identical real tokens
    np.testing.assert_array_equal(out64["tokens"], out65["tokens"])
    # rows LONGER than the schema are still a schema error
    with pytest.raises(ValueError, match="shape"):
        engine.coerce_inputs(
            {"tokens": np.zeros((1, 80), np.int32)}
        )


# -- engine: buckets, padding, zero compiles --------------------------------


def test_bucket_ladder_honors_exact_max_batch():
    """A spec-validated max_batch must survive as the top bucket even
    when it is not a power of two (96 -> (1,2,...,64,96), not a silent
    shrink to 64)."""
    model = get_model("fit_a_line")
    e = InferenceEngine(
        model, HostDRAMStore(), devices=jax.devices()[:1], max_batch=96
    )
    assert e.buckets == (1, 2, 4, 8, 16, 32, 64, 96)
    assert e.max_batch == 96 and e.bucket_for(80) == 96


def test_bucket_ladder_and_padding(mnist_serving):
    engine, _ = mnist_serving
    assert engine.buckets == (1, 2, 4, 8)
    assert engine.bucket_for(1) == 1
    assert engine.bucket_for(3) == 4
    with pytest.raises(ValueError):
        engine.bucket_for(9)
    batch = get_model("mnist").synth_batch(np.random.RandomState(1), 3)
    arrays, _ = engine.coerce_inputs({"image": batch["image"]})
    out, meta = engine.predict(arrays)
    assert meta["bucket"] == 4 and meta["rows"] == 3
    assert out["logits"].shape[0] == 3  # padding sliced off


def test_input_schema_rejects_bad_requests(mnist_serving):
    engine, _ = mnist_serving
    with pytest.raises(ValueError, match="missing input"):
        engine.coerce_inputs({})
    with pytest.raises(ValueError, match="shape"):
        engine.coerce_inputs({"image": np.zeros((2, 7, 7, 1), np.float32)})


def test_steady_state_request_path_zero_xla_compiles(mnist_serving):
    engine, _ = mnist_serving
    import jax._src.compiler as _compiler

    rng = np.random.RandomState(7)
    model = get_model("mnist")
    real = _compiler.backend_compile
    count = [0]

    def counting(*a, **k):
        count[0] += 1
        return real(*a, **k)

    _compiler.backend_compile = counting
    try:
        for n in (1, 2, 3, 8, 5, 1):
            arrays, _ = engine.coerce_inputs(
                {"image": model.synth_batch(rng, n)["image"]}
            )
            engine.predict(arrays)
    finally:
        _compiler.backend_compile = real
    assert count[0] == 0, f"{count[0]} XLA compiles on the request path"


# -- hot swap ---------------------------------------------------------------


def test_hot_swap_installs_newer_verified_checkpoint():
    store = HostDRAMStore()
    store.save_async(_line_state(1.0), generation=0)
    store.wait()
    engine = _line_engine(store)
    assert engine.load() and engine.weights_step == 1
    engine.warm()
    x = np.ones((2, 13), np.float32)
    out, meta = engine.predict({"x": x})
    np.testing.assert_allclose(out["pred"], np.full((2,), 14.0), atol=1e-5)
    assert not engine.refresh()  # nothing newer: no-op, no hash pass
    store.save_async(_line_state(3.0), generation=1)
    store.wait()
    assert engine.refresh()
    out, meta = engine.predict({"x": x})
    np.testing.assert_allclose(out["pred"], np.full((2,), 42.0), atol=1e-4)
    assert meta["weights_step"] == 3 and meta["weights_generation"] == 2


def test_torn_candidate_rejected_engine_keeps_serving():
    """chaos[serve.swap.torn]: the newest candidate's bytes rot before
    verification — latest_verified must reject it, the engine must keep
    answering from the old weights, and the rejection must count."""
    with telemetry.scoped() as (reg, rec):
        chaos = FaultSchedule(
            seed=7, events=[FaultEvent(step=0, point="serve.swap.torn")]
        )
        chaos.advance(0)
        store = HostDRAMStore(chaos=chaos)
        store.save_async(_line_state(1.0), generation=0)
        store.wait()
        engine = _line_engine(store)
        assert engine.load()
        engine.warm()
        store.save_async(_line_state(5.0), generation=1)
        store.wait()
        assert not engine.refresh()  # torn candidate rejected
        assert engine.weights_step == 1
        out, meta = engine.predict({"x": np.ones((1, 13), np.float32)})
        np.testing.assert_allclose(out["pred"], [14.0], atol=1e-5)
        assert reg.counter("edl_serve_swap_rejected_total").value() == 1
        kinds = [e.kind for e in rec.events()]
        assert "serve.swap.rejected" in kinds
        assert not chaos.pending()
    # A LATER clean checkpoint still swaps in (corruption cost one
    # candidate, not the swap machinery).
    store.save_async(_line_state(7.0), generation=2)
    store.wait()
    assert engine.refresh() and engine.weights_step == 7


def test_durable_dir_cold_start_and_disk_hot_swap(tmp_path):
    """A serving process in ANOTHER process than training sees new
    checkpoints only through the durable dir: cold start loads the
    newest spill, refresh() polls the dir and swaps newer steps in."""
    spill = str(tmp_path / "ckpts")
    train_store = HostDRAMStore(spill_dir=spill)
    train_store.save_async(_line_state(2.0), generation=0)
    train_store.wait()
    serve_store = HostDRAMStore(spill_dir=spill)  # fresh DRAM
    engine = _line_engine(serve_store)
    assert engine.load() and engine.weights_step == 2
    engine.warm()
    train_store.save_async(_line_state(4.0), generation=1)
    train_store.wait()
    assert engine.refresh() and engine.weights_step == 4
    out, _ = engine.predict({"x": np.ones((1, 13), np.float32)})
    np.testing.assert_allclose(out["pred"], [4.0 * 14.0], atol=1e-4)


def test_hot_swap_soak_no_request_observes_torn_weights():
    """Seeded soak: requests stream through the batcher while the
    checkpoint hot-swaps underneath.  EVERY response must match the
    pure function of the generation it REPORTS — a torn (mixed-
    generation) weight set would blend two generations and match
    neither — and zero requests may fail or drop."""
    store = HostDRAMStore()
    store.save_async(_line_state(1.0), generation=0)
    store.wait()
    engine = _line_engine(store, max_batch=4)
    assert engine.load()
    engine.warm()
    batcher = ContinuousBatcher(engine, queue_limit=512).start()
    rng = np.random.RandomState(0)
    results = []
    errors = []

    def client(i):
        x = rng.randn(1 + (i % 3), 13).astype(np.float32)
        try:
            out, meta = batcher.submit({"x": x}, deadline_s=30.0).result(
                timeout=30.0
            )
        except BaseException as e:  # any drop/fail breaks the soak
            errors.append(e)
            return
        results.append((x, out["pred"], meta["weights_step"]))

    try:
        stop = threading.Event()

        def swapper():
            g = 1
            while not stop.is_set():
                g += 2
                store.save_async(_line_state(float(g)), generation=g)
                store.wait()
                time.sleep(0.005)

        sw = threading.Thread(target=swapper, daemon=True)
        sw.start()
        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(60)
        ]
        for t in threads:
            t.start()
            time.sleep(0.002)
        for t in threads:
            t.join(timeout=60)
        stop.set()
        sw.join(timeout=10)
    finally:
        batcher.stop()
    assert not errors, f"requests failed during hot swaps: {errors[:3]}"
    assert len(results) == 60
    swaps_seen = {g for _, _, g in results}
    for x, pred, g in results:
        expect = g * (x.sum(axis=1) + 1.0)
        np.testing.assert_allclose(pred, expect, rtol=1e-4, atol=1e-3)
    # the soak must actually have crossed generations to prove anything
    assert len(swaps_seen) >= 2, swaps_seen


# -- continuous batcher -----------------------------------------------------


def test_batcher_coalesces_queued_requests_into_one_bucket():
    store = HostDRAMStore()
    store.save_async(_line_state(1.0), generation=0)
    store.wait()
    with telemetry.scoped() as (reg, _):
        engine = _line_engine(store, max_batch=4)
        engine.load()
        engine.warm()
        batcher = ContinuousBatcher(engine)
        # Queue BEFORE starting the worker: all three must ride one
        # micro-batch (continuous batching's coalescing moment).
        tickets = [
            batcher.submit({"x": np.ones((1, 13), np.float32)})
            for _ in range(3)
        ]
        batcher.start()
        metas = [t.result(timeout=10)[1] for t in tickets]
        batcher.stop()
        assert {m["bucket"] for m in metas} == {4}
        assert reg.counter("edl_serve_batches_total").value() == 1
        assert reg.counter("edl_serve_examples_total").value() == 3
        assert (
            reg.counter("edl_serve_requests_total").value(status="ok") == 3
        )
        occ = reg.histogram("edl_serve_batch_occupancy").series()
        assert occ["count"] == 1 and abs(occ["sum"] - 0.75) < 1e-9


def test_batcher_backpressure_queue_full_and_chaos():
    store = HostDRAMStore()
    store.save_async(_line_state(1.0), generation=0)
    store.wait()
    with telemetry.scoped() as (reg, _):
        engine = _line_engine(store)
        engine.load()
        chaos = FaultSchedule(
            seed=1, events=[FaultEvent(step=0, point="serve.queue.full")]
        )
        chaos.advance(0)
        batcher = ContinuousBatcher(engine, queue_limit=2, chaos=chaos)
        x = {"x": np.ones((1, 13), np.float32)}
        # chaos[serve.queue.full]: forced rejection regardless of depth
        with pytest.raises(QueueFullError) as ei:
            batcher.submit(x)
        assert ei.value.retry_after > 0
        # real depth-based rejection (worker not started: queue fills)
        batcher.submit(x)
        batcher.submit(x)
        with pytest.raises(QueueFullError):
            batcher.submit(x)
        assert (
            reg.counter("edl_serve_requests_total").value(status="rejected")
            == 2
        )
        assert reg.gauge("edl_serve_queue_depth").value() == 2


def test_batcher_expires_requests_past_deadline():
    store = HostDRAMStore()
    store.save_async(_line_state(1.0), generation=0)
    store.wait()
    with telemetry.scoped() as (reg, _):
        engine = _line_engine(store)
        engine.load()
        engine.warm()
        batcher = ContinuousBatcher(engine)
        t = batcher.submit(
            {"x": np.ones((1, 13), np.float32)}, deadline_s=0.01
        )
        time.sleep(0.05)
        batcher.start()
        with pytest.raises(DeadlineExceededError):
            t.result(timeout=10)
        batcher.stop()
        assert (
            reg.counter("edl_serve_requests_total").value(status="expired")
            == 1
        )


def test_chaos_slow_request_lands_in_latency_histogram():
    store = HostDRAMStore()
    store.save_async(_line_state(1.0), generation=0)
    store.wait()
    with telemetry.scoped() as (reg, _):
        engine = _line_engine(store)
        engine.load()
        engine.warm()
        chaos = FaultSchedule(
            seed=2,
            events=[
                FaultEvent(step=0, point="serve.request.slow", arg=0.3)
            ],
        )
        chaos.advance(0)
        batcher = ContinuousBatcher(engine, chaos=chaos).start()
        out, _ = batcher.submit(
            {"x": np.ones((1, 13), np.float32)}
        ).result(timeout=10)
        batcher.stop()
        h = reg.histogram("edl_serve_latency_seconds").series()
        assert h["count"] == 1 and h["sum"] >= 0.3
        assert not chaos.pending()


# -- HTTP front -------------------------------------------------------------


def _post(url, payload, timeout=15):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def test_http_predict_healthz_metrics_e2e(mnist_serving):
    engine, _ = mnist_serving
    batcher = ContinuousBatcher(engine).start()
    server = ServingServer(batcher, host="127.0.0.1").start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        img = get_model("mnist").synth_batch(np.random.RandomState(0), 2)[
            "image"
        ]
        r = _post(f"{base}/predict", {"inputs": {"image": img.tolist()}})
        assert len(r["outputs"]["label"]) == 2
        assert r["weights_step"] == engine.weights_step
        assert r["latency_ms"] > 0
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as h:
            health = json.loads(h.read())
        assert health["ok"] and health["warm_buckets"] == [1, 2, 4, 8]
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as m:
            prom = m.read().decode()
        assert "edl_serve_latency_seconds" in prom
        assert "edl_serve_requests_total" in prom
        # bad request: schema mismatch is a 400, not a 500
        try:
            _post(f"{base}/predict", {"inputs": {"bogus": [1]}})
            raise AssertionError("expected HTTP 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        server.stop()
        batcher.stop()


def test_http_backpressure_replies_429_with_retry_after(mnist_serving):
    engine, _ = mnist_serving
    chaos = FaultSchedule(
        seed=3, events=[FaultEvent(step=0, point="serve.queue.full")]
    )
    chaos.advance(0)
    batcher = ContinuousBatcher(engine, chaos=chaos).start()
    server = ServingServer(batcher, host="127.0.0.1").start()
    try:
        img = get_model("mnist").synth_batch(np.random.RandomState(0), 1)[
            "image"
        ]
        try:
            _post(
                f"http://127.0.0.1:{server.port}/predict",
                {"inputs": {"image": img.tolist()}},
            )
            raise AssertionError("expected HTTP 429")
        except urllib.error.HTTPError as e:
            assert e.code == 429
            assert float(e.headers["Retry-After"]) > 0
    finally:
        server.stop()
        batcher.stop()


# -- serving world / control plane -----------------------------------------


def test_replica_warms_before_registering_and_reports_telemetry():
    """The scale-up contract: by the time a replica is registered (and
    routable), every bucketed forward is a held executable; its
    telemetry then flows to the serving coordinator's merged view."""
    from edl_tpu.runtime.coordinator import LocalCoordinator

    with telemetry.scoped() as (reg, _):
        store = HostDRAMStore()
        store.save_async(_line_state(1.0), generation=0)
        store.wait()
        engine = _line_engine(store, max_batch=4)
        coord = LocalCoordinator(target_world=1, max_world=4)

        events = []
        orig_register = coord.register

        def register(tid, **kw):
            # registration must find the engine already warm
            events.append(("register", tuple(engine.warm_buckets)))
            return orig_register(tid, **kw)

        coord.register = register
        replica = ServingReplica(
            engine,
            coordinator=coord,
            replica_id="serve-0",
            heartbeat_interval=60.0,
            telemetry_interval=60.0,
        )
        replica.start()
        try:
            assert events == [("register", (1, 2, 4))]
            assert coord.members() == ["serve-0"]
            # drive one request so serving series exist, then report
            out, _ = replica.batcher.submit(
                {"x": np.ones((1, 13), np.float32)}
            ).result(timeout=10)
            replica.tick()
            tel = coord.telemetry()
            merged = tel["merged"]
            assert "edl_serve_latency_seconds" in merged["histograms"]
            assert "edl_serve_requests_total" in merged["counters"]
            assert "edl_serve_weights_step" in merged["gauges"]
        finally:
            replica.stop()
        assert coord.members() == []  # deregistered on stop


class _FakeServeCoord:
    """Minimal serving-coordinator double for lane unit tests."""

    def __init__(self, p95_ms=None, depth=0, target=1, rejected=0):
        self.calls = []
        self.target = target
        self._depth = depth
        self._rejected = rejected
        self._lat = None
        if p95_ms is not None:
            reg = telemetry.MetricsRegistry()
            h = reg.histogram("edl_serve_latency_seconds")
            for _ in range(20):
                h.observe(p95_ms / 1000.0)
            self._lat = reg.snapshot()["histograms"][
                "edl_serve_latency_seconds"
            ]

    def telemetry(self):
        merged = {
            "counters": {
                "edl_serve_requests_total": {
                    "status=rejected": self._rejected
                }
            },
            "gauges": {"edl_serve_queue_depth": {"": self._depth}},
            "histograms": (
                {"edl_serve_latency_seconds": self._lat}
                if self._lat
                else {}
            ),
        }
        return {"merged": merged}

    def metrics(self):
        return {"target_world": self.target, "world_size": self.target}

    def set_prewarm(self, n, trace_id=""):
        self.calls.append(("prewarm", n, trace_id))

    def set_target_world(self, n, trace_id=""):
        self.calls.append(("target", n, trace_id))
        self.target = n


def test_serving_lane_scales_up_on_p95_with_prewarm_first():
    from edl_tpu.autoscaler.serving import ServingLane

    with telemetry.scoped() as (_, rec):
        coord = _FakeServeCoord(p95_ms=900, target=1)
        lane = ServingLane(
            coord, min_replicas=1, max_replicas=3, p95_high_s=0.5
        )
        entry = lane.run_once()
        assert entry["actuated"] and entry["dry_run"]["proposed"] == 2
        assert entry["trace_id"]
        assert entry["observed"]["p95_latency_s"] > 0.5
        # prewarm announced BEFORE the retarget, both under ONE trace
        assert [c[0] for c in coord.calls] == ["prewarm", "target"]
        assert coord.calls[0][2] == coord.calls[1][2] == entry["trace_id"]
        ev = [e for e in rec.events() if e.kind == "autoscaler.decision"]
        assert len(ev) == 1 and ev[0].trace == entry["trace_id"]
        assert ev[0].data["lane"] == "serving"


def test_serving_lane_scales_up_on_queue_depth_and_rejections():
    from edl_tpu.autoscaler.serving import ServingLane

    with telemetry.scoped():
        coord = _FakeServeCoord(depth=50, target=2)
        lane = ServingLane(coord, min_replicas=1, max_replicas=4)
        entry = lane.run_once()
        assert entry["dry_run"]["proposed"] == 3 and entry["actuated"]

        # Rejections are read as the per-tick DELTA of the cumulative
        # counter: the baseline tick observes none, a fresh burst
        # between ticks scales up.
        coord2 = _FakeServeCoord(rejected=5, target=2)
        lane2 = ServingLane(
            coord2, min_replicas=1, max_replicas=4, hold_ticks=5
        )
        e = lane2.run_once()
        assert e["observed"]["rejected_total"] is None  # baseline only
        assert e["dry_run"]["proposed"] == 2 and not e["actuated"]
        coord2._rejected = 9  # 4 NEW rejections since the last tick
        e2 = lane2.run_once()
        assert e2["observed"]["rejected_total"] == 4
        assert e2["dry_run"]["proposed"] == 3 and e2["actuated"]


def test_serving_lane_stale_rejections_do_not_pin_the_fleet():
    """The rejected counter is cumulative: a restarted lane reading a
    fleet's lifetime total (a burst hours ago) must neither actuate a
    spurious scale-up on its first tick nor block scale-down forever."""
    from edl_tpu.autoscaler.serving import ServingLane

    with telemetry.scoped():
        coord = _FakeServeCoord(rejected=5, target=2)
        lane = ServingLane(
            coord, min_replicas=1, max_replicas=4, hold_ticks=2
        )
        e1 = lane.run_once()  # first tick: baseline, not overload
        assert e1["observed"]["rejected_total"] is None
        assert e1["dry_run"]["proposed"] == 2 and not e1["actuated"]
        # cumulative count unchanged since: still no NEW rejections ->
        # the idle hysteresis runs out and the fleet sheds
        e2 = lane.run_once()
        assert e2["observed"]["rejected_total"] is None
        assert e2["dry_run"]["proposed"] == 1 and e2["actuated"]


def test_serving_lane_scales_down_with_hysteresis_and_floor():
    from edl_tpu.autoscaler.serving import ServingLane

    with telemetry.scoped():
        coord = _FakeServeCoord(depth=0, target=2)
        lane = ServingLane(
            coord, min_replicas=1, max_replicas=4, hold_ticks=2
        )
        e1 = lane.run_once()
        assert not e1["actuated"]  # first idle tick: hysteresis hold
        e2 = lane.run_once()
        assert e2["actuated"] and e2["dry_run"]["proposed"] == 1
        # at the floor: idle forever, never below min_replicas
        e3 = lane.run_once()
        e4 = lane.run_once()
        assert e3["dry_run"]["proposed"] == 1
        assert e4["dry_run"]["proposed"] == 1 and not e4["actuated"]


def test_serving_lane_recent_window_p95_forgets_old_backlog():
    """p95 is computed over the sliding-window DELTA of the cumulative
    histogram: a cold-start backlog of slow requests must stop pinning
    p95 (and the fleet size) once recent traffic is fast."""
    from edl_tpu.autoscaler.serving import ServingLane

    with telemetry.scoped():
        reg = telemetry.MetricsRegistry()
        h = reg.histogram("edl_serve_latency_seconds")
        for _ in range(50):
            h.observe(2.0)  # the bad old days

        coord = _FakeServeCoord(target=2)

        def tel():
            return {
                "merged": {
                    "counters": {},
                    "gauges": {"edl_serve_queue_depth": {"": 0}},
                    "histograms": {
                        "edl_serve_latency_seconds": reg.snapshot()[
                            "histograms"
                        ]["edl_serve_latency_seconds"]
                    },
                }
            }

        coord.telemetry = tel
        lane = ServingLane(
            coord,
            min_replicas=1,
            max_replicas=4,
            p95_high_s=0.5,
            p95_low_s=0.3,
            hold_ticks=1,
        )
        e1 = lane.run_once()
        assert e1["dry_run"]["proposed"] == 3  # backlog: scale up
        # recent traffic is fast: the window delta must show ~5ms p95
        for _ in range(8):
            for _ in range(50):
                h.observe(0.005)
            e = lane.run_once()
        assert e["observed"]["p95_latency_s"] < 0.3
        assert e["dry_run"]["proposed"] < coord.target + 1


def test_serving_lane_e2e_over_local_coordinator_telemetry():
    """Closure: replica ships real serving telemetry to a REAL
    coordinator; the lane reads the merged view and scales."""
    from edl_tpu.autoscaler.serving import ServingLane
    from edl_tpu.runtime.coordinator import LocalCoordinator

    with telemetry.scoped() as (reg, _):
        store = HostDRAMStore()
        store.save_async(_line_state(1.0), generation=0)
        store.wait()
        engine = _line_engine(store, max_batch=4)
        coord = LocalCoordinator(target_world=1, max_world=3)
        replica = ServingReplica(
            engine,
            coordinator=coord,
            replica_id="serve-0",
            heartbeat_interval=60.0,
            telemetry_interval=60.0,
        )
        replica.start()
        try:
            # a burst of slow observations (the replica's own registry)
            h = reg.histogram("edl_serve_latency_seconds")
            for _ in range(30):
                h.observe(1.5)
            replica.tick()
            lane = ServingLane(
                coord, min_replicas=1, max_replicas=3, p95_high_s=0.5
            )
            entry = lane.run_once()
            assert entry["actuated"]
            assert entry["dry_run"]["proposed"] == 2
            assert coord.target_world() == 2
            # the hint rode the same decision: a joining replica warms
            # the announced fleet BEFORE the plan routes to it
            assert coord.plan().prewarm == 2
            assert coord.plan().prewarm_trace == entry["trace_id"]
        finally:
            replica.stop()


def test_attach_serving_lane_rides_training_autoscaler_tick():
    from edl_tpu.autoscaler.serving import ServingLane, attach_serving_lane

    with telemetry.scoped():
        class _Scaler:
            decision_log = []
            decision_log_max = 256

            def run_once(self):
                return "plan"

        scaler = _Scaler()
        coord = _FakeServeCoord(depth=50, target=1)
        lane = attach_serving_lane(
            scaler, ServingLane(coord, min_replicas=1, max_replicas=2)
        )
        assert scaler.run_once() == "plan"
        assert lane.decision_log and scaler.decision_log
        assert scaler.decision_log[-1]["lane"] == "serving"


# -- histogram quantiles ----------------------------------------------------


def test_histogram_quantile_interpolation_and_edges():
    from edl_tpu.telemetry.aggregate import histogram_quantile

    assert histogram_quantile(None, 0.95) is None
    assert histogram_quantile({}, 0.5) is None
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("edl_serve_latency_seconds")
    for v in (0.002, 0.002, 0.002, 0.2):
        h.observe(v)
    series = reg.snapshot()["histograms"]["edl_serve_latency_seconds"]
    p50 = histogram_quantile(series, 0.5)
    assert 0.001 <= p50 <= 0.0025
    p95 = histogram_quantile(series, 0.95)
    assert 0.1 <= p95 <= 0.25
    # +Inf-bucket observations clamp to the largest finite bound
    h2 = reg.histogram("edl_resize_seconds")
    h2.observe(999.0)
    s2 = reg.snapshot()["histograms"]["edl_resize_seconds"]
    assert histogram_quantile(s2, 0.99) == 120.0


# -- manifests / spec / CLI -------------------------------------------------

SERVING_JOB_YAML = """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: serve-demo}
spec:
  fault_tolerant: true
  global_batch_size: 64
  checkpoint_dir: /ckpts
  trainer:
    entrypoint: mnist
    min_instance: 1
    max_instance: 4
    slice_topology: cpu
  serving:
    min_replicas: 2
    max_replicas: 5
    port: 7180
    max_batch: 32
"""


def test_serving_spec_roundtrip_and_validation():
    from edl_tpu.resource.training_job import TrainingJob, ValidationError

    job = TrainingJob.from_yaml(SERVING_JOB_YAML).validate()
    sv = job.spec.serving
    assert (sv.min_replicas, sv.max_replicas, sv.max_batch) == (2, 5, 32)
    # manifest round-trip keeps the serving section
    job2 = TrainingJob.from_manifest(job.to_manifest())
    assert job2.spec.serving.max_replicas == 5
    # serving without a durable checkpoint dir cannot load weights
    bad = TrainingJob.from_yaml(
        SERVING_JOB_YAML.replace("  checkpoint_dir: /ckpts\n", "")
    )
    with pytest.raises(ValidationError, match="checkpoint_dir"):
        bad.validate()
    worse = TrainingJob.from_yaml(
        SERVING_JOB_YAML.replace("max_replicas: 5", "max_replicas: 1")
    )
    with pytest.raises(ValidationError, match="replica bounds"):
        worse.validate()


def test_serving_manifests_render_fleet_and_env_contract():
    from edl_tpu.controller.jobparser import parse_to_serving_manifests
    from edl_tpu.resource.training_job import TrainingJob

    job = TrainingJob.from_yaml(SERVING_JOB_YAML).validate()
    objs = parse_to_serving_manifests(job)
    kinds = [(o["kind"], o["metadata"]["name"]) for o in objs]
    assert kinds == [
        ("Deployment", "serve-demo-serve-coordinator"),
        ("Service", "serve-demo-serve-coordinator"),
        ("Deployment", "serve-demo-serve"),
        ("Service", "serve-demo-serve"),
        ("Deployment", "serve-demo-router"),
        ("Service", "serve-demo-router"),
    ]
    dep = objs[2]
    assert dep["spec"]["replicas"] == 2
    container = dep["spec"]["template"]["spec"]["containers"][0]
    assert container["command"] == ["python", "-m", "edl_tpu.serving.server"]
    env = {e["name"]: e.get("value") for e in container["env"]}
    assert env["EDL_SERVE_MAX_BATCH"] == "32"
    assert env["EDL_SERVE_PORT"] == "7180"
    assert env["EDL_CHECKPOINT_DIR"] == "/ckpts"
    assert env["EDL_COORDINATOR_ADDR"].startswith(
        "serve-demo-serve-coordinator:"
    )
    # the serving coordinator bounds the lane's replica range
    cmd = objs[0]["spec"]["template"]["spec"]["containers"][0]["command"]
    assert cmd[cmd.index("--min-world") + 1] == "2"
    assert cmd[cmd.index("--max-world") + 1] == "5"
    # the front door (ISSUE 20): routerd rides the same serving
    # coordinator, configured by the EDL_ROUTE_* contract
    rcontainer = objs[4]["spec"]["template"]["spec"]["containers"][0]
    assert rcontainer["command"] == [
        "python", "-m", "edl_tpu.serving.router",
    ]
    renv = {e["name"]: e.get("value") for e in rcontainer["env"]}
    assert renv["EDL_COORDINATOR_ADDR"].startswith(
        "serve-demo-serve-coordinator:"
    )
    assert renv["EDL_ROUTE_PORT"] == "7190"
    assert renv["EDL_ROUTE_RETRY_BUDGET_MS"] == "10000"
    assert renv["EDL_ROUTE_PROBE_MS"] == "500"
    assert renv["EDL_ROUTE_EJECT_AFTER"] == "3"
    assert objs[5]["spec"]["ports"] == [{"name": "route", "port": 7190}]
    # a train-only job renders NO serving objects
    job.spec.serving = None
    assert parse_to_serving_manifests(job) == []


def test_cli_manifests_include_serving_fleet(tmp_path, capsys):
    import yaml

    from edl_tpu.cli import main

    p = tmp_path / "job.yaml"
    p.write_text(SERVING_JOB_YAML)
    assert main(["manifests", str(p)]) == 0
    docs = list(yaml.safe_load_all(capsys.readouterr().out))
    names = sorted(d["metadata"]["name"] for d in docs)
    assert "serve-demo-serve" in names
    assert "serve-demo-serve-coordinator" in names


def test_cli_metrics_pretty_prints_serving_section(capsys):
    from edl_tpu.cli import main
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(target_world=1, max_world=2)
    coord.register("serve-0")
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("edl_serve_latency_seconds")
    for _ in range(10):
        h.observe(0.02)
    reg.counter("edl_serve_requests_total").inc(10, status="ok")
    reg.gauge("edl_serve_queue_depth").set(3)
    reg.gauge("edl_serve_weights_step").set(42)
    # drain posture (ISSUE 15): per-replica state + drain counters
    reg.gauge("edl_serve_draining").set(1, replica="serve-0")
    reg.counter("edl_serve_drains_total").inc()
    coord.report_telemetry("serve-0", snapshot=reg.snapshot(), seq=1)
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start(
        evict=False
    )
    try:
        assert main(["metrics", f"127.0.0.1:{server.port}"]) == 0
        out = capsys.readouterr().out
        assert "serving" in out
        assert "latency_p95" in out and "ms" in out
        assert "queue_depth_max" in out and "3" in out
        assert "weights_step" in out and "42" in out
        assert "status=ok" in out
        assert "drain{replica=serve-0}" in out and "draining" in out
        assert "drains_total" in out
    finally:
        server.stop()
