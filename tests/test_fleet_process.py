"""Fleet-market acceptance on REAL processes (the ISSUE 11 bar): a
scripted serving latency spike provably steals chips from the
lowest-priority trainer and gives them back when the spike clears —

- the preemption is a consensus-clean scale-down: both members of the
  victim world leave at ONE agreed stop step (skew 0 across their
  journals, the PR 6 claim under the arbiter's actuation),
- the serving grant lands only after the victim-drain ack,
- every transition runs under its OWN minted trace id spanning
  decision -> vote/quiesce -> resize -> first post-resize step,
- warm resizes perform ZERO true XLA compiles on EVERY member
  (journaled at the backend_compile seam by the launcher's
  ``EDL_COUNT_XLA_COMPILES``),
- the protected high-priority trainer is never touched.

The storm driver is shared with ``bench.py fleet``
(``bench_lib.fleet.run_fleet_storm``); this test asserts its
invariants, the bench publishes its figures."""

from bench_lib.fleet import run_fleet_storm


def test_fleet_spike_steals_chips_from_lowest_priority_and_returns(
    tmp_path,
):
    r = run_fleet_storm(str(tmp_path), base_port=13500)

    # -- the market behaved: calm is a fixed point, the victim is the
    #    LOWEST-priority trainer, the chips came back ----------------------
    assert r["calm_tick_diffs"] == 0
    assert r["victim"] == "lo"
    assert all(p["victim"] == "lo" for p in r["preemptions"])
    spiked = [
        c["holdings"]
        for c in r["chips_over_time"]
        if c["phase"] in ("spike", "spike-hold")
    ]
    assert spiked and all(
        h == {"api": 2, "hi": 1, "lo": 1} for h in spiked
    )
    assert r["chips_over_time"][-1]["holdings"] == {
        "api": 1,
        "hi": 1,
        "lo": 2,
    }
    assert r["slo_attainment"] == 1.0

    # -- consensus-clean scale-down: one agreed boundary ------------------
    assert r["stop_skew_steps"] == 0
    assert r["stop_step"] > 0
    spike_entries = {
        d["job"]: d for d in r["spike_record"]["decisions"]
    }
    assert spike_entries["lo"]["preempted"]
    assert spike_entries["lo"]["preempted_by"] == "api"
    assert spike_entries["lo"]["priority"] == 0
    assert spike_entries["hi"]["priority"] == 10
    # the serving grant waited for the victim world's drain ack
    assert spike_entries["lo"]["drained"] is True

    # -- one trace id per transition, end to end --------------------------
    tr = r["traces"]
    ids = [
        tr.get(k)
        for k in (
            "preempt_down",
            "preempt_serve_up",
            "restore_up",
            "restore_serve_down",
        )
    ]
    assert all(ids) and len(set(ids)) == 4

    def kinds(member, trace):
        return [
            e["kind"]
            for e in r["member_events"][member]
            if e.get("trace") == trace
        ]

    down = tr["preempt_down"]
    # the data-plane agreement journals under the decision's id on the
    # members that ran it, and the survivor's resize + first step close
    # the chain
    assert "consensus.stop" in kinds("lo-a", down)
    for member in ("lo-a", "lo-b"):
        assert "consensus.quiesce" in kinds(member, down), member
    assert "resize" in kinds("lo-a", down)
    assert "step.first" in kinds("lo-a", down)
    up = tr["restore_up"]
    for member in ("lo-a", "lo-b"):
        assert "resize" in kinds(member, up), member
        assert "step.first" in kinds(member, up), member

    # -- zero-compile warm resizes, measured on real workers --------------
    for member, evs in r["member_events"].items():
        for ev in evs:
            if ev.get("kind") == "step.first" and ev.get("trace") in (
                down,
                up,
            ):
                assert ev["data"]["xla_compiles"] == 0, (member, ev)

    # -- the protected high-priority job was never touched ----------------
    assert r["hi_generation_stable"]
    assert r["hi_resize_worlds"] == [1]
