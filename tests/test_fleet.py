"""Fleet-market tests: golden arbiter scenarios (the multi-job analog
of the reference's scaling suite, ``pkg/autoscaler_internal_test.go``
— starved low-priority job, max-capped job, inventory exhaustion,
oscillation-free convergence), the decision-log schema, actuation
ordering (prewarm→retarget per transition, downs-before-ups, victim
drain), bidder adapters, and the ``edl fleet`` CLI."""

import pytest

from edl_tpu import telemetry
from edl_tpu.fleet import (
    Bid,
    ChipInventory,
    FleetArbiter,
    ServingBidder,
    TrainingBidder,
    arbitrate,
    attach_fleet,
)


def tbid(
    name,
    pri=0,
    cur=1,
    mn=1,
    mx=4,
    chips=1,
    util=None,
    legal=None,
):
    return Bid(
        name=name,
        kind="training",
        priority=pri,
        chips_per_unit=chips,
        min_units=mn,
        max_units=mx,
        current_units=cur,
        legal_units=list(legal) if legal else [],
        utility=util,
        elastic=mn < mx,
    )


def sbid(name, cur=1, req=1, mn=1, mx=4, chips=1):
    return Bid(
        name=name,
        kind="serving",
        priority=100,
        chips_per_unit=chips,
        min_units=mn,
        max_units=mx,
        current_units=cur,
        required_units=req,
        elastic=mn < mx,
    )


# ---- golden fixed-point scenarios -------------------------------------------


def test_calm_full_inventory_is_a_fixed_point():
    r = arbitrate(
        [tbid("lo", 0, 2, mx=2), tbid("hi", 10, 1, mx=1), sbid("s", 1, 1, mx=2)],
        4,
    )
    assert r.targets == {"lo": 2, "hi": 1, "s": 1}
    assert r.free_chips == 0 and not r.preemptions and not r.unmet
    assert r.iterations == 1


def test_starved_low_priority_job_pins_at_min():
    """Higher tier takes every marginal chip; the low tier holds its
    floor (the reference's 'starved' variant, now cross-job)."""
    r = arbitrate([tbid("lo", 0, 1, mx=4), tbid("hi", 10, 1, mx=4)], 5)
    assert r.targets == {"hi": 4, "lo": 1}
    assert r.free_chips == 0


def test_max_capped_job_leaves_chips_free():
    r = arbitrate([tbid("a", 0, 1, mx=3)], 8)
    assert r.targets == {"a": 3}
    assert r.free_chips == 5  # never grown past max


def test_inventory_exhaustion_never_overcommits():
    bids = [
        tbid("a", 5, 1, mx=8, chips=2),
        tbid("b", 3, 1, mx=8, chips=2),
        tbid("c", 0, 1, mx=8, chips=2),
    ]
    r = arbitrate(bids, 9)
    used = sum(r.targets[b.name] * b.chips_per_unit for b in bids)
    assert used <= 9 and r.free_chips == 9 - used
    # priority order got the marginal chips
    assert r.targets["a"] >= r.targets["b"] >= r.targets["c"]
    assert r.targets["c"] == 1  # floor held


def test_serving_spike_preempts_lowest_priority_trainer():
    """THE preemption contract: among preemptible trainers the LOWEST
    priority sheds first, one legal step, and the serving requirement
    is covered exactly."""
    r = arbitrate(
        [
            tbid("lo", 0, 2, mx=2),
            tbid("hi", 10, 2, mx=2),
            sbid("api", 1, 2, mx=2),
        ],
        5,
    )
    assert r.targets == {"lo": 1, "hi": 2, "api": 2}
    assert [p["victim"] for p in r.preemptions] == ["lo"]
    assert r.preemptions[0]["beneficiary"] == "api"
    assert not r.unmet


def test_chips_return_when_spike_clears():
    """Serving above its requirement sheds to it, and the freed chips
    flow back to training in the SAME fixed point."""
    r = arbitrate(
        [tbid("lo", 0, 1, mx=2), tbid("hi", 10, 1, mx=1), sbid("api", 2, 1, mx=2)],
        4,
    )
    assert r.targets == {"api": 1, "lo": 2, "hi": 1}
    assert r.free_chips == 0 and not r.preemptions


def test_preemption_stops_at_min_and_reports_unmet():
    """Floors are floors: when every trainer is at min the serving
    requirement goes unmet and is REPORTED, not silently absorbed."""
    r = arbitrate(
        [tbid("lo", 0, 1, mx=2), sbid("api", 1, 4, mx=4)], 2
    )
    assert r.targets == {"lo": 1, "api": 1}
    assert r.unmet == {"api": 3}


def test_oscillation_free_convergence():
    """Feeding a fixed point's targets back as currents changes
    nothing: no diffs, no preemptions, one iteration (the
    livelock-at-full-utilization class the reference had)."""
    bids = [
        tbid("lo", 0, 2, mx=4),
        tbid("hi", 10, 1, mx=4),
        sbid("api", 1, 2, mx=2),
    ]
    r1 = arbitrate(bids, 6)
    again = [
        tbid("lo", 0, r1.targets["lo"], mx=4),
        tbid("hi", 10, r1.targets["hi"], mx=4),
        sbid("api", r1.targets["api"], 2, mx=2),
    ]
    r2 = arbitrate(again, 6)
    assert r2.targets == r1.targets
    assert not r2.preemptions
    assert r2.iterations == 1


def test_goodput_per_chip_orders_growth_within_tier():
    """The objective: within one priority tier the marginal chip goes
    to the best measured goodput-per-chip; unmeasured bids sort last."""
    r = arbitrate(
        [
            tbid("meh", 0, 1, mx=4, util=0.05),
            tbid("good", 0, 1, mx=4, util=0.8),
            tbid("blind", 0, 1, mx=4, util=None),
        ],
        4,
    )
    assert r.targets["good"] == 2
    assert r.targets["meh"] + r.targets["blind"] == 2  # floors + leftover


def test_growth_spreads_within_a_tier_by_diminishing_utility():
    """Utility is re-scaled to the EVOLVING allocation: a job that just
    took a step needs a proportionally better ledger to take the next
    one, so equal-tier jobs spread instead of one absorbing the whole
    free pool."""
    r = arbitrate(
        [tbid("a", 0, 1, mx=4, util=0.9), tbid("b", 0, 1, mx=4, util=0.8)],
        6,
    )
    assert r.targets == {"a": 3, "b": 3}


def test_preemption_rotates_between_equal_tier_victims():
    """Victim fulfillment is computed at the evolving allocation too:
    a big requirement sheds BOTH equal-tier trainers evenly, not one
    to its floor first."""
    r = arbitrate(
        [
            tbid("a", 0, 4, mx=4),
            tbid("b", 0, 4, mx=4),
            sbid("api", 1, 5, mx=5),
        ],
        9,
    )
    assert r.targets == {"a": 2, "b": 2, "api": 5}
    assert [p["victim"] for p in r.preemptions] == ["a", "b", "a", "b"]


def test_legal_size_quantized_preemption_steps():
    """Slice/batch quantization survives preemption: a [1,2,4] trainer
    sheds 4 -> 2 (a whole legal step), never 4 -> 3."""
    r = arbitrate(
        [tbid("lo", 0, 4, mx=4, legal=[1, 2, 4]), sbid("api", 1, 2, mx=2)],
        5,
    )
    assert r.targets == {"lo": 2, "api": 2}
    assert r.preemptions[0]["units_to"] == 2


def test_oversubscription_sheds_lowest_priority_first():
    """Inventory shrank under running jobs: the shed starts at the
    lowest tier (pass 0), not at whoever sorts first."""
    r = arbitrate([tbid("lo", 0, 4, mx=4), tbid("hi", 9, 4, mx=4)], 5)
    assert r.targets["hi"] == 4 and r.targets["lo"] == 1
    assert r.free_chips == 0


def test_duplicate_bid_names_rejected():
    with pytest.raises(ValueError):
        arbitrate([tbid("x"), tbid("x")], 4)


# ---- inventory ---------------------------------------------------------------


def test_chip_inventory_accounting():
    inv = ChipInventory(total_chips=8)
    inv.set_holding("a", 3)
    inv.set_holding("b", 2)
    assert inv.free() == 3 and inv.allocated() == 5
    inv.set_holding("a", 0)
    assert "a" not in inv.holdings and inv.free() == 6
    snap = inv.snapshot()
    assert snap == {"total_chips": 8, "free_chips": 6, "holdings": {"b": 2}}
    with pytest.raises(ValueError):
        inv.set_holding("c", -1)


def test_inventory_from_cluster_resource_parks_scheduled_chips():
    from edl_tpu.cluster.resources import ClusterResource

    r = ClusterResource(tpu_total=16, tpu_limit=6)
    assert r.free_chips() == 10
    inv = ChipInventory.from_cluster_resource(r)
    assert inv.total_chips == 16
    assert inv.holdings == {"(scheduled)": 6}
    assert inv.free() == 10


# ---- resource-model plumbing -------------------------------------------------


def _job(priority=0, mn=1, mx=4):
    from edl_tpu.resource.training_job import TrainingJob

    return TrainingJob.from_manifest(
        {
            "apiVersion": "edl.tpu.dev/v1",
            "kind": "TrainingJob",
            "metadata": {"name": "j"},
            "spec": {
                "fault_tolerant": True,
                "priority": priority,
                "global_batch_size": 96,
                "trainer": {
                    "min_instance": mn,
                    "max_instance": mx,
                    "slice_topology": "v5e-4",
                },
            },
        }
    ).validate()


def test_training_bidder_from_job_reads_spec():
    job = _job(priority=7, mn=1, mx=8)
    b = TrainingBidder.from_job(job, coordinator=None)
    assert b.priority == 7
    assert (b.min_units, b.max_units) == (1, 8)
    assert b.chips_per_unit == 4
    assert b.legal_units == job.legal_world_sizes() == [1, 2, 3, 4, 6, 8]


def test_jobview_carries_priority():
    from edl_tpu.autoscaler.algorithm import JobView

    assert JobView.from_job(_job(priority=3)).priority == 3


def test_spec_priority_validated():
    from edl_tpu.resource.training_job import ValidationError

    with pytest.raises(ValidationError):
        _job(priority=-1)


# ---- arbiter driver: actuation + journaling ---------------------------------


class FakeCoord:
    """Coordinator double shared across bidders; every call lands in a
    COMMON sequenced log so cross-job ordering is assertable."""

    def __init__(self, world, seq, name, goodput=None):
        self.world = world
        self.seq = seq
        self.name = name
        self.goodput = goodput

    def metrics(self):
        return {
            "target_world": self.world,
            "world_size": self.world,
            "world_acked": True,
            "acked_members": 1,
        }

    def telemetry(self):
        if self.goodput is None:
            return {}
        return {"goodput": {"frac": self.goodput}, "step_rate": 5.0}

    def set_prewarm(self, n, trace_id=""):
        self.seq.append((self.name, "prewarm", n, trace_id))

    def set_target_world(self, n, trace_id=""):
        self.seq.append((self.name, "target", n, trace_id))
        self.world = n

    def target_world(self):
        return self.world


class FakeLane:
    """Minimal ServingLane stand-in: fixed requirement, real bounds."""

    def __init__(self, coord, required, mn=1, mx=2):
        self.coordinator = coord
        self.min_replicas = mn
        self.max_replicas = mx
        self.required = required
        self.on_scale = None

    def observe(self):
        return {"p95_latency_s": None, "queue_depth": 0}

    def current_replicas(self):
        return self.coordinator.world

    def desired_replicas(self, obs, current):
        return self.required, "scripted"


def _market(seq, lo_world=2, hi_world=1, serve_world=1, required=1):
    lo = FakeCoord(lo_world, seq, "lo", goodput=0.9)
    hi = FakeCoord(hi_world, seq, "hi", goodput=0.8)
    api = FakeCoord(serve_world, seq, "api")
    arbiter = FleetArbiter(
        4,
        trainers=[
            TrainingBidder("lo", lo, priority=0, min_units=1, max_units=2),
            TrainingBidder("hi", hi, priority=10, min_units=1, max_units=1),
        ],
        fleets=[
            ServingBidder("api", FakeLane(api, required)),
        ],
    )
    return arbiter, lo, hi, api


def test_arbiter_prewarm_before_retarget_per_transition_with_own_trace():
    with telemetry.scoped():
        seq = []
        arbiter, lo, hi, api = _market(seq, required=2)
        rec = arbiter.run_once()
    assert rec is not None
    # two transitions: lo down, api up — each prewarm->target with ONE
    # non-empty trace id, and the two ids are distinct
    lo_ops = [op for op in seq if op[0] == "lo"]
    api_ops = [op for op in seq if op[0] == "api"]
    assert [op[1] for op in lo_ops] == ["prewarm", "target"]
    assert [op[1] for op in api_ops] == ["prewarm", "target"]
    lo_traces = {op[3] for op in lo_ops}
    api_traces = {op[3] for op in api_ops}
    assert len(lo_traces) == 1 and len(api_traces) == 1
    assert lo_traces != api_traces and "" not in lo_traces | api_traces
    # downs actuate before ups: the victim's chips free first
    assert seq.index(lo_ops[0]) < seq.index(api_ops[0])
    assert lo.world == 1 and api.world == 2 and hi.world == 1


def test_arbiter_decision_log_schema():
    """The per-job decision-log contract the tentpole adds: every bid
    journals an entry with priority / preemption / trace fields."""
    with telemetry.scoped():
        seq = []
        arbiter, *_ = _market(seq, required=2)
        rec = arbiter.run_once()
    required_keys = {
        "lane", "job", "kind", "priority", "dry_run", "observed",
        "required_units", "utility", "preempted", "preempted_by",
        "actuated", "drained", "reason", "trace_id",
    }
    entries = {d["job"]: d for d in rec["decisions"]}
    assert set(entries) == {"lo", "hi", "api"}
    for d in rec["decisions"]:
        assert required_keys <= set(d), sorted(required_keys - set(d))
        assert d["lane"] == "fleet"
        assert set(d["dry_run"]) == {"current", "proposed", "diff"}
    assert entries["lo"]["preempted"] and entries["lo"]["preempted_by"] == "api"
    assert entries["lo"]["priority"] == 0 and entries["hi"]["priority"] == 10
    assert entries["lo"]["trace_id"] and entries["lo"]["actuated"]
    assert entries["lo"]["drained"] is True
    assert entries["hi"]["trace_id"] == ""  # no transition, no id
    assert entries["api"]["required_units"] == 2
    # the arbiter's own log mirrors the tick's entries
    assert arbiter.decision_log[-3:] == rec["decisions"]


def test_arbiter_journals_fleet_events_and_metrics():
    with telemetry.scoped() as (reg, rec_):
        seq = []
        arbiter, *_ = _market(seq, required=2)
        arbiter.run_once()
        kinds = [e.kind for e in rec_.events()]
        assert kinds.count("fleet.decision") == 3
        assert kinds.count("fleet.preempt") == 1
        preempt = next(
            e for e in rec_.events() if e.kind == "fleet.preempt"
        )
        assert preempt.data["victim"] == "lo"
        assert preempt.data["victim_trace"]
        assert preempt.data["beneficiary_trace"]
        snap = reg.snapshot()
        gauges = snap["gauges"]
        assert gauges["edl_fleet_chips_total"][""] == 4
        assert gauges["edl_fleet_chips_free"][""] == 0
        assert gauges["edl_fleet_chips_allocated"]["job=lo"] == 1
        assert gauges["edl_fleet_chips_allocated"]["job=api"] == 2
        assert gauges["edl_fleet_unmet_demand_chips"]["job=api"] == 0
        assert (
            snap["counters"]["edl_fleet_preemptions_total"]["job=lo"]
            == 1
        )


def test_unreachable_coordinator_freezes_its_holding():
    """A bidder whose coordinator is down neither grows nor sheds, and
    the market reserves (at least) its floor instead of handing its
    chips to someone else."""

    class DeadCoord:
        def metrics(self):
            raise ConnectionError("down")

    with telemetry.scoped():
        seq = []
        arbiter, lo, hi, api = _market(seq, required=2)
        arbiter.trainers[0] = TrainingBidder(
            "lo", DeadCoord(), priority=0, min_units=1, max_units=2
        )
        # lo's LAST-KNOWN holding (the previous tick's actuation) is 2
        # chips — its pods still physically hold them, so the spike
        # must NOT be granted lo's second chip just because lo's
        # coordinator stopped answering.
        arbiter.inventory.set_holding("lo", 2)
        rec = arbiter.run_once()
    assert rec["blind"] == ["lo"]
    jobs = {d["job"] for d in rec["decisions"]}
    assert "lo" not in jobs
    assert api.world == 1 and hi.world == 1
    api_entry = next(
        d for d in rec["decisions"] if d["job"] == "api"
    )
    assert api_entry["dry_run"]["proposed"] == 1  # requirement unmet
    assert rec["unmet"] == {"api": 1}


def test_failed_actuation_keeps_the_physical_holding():
    """A retarget that fails leaves the old allocation standing: the
    journaled holding (what the blind-coordinator freeze reserves next
    tick) must stay at the PHYSICAL occupancy, not the unactuated
    target — and drained must not claim a quiesce that never ran."""

    class FlakyCoord(FakeCoord):
        def set_target_world(self, n, trace_id=""):
            raise ConnectionError("retarget lost")

    with telemetry.scoped():
        seq = []
        lo = FlakyCoord(2, seq, "lo", goodput=0.9)
        api = FakeCoord(1, seq, "api")
        arbiter = FleetArbiter(
            4,
            trainers=[
                TrainingBidder(
                    "lo", lo, priority=0, min_units=1, max_units=2
                ),
                TrainingBidder(
                    "hi",
                    FakeCoord(1, seq, "hi", goodput=0.8),
                    priority=10,
                    min_units=1,
                    max_units=1,
                ),
            ],
            fleets=[ServingBidder("api", FakeLane(api, 2))],
        )
        rec = arbiter.run_once()
    entry = next(d for d in rec["decisions"] if d["job"] == "lo")
    assert not entry["actuated"] and entry["drained"] is False
    # the pods still hold 2 chips; the ledger must say so
    assert arbiter.inventory.holdings["lo"] == 2


def test_market_clears_stale_non_fleet_holdings():
    """A non-fleet holding that vanishes from the fresh inventory
    inquiry is cleared from the arbiter's ledger — no phantom
    allocated chips in chips-over-time."""
    src = {"inv": ChipInventory(total_chips=4)}
    src["inv"].set_holding("(scheduled)", 2)
    with telemetry.scoped():
        seq = []
        lo = FakeCoord(1, seq, "lo", goodput=0.5)
        arbiter = FleetArbiter(
            lambda: src["inv"],
            trainers=[
                TrainingBidder(
                    "lo", lo, priority=0, min_units=1, max_units=4
                )
            ],
        )
        r1 = arbiter.run_once()
        assert r1["inventory"]["holdings"] == {"(scheduled)": 2, "lo": 2}
        fresh = ChipInventory(total_chips=4)  # outside workload done
        src["inv"] = fresh
        r2 = arbiter.run_once()
    assert "(scheduled)" not in r2["inventory"]["holdings"]
    assert r2["inventory"]["holdings"] == {"lo": 4}


def test_attach_fleet_rides_the_autoscaler_tick():
    from edl_tpu.autoscaler.scaler import Autoscaler

    class NullCluster:
        def update_parallelism(self, job, n):
            pass

        def delete_pod(self, name):
            return True

    with telemetry.scoped():
        sc = Autoscaler(NullCluster(), coord_client_factory=lambda j: None)
        seq = []
        arbiter, *_ = _market(seq, required=2)
        attach_fleet(sc, arbiter)
        assert sc.run_once() is None  # no single-cluster jobs registered
        fleet_entries = [
            d for d in sc.decision_log if d.get("lane") == "fleet"
        ]
        assert {d["job"] for d in fleet_entries} == {"lo", "hi", "api"}
        with pytest.raises(ValueError):
            attach_fleet(sc, arbiter)


def test_serving_bidder_band_with_scripted_signals():
    """The REAL ServingLane band logic (p95 window / hysteresis) drives
    the bid's hard requirement when signals are scripted."""
    from edl_tpu.autoscaler.serving import ServingLane
    from edl_tpu.runtime.coordinator import LocalCoordinator

    with telemetry.scoped():
        coord = LocalCoordinator(target_world=1, max_world=2)
        lane = ServingLane(
            coord, min_replicas=1, max_replicas=2, hold_ticks=2
        )
        sig = {"p95_latency_s": 0.01, "queue_depth": 0}
        bidder = ServingBidder("api", lane, signals=lambda: dict(sig))
        assert bidder.collect().required_units == 1
        sig["p95_latency_s"] = 3.0
        bid = bidder.collect()
        assert bid.required_units == 2
        assert "overloaded" in bid.observed["slo_reason"]
        sig["p95_latency_s"] = 0.001
        coord.set_target_world(2)
        assert bidder.collect().required_units == 2  # hysteresis hold 1/2
        assert bidder.collect().required_units == 1  # sheds on tick 2


# ---- edl fleet CLI -----------------------------------------------------------


def test_fleet_cli_table_and_json(capsys):
    from edl_tpu.cli import main as cli_main
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(target_world=2, max_world=2)
    coord.register("t0")
    coord.register("t1")
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    try:
        url = f"127.0.0.1:{server.port}"
        rc = cli_main(
            ["fleet", "--job", f"lo={url},chips=4,priority=2", "--chips", "16"]
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "lo" in out and "training" in out
        assert "chips allocated: 8 / 16 total" in out
        rc = cli_main(["fleet", "--job", f"lo={url},chips=4", "--json"])
        assert rc == 0
        import json

        doc = json.loads(capsys.readouterr().out)
        assert doc["bidders"][0]["chips"] == 8
        assert doc["chips_allocated"] == 8
    finally:
        server.stop()


def test_fleet_cli_requires_bidders(capsys):
    from edl_tpu.cli import main as cli_main

    assert cli_main(["fleet"]) == 2
    assert "no bidders" in capsys.readouterr().err
