"""ISSUE 20 acceptance: the fleet front door.

Three layers of coverage:

- ``RetryingClient`` unit contract (fake targets, fake clock): 429
  backs off HERE honoring Retry-After, 503 goes ELSEWHERE, refused is
  dead, 4xx is never retried, and exhaustion is TYPED — ``saturated``
  only when the last full pass over the fleet was queue-full end to
  end.
- ``RequestRouter`` logic against scriptable stub backends (no JAX):
  least-loaded pick, steer-before-503 from all three drain signals
  (intent, journal, healthz bit), passive eject + active-probe-only
  readmit, saturated-503 vs broken-502 at the ``RouterServer`` front,
  advisory prefix affinity.
- Live JAX fleets: /predict through a real fit fleet, mid-stream
  /generate re-drive (RESUME on a matching purity stamp, RESTART on
  skew), the drain-ordering guarantee (victim ack implies the router
  already steered — one trace id across ServingLane decision ->
  route.steer -> serve.drain ack), and the seeded router chaos soak
  whose recorder digest and structured log are bit-identical across
  same-seed reruns.
"""

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu import telemetry
from edl_tpu.chaos.schedule import FaultEvent, FaultSchedule
from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.models.base import get_model
from edl_tpu.runtime.coordinator import LocalCoordinator
from edl_tpu.runtime.train import TrainState
from edl_tpu.serving import (
    ContinuousBatcher,
    DecodeEngine,
    DrainingError,
    InferenceEngine,
    QueueFullError,
    RequestRouter,
    RetryBudgetExhausted,
    RetryingClient,
    RouterServer,
    ServingReplica,
    ServingServer,
    UpstreamClientError,
)
from tests.test_decode_serving import _reference_decode

_OPT = optax.adam(1e-3)


def _line_state(g: float) -> TrainState:
    params = {
        "w": jnp.full((13,), g, jnp.float32),
        "b": jnp.asarray(g, jnp.float32),
    }
    return TrainState(
        step=jnp.asarray(int(g), jnp.int32),
        params=params,
        opt_state=_OPT.init(params),
    )


def _lm_state(model, step: int, seed: int) -> TrainState:
    p = model.init_params(jax.random.key(seed))
    return TrainState(
        step=jnp.asarray(step, jnp.int32),
        params=p,
        opt_state=_OPT.init(p),
    )


# -- RetryingClient: the shared client-side fallback contract -----------------


class _FakeWire:
    """Deterministic clock+sleep pair for retry-loop unit tests."""

    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def clock(self):
        return self.t

    def sleep(self, d):
        self.sleeps.append(round(d, 6))
        self.t += d


class _Scripted:
    """A target that raises/returns from a per-call script."""

    def __init__(self, name, script):
        self.name = name
        self.script = list(script)
        self.calls = 0

    def __call__(self, request):
        self.calls += 1
        step = self.script.pop(0) if self.script else self.script_tail
        if isinstance(step, BaseException):
            raise step
        return step

    script_tail = None

    def __repr__(self):
        return self.name


def test_retrying_client_queue_full_backs_off_here():
    wire = _FakeWire()
    outcomes = []
    target = _Scripted(
        "full",
        [
            QueueFullError("full", retry_after=0.07),
            QueueFullError("full", retry_after=0.07),
            {"ok": True},
        ],
    )
    client = RetryingClient(
        [target],
        budget_s=5.0,
        sleep=wire.sleep,
        clock=wire.clock,
        on_attempt=lambda t, o, e: outcomes.append((t.name, o)),
    )
    assert client.call({})["ok"]
    # all three attempts hit the SAME target, honoring its Retry-After
    assert target.calls == 3
    assert wire.sleeps == [0.07, 0.07]
    assert outcomes == [
        ("full", "queue_full"),
        ("full", "queue_full"),
        ("full", "ok"),
    ]


def test_retrying_client_draining_goes_elsewhere():
    wire = _FakeWire()
    outcomes = []
    a = _Scripted("a", [DrainingError("leaving", retry_after=0.5)])
    b = _Scripted("b", [{"served_by": "b"}])
    client = RetryingClient(
        [a, b],
        budget_s=5.0,
        sleep=wire.sleep,
        clock=wire.clock,
        on_attempt=lambda t, o, e: outcomes.append((t.name, o)),
    )
    assert client.call({})["served_by"] == "b"
    # ONE attempt on the draining target — no back-off-here burn
    assert a.calls == 1 and b.calls == 1
    assert wire.sleeps == []
    assert outcomes == [("a", "draining"), ("b", "ok")]


def test_retrying_client_refused_goes_elsewhere():
    wire = _FakeWire()
    a = _Scripted("a", [ConnectionError("refused")])
    b = _Scripted("b", [{"served_by": "b"}])
    client = RetryingClient(
        [a, b], budget_s=5.0, sleep=wire.sleep, clock=wire.clock
    )
    assert client.call({})["served_by"] == "b"
    assert a.calls == 1


def test_retrying_client_client_error_never_retried():
    wire = _FakeWire()
    a = _Scripted("a", [UpstreamClientError(400, {"error": "bad prompt"})])
    b = _Scripted("b", [{"served_by": "b"}])
    client = RetryingClient(
        [a, b], budget_s=5.0, sleep=wire.sleep, clock=wire.clock
    )
    with pytest.raises(UpstreamClientError) as ei:
        client.call({})
    assert ei.value.status == 400
    assert b.calls == 0  # every replica would say the same thing


def test_retrying_client_saturated_exhaustion_is_typed():
    wire = _FakeWire()

    # every attempt everywhere is queue-full: the fleet is BUSY
    def full(req):
        raise QueueFullError("full", retry_after=0.2)

    client = RetryingClient(
        [full, full],
        budget_s=2.0,
        attempts=12,
        sleep=wire.sleep,
        clock=wire.clock,
    )
    with pytest.raises(RetryBudgetExhausted) as ei:
        client.call({})
    assert ei.value.saturated
    assert ei.value.retry_after >= 0.2  # the largest backend hint
    assert ei.value.attempts > 0


def test_retrying_client_broken_fleet_is_not_saturated():
    wire = _FakeWire()

    def dead(req):
        raise ConnectionError("refused")

    client = RetryingClient(
        [dead], budget_s=1.0, attempts=6, sleep=wire.sleep,
        clock=wire.clock,
    )
    with pytest.raises(RetryBudgetExhausted) as ei:
        client.call({})
    assert not ei.value.saturated  # gone, not busy: no Retry-After lie


def test_retrying_client_empty_fleet_exhausts_immediately():
    client = RetryingClient([], budget_s=1.0)
    with pytest.raises(RetryBudgetExhausted) as ei:
        client.call({})
    assert not ei.value.saturated


# -- stub backends: router logic without a JAX engine -------------------------


class _StubReplica:
    """A scriptable fake serving replica: /healthz vitals plus
    /predict and /generate behaviors, for status-code choreography
    tests that need no real engine.  ``predict``/``generate`` return
    (code, body) or (code, body, headers)."""

    def __init__(self, rid, healthz=None, predict=None, generate=None):
        self.rid = rid
        self.healthz = healthz or {}
        self.predict = predict or (
            lambda req: (200, {"outputs": {"y": [1.0]}, "weights_step": 1})
        )
        self.generate = generate
        self.hits = []
        self._srv = None
        self._bind(0)

    def _handler(self):
        outer = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, code, body, headers=()):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    h = outer.healthz() if callable(outer.healthz) else (
                        dict(outer.healthz)
                    )
                    base = {
                        "ok": True,
                        "weights_step": 1,
                        "weights_generation": 0,
                        "queue_depth": 0,
                        "queue_limit": 8,
                        "saturation": 0.0,
                        "in_flight": 0,
                        "draining": False,
                    }
                    base.update(h)
                    self._reply(200 if base.get("ok") else 503, base)
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                outer.hits.append((self.path, req))
                if self.path == "/predict":
                    out = outer.predict(req)
                elif self.path == "/generate" and outer.generate:
                    out = outer.generate(req)
                else:
                    out = (404, {"error": "not found"})
                self._reply(out[0], out[1], out[2] if len(out) > 2 else ())

        return H

    def _bind(self, port):
        self._srv = ThreadingHTTPServer(("127.0.0.1", port), self._handler())
        self.port = self._srv.server_address[1]
        self.address = f"127.0.0.1:{self.port}"
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    def stop(self):
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None

    def restart(self):
        """Come back on the SAME address (the restarted-pod shape)."""
        self.stop()
        self._bind(self.port)


class _Plan:
    def __init__(self, members, addresses, generation=1):
        self.generation = generation
        self.members = tuple(members)
        self.addresses = tuple(addresses)


class _StubCoord:
    """Plan + telemetry double for router-logic tests."""

    def __init__(self, stubs, events=None, gauges=None):
        self._stubs = list(stubs)
        self.events = list(events or [])
        self.gauges = gauges or {}

    def plan(self):
        return _Plan(
            [s.rid for s in self._stubs],
            [s.address for s in self._stubs],
        )

    def telemetry(self):
        return {
            "merged": {
                "counters": {},
                "gauges": self.gauges,
                "histograms": {},
            },
            "events": list(self.events),
        }


def test_router_least_loaded_pick():
    with telemetry.scoped():
        stubs = [_StubReplica(f"ll-{i}") for i in range(3)]
        try:
            router = RequestRouter(_StubCoord(stubs))
            router.sync()
            router.probe_all()
            with router._lock:
                router._replicas["ll-0"].queue_depth = 6.0
                router._replicas["ll-1"].queue_depth = 1.0
                router._replicas["ll-2"].kv_occupancy = 0.9  # *4.0 = 3.6
            order = [v.replica_id for v in router._order()]
            assert order == ["ll-1", "ll-2", "ll-0"]
            out = router.predict({"inputs": {}})
            assert out["weights_step"] == 1
            # the admission landed on the least-loaded stub
            assert [len(s.hits) for s in stubs] == [0, 1, 0]
        finally:
            for s in stubs:
                s.stop()


def test_router_drain_intent_steers_before_the_503():
    """mark_draining (the lane's intent publication) removes the
    victim from rotation IMMEDIATELY — it never has to 503 anyone —
    and journals route.steer under the decision trace."""
    with telemetry.scoped() as (_, rec):
        stubs = [_StubReplica("st-0"), _StubReplica("st-1")]
        try:
            router = RequestRouter(_StubCoord(stubs))
            router.sync()
            router.probe_all()
            router.mark_draining(["st-0"], trace="tr-drain")
            for _ in range(4):
                router.predict({"inputs": {}})
            # the victim saw ZERO admissions after the intent
            assert len(stubs[0].hits) == 0
            assert len(stubs[1].hits) == 4
            steers = [e for e in rec.events() if e.kind == "route.steer"]
            assert steers and steers[0].data == {
                "replica": "st-0", "source": "intent",
            }
            assert steers[0].trace == "tr-drain"
            table = router.routing_table()
            health = {r["replica"]: r["health"] for r in table["replicas"]}
            assert health == {"st-0": "draining", "st-1": "healthy"}
        finally:
            for s in stubs:
                s.stop()


def test_router_journal_drain_events_steer_once():
    """serve.drain flight events in the coordinator's merged journal
    are the router's second steer signal (kubelet preStop drains no
    intent ever announced) — consumed by seq watermark, so a replayed
    tail steers exactly once."""
    with telemetry.scoped() as (_, rec):
        stubs = [_StubReplica("jd-0"), _StubReplica("jd-1")]
        try:
            coord = _StubCoord(stubs)
            router = RequestRouter(coord)
            router.sync()
            coord.events = [
                {
                    "seq": 7,
                    "kind": "serve.drain",
                    "data": {"replica": "jd-1", "phase": "start"},
                    "trace": "tr-journal",
                }
            ]
            router.sync()
            router.sync()  # same tail again: watermark dedupes
            steers = [e for e in rec.events() if e.kind == "route.steer"]
            assert len(steers) == 1
            assert steers[0].data == {
                "replica": "jd-1", "source": "journal",
            }
            assert steers[0].trace == "tr-journal"
            assert [v.replica_id for v in router._routable()] == ["jd-0"]
        finally:
            for s in stubs:
                s.stop()


def test_router_healthz_draining_bit_steers():
    with telemetry.scoped() as (_, rec):
        stub = _StubReplica("hz-0", healthz={"draining": True})
        other = _StubReplica("hz-1")
        try:
            router = RequestRouter(_StubCoord([stub, other]))
            router.sync()
            router.probe_all()
            assert [v.replica_id for v in router._routable()] == ["hz-1"]
            steers = [e for e in rec.events() if e.kind == "route.steer"]
            assert steers[0].data == {
                "replica": "hz-0", "source": "healthz",
            }
        finally:
            stub.stop()
            other.stop()


def test_router_passive_eject_and_probe_only_readmit():
    """Consecutive refused attempts eject; a good REQUEST cannot
    resurrect the replica — only a clean active /healthz probe can."""
    with telemetry.scoped() as (_, rec):
        dead = _StubReplica("ej-0")
        live = _StubReplica("ej-1")
        try:
            router = RequestRouter(_StubCoord([dead, live]), eject_after=3)
            router.sync()
            router.probe_all()
            dead.stop()  # abrupt kill: connection refused from now on
            for _ in range(3):
                # each predict tries ej-0 first (tied score, lower id),
                # absorbs the refusal, and is served by ej-1
                out = router.predict({"inputs": {}})
                assert out["weights_step"] == 1
            ejects = [e for e in rec.events() if e.kind == "route.eject"]
            assert ejects and ejects[0].data == {
                "replica": "ej-0", "consecutive_failures": 3,
            }
            assert [v.replica_id for v in router._routable()] == ["ej-1"]
            # 4th request: the ejected replica is not even attempted
            router.predict({"inputs": {}})
            assert len(live.hits) == 4
            # a failing active probe keeps it ejected...
            router.probe("ej-0")
            health = {
                r["replica"]: r["health"]
                for r in router.routing_table()["replicas"]
            }
            assert health["ej-0"] == "ejected"
            # ...and a clean one re-admits (the restarted-pod shape)
            dead.restart()
            assert router.probe("ej-0")
            health = {
                r["replica"]: r["health"]
                for r in router.routing_table()["replicas"]
            }
            assert health["ej-0"] == "healthy"
            readmits = [
                e for e in rec.events() if e.kind == "route.readmit"
            ]
            assert readmits and readmits[0].data == {"replica": "ej-0"}
        finally:
            dead.stop()
            live.stop()


def test_router_server_saturated_503_vs_broken_502():
    """The front door's exhaustion typing: a BUSY fleet answers 503 +
    Retry-After (come back), a GONE fleet answers 502 (no promises)."""
    with telemetry.scoped():
        full = _StubReplica(
            "sat-0",
            predict=lambda req: (
                429,
                {"error": "queue full", "retry_after_s": 0.01},
                [("Retry-After", "0.010")],
            ),
        )
        try:
            router = RequestRouter(
                _StubCoord([full]),
                retry_budget_s=0.4,
                attempts=6,
                base_backoff_s=0.005,
                max_backoff_s=0.02,
            )
            router.sync()
            router.probe_all()
            server = RouterServer(
                router, host="127.0.0.1", sync_interval_s=30.0
            ).start()
            base = f"http://127.0.0.1:{server.port}"

            def post(path, payload):
                req = urllib.request.Request(
                    f"{base}{path}",
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                return urllib.request.urlopen(req, timeout=15)

            try:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    post("/predict", {"inputs": {}})
                assert ei.value.code == 503
                assert ei.value.headers.get("Retry-After") is not None
                body = json.loads(ei.value.read())
                assert body["saturated"] is True
                assert body["retry_after_s"] >= 0.01

                # now the fleet is GONE, not busy
                full.stop()
                with pytest.raises(urllib.error.HTTPError) as ei:
                    post("/predict", {"inputs": {}})
                assert ei.value.code == 502
                assert ei.value.headers.get("Retry-After") is None

                # routerd healthz goes unready with zero healthy backends
                with router._lock:
                    router._replicas["sat-0"].health = "ejected"
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(f"{base}/healthz", timeout=5)
                assert ei.value.code == 503
            finally:
                server.stop()
        finally:
            full.stop()


def test_router_prefix_affinity_is_advisory():
    """Shared-prefix sessions are steered to the replica already
    holding their cached blocks — but ONLY while that replica's load
    stays within the advisory bound."""
    with telemetry.scoped():
        decode_hz = {
            "decode": {
                "block_tokens": 8,
                "decode_queue_depth": 0,
                "kv_occupancy": 0.0,
            }
        }
        stubs = [
            _StubReplica(
                f"aff-{i}",
                healthz=dict(decode_hz),
                generate=lambda req: (
                    200,
                    {"tokens": [1, 2], "weights_step": 1},
                ),
            )
            for i in range(2)
        ]
        try:
            router = RequestRouter(_StubCoord(stubs))
            router.sync()
            router.probe_all()
            prompt = list(range(1, 17))  # two full 8-token blocks
            out = router.generate(
                {"inputs": {"tokens": prompt}, "max_new_tokens": 2}
            )
            assert out["tokens"] == [1, 2]
            # tied scores pick aff-0; its blocks are now remembered
            assert len(stubs[0].hits) == 1
            hashes = router._chain_hashes({"inputs": {"tokens": prompt}})
            assert len(hashes) == 2
            # load aff-0 within the advisory bound: still promoted
            with router._lock:
                router._replicas["aff-0"].queue_depth = 3.0
            order = router._order(generate=True, hashes=hashes)
            assert order[0].replica_id == "aff-0"
            # beyond the bound: affinity yields to load (advisory ONLY)
            with router._lock:
                router._replicas["aff-0"].queue_depth = 10.0
            order = router._order(generate=True, hashes=hashes)
            assert order[0].replica_id == "aff-1"
        finally:
            for s in stubs:
                s.stop()


# -- live fleets --------------------------------------------------------------


def _fit_replica(coord, store, rid):
    engine = InferenceEngine(
        get_model("fit_a_line"),
        store,
        devices=jax.devices()[:1],
        max_batch=4,
    )
    batcher = ContinuousBatcher(engine)
    server = ServingServer(batcher, host="127.0.0.1")
    return ServingReplica(
        engine,
        batcher=batcher,
        server=server,
        coordinator=coord,
        replica_id=rid,
        address=f"127.0.0.1:{server.port}",
        heartbeat_interval=0.05,
        telemetry_interval=1e9,
    ).start()


def _lm_replica(coord, engine, rid):
    batcher = ContinuousBatcher(engine)
    server = ServingServer(batcher, host="127.0.0.1")
    return ServingReplica(
        engine,
        batcher=batcher,
        server=server,
        coordinator=coord,
        replica_id=rid,
        address=f"127.0.0.1:{server.port}",
        heartbeat_interval=0.05,
        telemetry_interval=1e9,
    ).start()


@pytest.fixture(scope="module")
def lm_engines():
    """Three warmed tiny-LM decode engines: two on the SAME weights
    (step 1 — the resume pair) and one a step ahead (step 2 — the
    purity-skew survivor).  Warm once; tests build fresh batchers and
    replicas around them."""
    model = get_model("transformer_lm", tiny=True)
    s1 = HostDRAMStore()
    s1.save_async(_lm_state(model, 1, 1), generation=0)
    s1.wait()
    s2 = HostDRAMStore()
    s2.save_async(_lm_state(model, 2, 2), generation=0)
    s2.wait()
    engines = []
    for store in (s1, s1, s2):
        e = DecodeEngine(
            model,
            store,
            devices=jax.devices()[:1],
            max_batch=1,
            max_seqs=4,
            block_tokens=16,
        )
        assert e.load()
        e.warm()
        engines.append(e)
    params = {
        1: _lm_state(model, 1, 1).params,
        2: _lm_state(model, 2, 2).params,
    }
    return model, params, engines


def test_router_predict_through_live_fleet():
    with telemetry.scoped():
        store = HostDRAMStore()
        store.save_async(_line_state(1.0), generation=0)
        store.wait()
        coord = LocalCoordinator(
            target_world=2, max_world=4, heartbeat_timeout=1e9
        )
        reps = [_fit_replica(coord, store, f"lv-{i}") for i in range(2)]
        try:
            router = RequestRouter(coord)
            router.sync()
            router.probe_all()
            assert router.plan_generation == coord.generation()
            x = np.ones((2, 13), np.float32)
            out = router.predict({"inputs": {"x": x.tolist()}})
            np.testing.assert_allclose(
                out["outputs"]["pred"],
                1.0 * (x.sum(axis=1) + 1.0),
                rtol=1e-4,
            )
            assert out["weights_step"] == 1
        finally:
            for r in reps:
                r.stop()


def test_router_stream_redrive_resumes_without_dup_or_drop(lm_engines):
    """A mid-stream cut re-drives on a survivor serving the SAME
    weights step: the client stream carries every reference token
    exactly once, indices globally contiguous, no restart line."""
    model, params, (ea, eb, _) = lm_engines
    with telemetry.scoped() as (_, rec):
        coord = LocalCoordinator(
            target_world=2, max_world=4, heartbeat_timeout=1e9
        )
        ra = _lm_replica(coord, ea, "lm-a")
        rb = _lm_replica(coord, eb, "lm-b")
        try:
            chaos = FaultSchedule(0, [FaultEvent(0, "route.stream.cut")])
            chaos.advance(0)
            router = RequestRouter(coord, chaos=chaos)
            router.sync()
            router.probe_all()
            rng = np.random.RandomState(3)
            prompt = model.synth_batch(rng, 1)["tokens"][0, :8].tolist()
            ref = _reference_decode(model, params[1], prompt, 6, ea)
            events = []
            router.generate_stream(
                {"inputs": {"tokens": prompt}, "max_new_tokens": 6},
                events.append,
            )
            done = events[-1]
            assert done.get("done") and done["tokens"] == ref
            assert done["redriven"] == 1
            toks = [e for e in events if "token" in e]
            assert [e["i"] for e in toks] == list(range(6))
            assert [e["token"] for e in toks] == ref
            assert not any(e.get("restart") for e in events)
            redrives = [
                e.data["outcome"]
                for e in rec.events()
                if e.kind == "route.redrive"
            ]
            assert redrives == ["resume"]
        finally:
            ra.stop()
            rb.stop()


def test_router_stream_redrive_restarts_on_purity_skew(lm_engines):
    """When the only survivor serves a DIFFERENT weights step, the
    resumed leg's first-token stamp mismatches and the router
    abandons it BEFORE forwarding a token: the client sees one
    restart line (prior tokens void — the batcher's own hot-swap
    contract) and then the survivor's pure sequence."""
    model, params, (ea, _, ec) = lm_engines
    with telemetry.scoped() as (_, rec):
        coord = LocalCoordinator(
            target_world=2, max_world=4, heartbeat_timeout=1e9
        )
        ra = _lm_replica(coord, ea, "lm-a")  # step 1: first pick
        rc = _lm_replica(coord, ec, "lm-c")  # step 2: the survivor
        try:
            chaos = FaultSchedule(0, [FaultEvent(0, "route.stream.cut")])
            chaos.advance(0)
            router = RequestRouter(coord, chaos=chaos)
            router.sync()
            router.probe_all()
            rng = np.random.RandomState(4)
            prompt = model.synth_batch(rng, 1)["tokens"][0, :8].tolist()
            ref2 = _reference_decode(model, params[2], prompt, 6, ec)
            events = []
            killed = []

            def emit(ev):
                events.append(ev)
                if "token" in ev and not killed:
                    # the kill lands right after the first token; the
                    # chaos cut then tears THIS stream and every later
                    # attempt on lm-a is connection-refused
                    ra.server.stop()
                    killed.append(True)

            router.generate_stream(
                {"inputs": {"tokens": prompt}, "max_new_tokens": 6},
                emit,
            )
            done = events[-1]
            assert done.get("done") and done["tokens"] == ref2
            restarts = [e for e in events if e.get("restart")]
            assert len(restarts) == 1 and restarts[0]["redrive"] is True
            # after the restart the indices renumber from 0 and every
            # token is the step-2 reference — nothing mixed in
            after = events[events.index(restarts[0]) + 1:]
            toks = [e for e in after if "token" in e]
            assert [e["i"] for e in toks] == list(range(6))
            assert [e["token"] for e in toks] == ref2
            assert toks[0]["weights_step"] == 2
            redrives = [
                e.data["outcome"]
                for e in rec.events()
                if e.kind == "route.redrive"
            ]
            assert redrives == ["resume", "restart"]
        finally:
            ra.stop()
            rc.stop()


def test_drain_victim_ack_implies_router_already_steering():
    """ISSUE 20 satellite: the scale-down ordering guarantee, read off
    the merged flight journal as ONE trace — ServingLane decision ->
    route.steer (intent) -> serve.drain ack.  The steer's seq strictly
    precedes the drain's, so by the time a victim acks, the router had
    already stopped admitting to it."""
    from edl_tpu.autoscaler.serving import ServingLane

    with telemetry.scoped() as (_, rec):
        store = HostDRAMStore()
        store.save_async(_line_state(1.0), generation=0)
        store.wait()
        coord = LocalCoordinator(
            target_world=2, max_world=4, heartbeat_timeout=1e9
        )
        reps = [_fit_replica(coord, store, f"fd-{i}") for i in range(2)]
        try:
            router = RequestRouter(coord)
            router.sync()
            router.probe_all()
            victim = list(coord.plan().members)[-1]
            lane = ServingLane(
                coord,
                router=router,
                min_replicas=1,
                max_replicas=4,
                hold_ticks=1,
                victim_drain_timeout=10.0,
            )
            entry = lane.run_once()
            assert entry["actuated"] and entry["drain"]["acked"]
            tid = entry["trace_id"]
            assert tid
            evs = rec.events()
            steers = [
                e for e in evs
                if e.kind == "route.steer"
                and e.data.get("replica") == victim
            ]
            starts = [
                e for e in evs
                if e.kind == "serve.drain"
                and e.data.get("replica") == victim
                and e.data.get("phase") == "start"
            ]
            acks = [
                e for e in evs
                if e.kind == "serve.drain"
                and e.data.get("replica") == victim
                and e.data.get("phase") == "done"
            ]
            assert steers and starts and acks
            # one causal chain: decision, steer and ack share the trace
            assert steers[0].trace == tid
            assert starts[0].trace == tid
            assert acks[0].trace == tid
            # and the ordering: steered BEFORE the victim even began
            assert steers[0].seq < starts[0].seq < acks[0].seq
            # the router had stopped admitting to the victim
            assert victim not in [
                v.replica_id for v in router._routable()
            ]
        finally:
            for r in reps:
                r.stop()


# -- the seeded router chaos soak ---------------------------------------------


def _router_soak_events():
    return [
        FaultEvent(1, "route.backend.refused"),
        FaultEvent(2, "route.probe.fail"),
        FaultEvent(3, "route.probe.fail"),
        FaultEvent(5, "route.stream.cut"),
    ]


def _run_router_soak(seed: int):
    """One soak through the front door: a backend refusal absorbed, a
    probe-failure eject + probe readmit, a mid-stream cut re-driven,
    a drain steer — under live traffic, zero client-visible failures.
    Returns (digest, log): both must be bit-identical across
    same-seed runs."""
    with telemetry.scoped() as (_, rec):
        schedule = FaultSchedule(seed, _router_soak_events())
        log = []
        store = HostDRAMStore()
        store.save_async(_line_state(1.0), generation=0)
        store.wait()
        coord = LocalCoordinator(
            target_world=8, max_world=8, heartbeat_timeout=1e9
        )
        fit = [_fit_replica(coord, store, f"rt-{i}") for i in range(2)]
        lm = get_model("transformer_lm", tiny=True)
        dstore = HostDRAMStore()
        dstore.save_async(_lm_state(lm, 1, 1), generation=0)
        dstore.wait()
        dengine = DecodeEngine(
            lm,
            dstore,
            devices=jax.devices()[:1],
            max_batch=1,
            max_seqs=4,
            block_tokens=16,
        )
        drep = _lm_replica(coord, dengine, "rt-d")
        try:
            router = RequestRouter(
                coord,
                chaos=schedule,
                eject_after=2,
                retry_budget_s=8.0,
                base_backoff_s=0.01,
                max_backoff_s=0.05,
            )
            router.sync()
            router.probe_all()
            x = np.ones((1, 13), np.float32).tolist()
            rng = np.random.RandomState(seed)
            prompt = lm.synth_batch(rng, 1)["tokens"][0, :8].tolist()

            def predict():
                out = router.predict({"inputs": {"x": x}})
                assert abs(out["outputs"]["pred"][0] - 14.0) < 1e-2
                return out

            def stream():
                events = []
                router.generate_stream(
                    {"inputs": {"tokens": prompt}, "max_new_tokens": 5},
                    events.append,
                )
                done = events[-1]
                assert done.get("done")
                return done

            def health(rid):
                return {
                    r["replica"]: r["health"]
                    for r in router.routing_table()["replicas"]
                }[rid]

            # round 0: clean traffic, both planes
            for _ in range(3):
                predict()
            base_tokens = stream()["tokens"]
            log.append(("clean", 3, tuple(base_tokens)))

            # step 1: one backend refusal, absorbed invisibly
            schedule.advance(1)
            predict()
            log.append(("refusal_absorbed", True))

            # steps 2-3: consecutive probe failures eject rt-0; the
            # fleet keeps serving; ONLY a clean probe re-admits
            schedule.advance(2)
            router.probe("rt-0")
            schedule.advance(3)
            router.probe("rt-0")
            log.append(("ejected", health("rt-0")))
            predict()
            router.probe("rt-0")  # chaos spent: the probe is clean
            log.append(("readmitted", health("rt-0")))

            # step 5: the stream cut — re-driven, tokens identical to
            # the uncut run (generation purity across the re-drive)
            schedule.advance(5)
            done = stream()
            log.append(
                (
                    "redrive",
                    done["redriven"],
                    tuple(done["tokens"]) == tuple(base_tokens),
                )
            )

            # a drain steer: the victim takes no further admissions
            router.mark_draining(["rt-1"], trace="soak-drain")
            predict()
            log.append(("steered", health("rt-1")))
            return rec.digest(), log
        finally:
            for r in fit:
                r.stop()
            drep.stop()


@pytest.mark.parametrize("seed", [7])
def test_router_chaos_soak_bit_identical(seed):
    d1, log1 = _run_router_soak(seed)
    d2, log2 = _run_router_soak(seed)
    assert log1 == log2
    assert d1 == d2
    # and the soak actually saw what it claims
    stages = [entry[0] for entry in log1]
    assert stages == [
        "clean",
        "refusal_absorbed",
        "ejected",
        "readmitted",
        "redrive",
        "steered",
    ]
    assert log1[2][1] == "ejected"
    assert log1[3][1] == "healthy"
    assert log1[4][1] == 1 and log1[4][2] is True
    assert log1[5][1] == "draining"


# -- operator surfaces: edl route + edl metrics router section ----------------


def test_route_cli_prints_routing_table(capsys):
    """ISSUE 20 satellite: `edl route <addr>` prints the live routing
    table — every backend, its health, and the load score admissions
    are spread by."""
    from edl_tpu.cli import main

    with telemetry.scoped():
        stubs = [
            _StubReplica("rc-0"),
            _StubReplica("rc-1", healthz={"draining": True}),
        ]
        rs = None
        try:
            router = RequestRouter(_StubCoord(stubs))
            router.sync()
            router.probe_all()
            rs = RouterServer(
                router, host="127.0.0.1", port=0, sync_interval_s=1e9
            ).start()
            assert main(["route", f"127.0.0.1:{rs.port}"]) == 0
            out = capsys.readouterr().out
            assert "rc-0" in out and "rc-1" in out
            assert "healthy" in out and "draining" in out
            assert stubs[0].address in out
            assert "plan_generation" in out
            # --json round-trips the raw table
            assert main(
                ["route", f"127.0.0.1:{rs.port}", "--json"]
            ) == 0
            table = json.loads(capsys.readouterr().out)
            assert {r["replica"] for r in table["replicas"]} == {
                "rc-0",
                "rc-1",
            }
        finally:
            if rs is not None:
                rs.stop()
            for s in stubs:
                s.stop()


def test_metrics_cli_prints_router_section(capsys):
    """ISSUE 20 satellite: the routerd ships its registry to the
    coordinator as source \"router\" (RouterServer._report_telemetry),
    and `edl metrics` renders the front-door section — backends by
    state, request outcomes, steers, retries absorbed, ejections."""
    from edl_tpu.cli import main
    from edl_tpu.runtime.coord_service import CoordinatorServer

    with telemetry.scoped():
        stubs = [_StubReplica(f"mc-{i}") for i in range(2)]
        rs = None
        cs = None
        try:
            coord = LocalCoordinator(
                target_world=2, max_world=4, heartbeat_timeout=1e9
            )
            for s in stubs:
                coord.register(s.rid, address=s.address)
            router = RequestRouter(coord)
            rs = RouterServer(
                router, host="127.0.0.1", port=0, sync_interval_s=1e9
            )
            router.sync()
            router.probe_all()
            router.predict({"inputs": {}})
            router.mark_draining(["mc-1"], trace="tr-metrics-cli")
            router.predict({"inputs": {}})
            # eject mc-1 by passive failures while it is down
            stubs[1].stop()
            with router._lock:
                v = router._replicas["mc-1"]
                v.health = "healthy"
            for _ in range(3):
                router.probe("mc-1")
            rs._report_telemetry()
            cs = CoordinatorServer(
                coord, host="127.0.0.1", port=0
            ).start(evict=False)
            assert main(["metrics", f"127.0.0.1:{cs.port}"]) == 0
            out = capsys.readouterr().out
            assert "router" in out
            assert "backends{state=healthy}" in out
            assert "requests{outcome=ok}" in out
            assert "steers_total" in out
            assert "ejections_total" in out
        finally:
            if cs is not None:
                cs.stop()
            if rs is not None:
                rs.stop()
            for s in stubs:
                s.stop()
