"""Content-addressed KV prefix cache (ISSUE 17): shared-prefix
admissions skip straight to the first cold block.

Key guarantees under test:

- **refcounted pool**: shared blocks return to circulation only at
  refcount 0; a published refcount-0 block parks on the cached LRU
  and is evicted lazily — retention never starves admission; freeing
  an unowned id raises ``BlockOwnershipError`` (the double-free
  regression that would hand one block to two sequences);
- **chain-hash lookup**: the longest published run is claimed, the
  divergence block onward prefills cold, the trailing partial block
  is always private, and a colliding hash (chaos ``hash.skew``) is a
  miss — never someone else's K/V;
- **bit-identical reuse**: a warm admission's tokens equal both the
  cold-prefill reference AND a cold same-prompt run, per LM family;
- **generation keying**: a hot swap invalidates the whole index
  atomically — zero cross-generation reuse, asserted per swap in the
  seeded soak, which also journals bit-identically across same-seed
  reruns.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu import telemetry
from edl_tpu.chaos.schedule import FaultEvent, FaultSchedule
from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.models.base import get_model
from edl_tpu.serving import (
    BlockOwnershipError,
    DecodeEngine,
    KVBlockPool,
    PrefixCache,
    TokenContinuousBatcher,
    chain_hashes,
)
from tests.test_decode_serving import _lm_state, _reference_decode


def _pool(num_blocks=8):
    return KVBlockPool(
        2, 4, 16, num_blocks=num_blocks, block_tokens=16,
        dtype=jnp.bfloat16, sharding=None,
    )


def _build_engine(step=1, seed=1, **kw):
    model = get_model("transformer_lm", tiny=True)
    store = HostDRAMStore()
    store.save_async(_lm_state(model, step, seed), generation=0)
    store.wait()
    engine = DecodeEngine(
        model,
        store,
        devices=jax.devices()[:1],
        max_batch=1,
        max_seqs=4,
        block_tokens=16,
        **kw,
    )
    assert engine.load()
    engine.warm()
    return model, store, engine


@pytest.fixture(scope="module")
def prefix_lm():
    """One warmed transformer_lm DecodeEngine shared by the tests that
    don't hot-swap; every test must leave the pool with zero
    live-sequence blocks."""
    return _build_engine()


def _gen(batcher, prompt, n=4, timeout=60):
    return batcher.submit_generate(
        {"tokens": list(prompt)}, max_new_tokens=n, deadline_s=60.0
    ).result(timeout=timeout)


# -- the refcounted pool ------------------------------------------------------


def test_pool_double_free_raises_typed_error():
    """ISSUE 17 satellite regression: pre-guard, ``free`` accepted any
    id silently — a double free enqueued one block twice and two
    later sequences shared it.  Now it raises."""
    pool = _pool()
    got = pool.alloc(2)
    pool.free(got)
    with pytest.raises(BlockOwnershipError, match="without being owned"):
        pool.free([got[0]])  # double free
    with pytest.raises(BlockOwnershipError):
        pool.free([pool.num_blocks - 1])  # never-allocated stray id
    with pytest.raises(ValueError, match="trash"):
        pool.free([0])
    # the guard kept the free list clean: every id grants exactly once
    grant = pool.alloc(pool.usable_blocks)
    assert sorted(grant) == list(range(1, pool.num_blocks))


def test_pool_refcount_shares_and_returns_at_zero():
    pool = _pool()
    (b,) = pool.alloc(1)
    pool.ref(b)  # a second claimant
    assert pool.refcount(b) == 2
    pool.free([b])
    assert pool.refcount(b) == 1, "refcount>0: not freed"
    assert pool.free_blocks == pool.usable_blocks - 1
    pool.free([b])
    assert pool.refcount(b) == 0
    assert pool.free_blocks == pool.usable_blocks
    with pytest.raises(BlockOwnershipError):
        pool.ref(b)  # neither owned nor cached anymore


def test_pool_published_blocks_park_on_lru_and_evict_under_pressure():
    """A refcount-0 PUBLISHED block is cached (claimable), not freed;
    ``alloc`` under pressure evicts the LRU cached block and tells
    the index via ``on_evict`` — retention never starves admission."""
    pool = _pool(num_blocks=5)  # 4 usable
    a = pool.alloc(4)
    for b in a[:2]:
        pool.publish(b)
    pool.free(a)
    assert pool.cached_blocks == 2 and pool.free_blocks == 4
    pool.ref(a[0])  # revive from the cache: refcount 0 -> 1
    assert pool.refcount(a[0]) == 1 and pool.cached_blocks == 1
    pool.free([a[0]])  # back to the cache (still published)
    evicted = []
    pool.on_evict = evicted.append
    grant = pool.alloc(4)  # needs both cached blocks evicted
    assert grant is not None and len(grant) == 4
    assert sorted(evicted) == sorted(a[:2])
    assert pool.evictions == 2
    pool.free(grant)


def test_pool_alloc_still_all_or_nothing_with_cache():
    pool = _pool(num_blocks=5)
    a = pool.alloc(3)
    pool.publish(a[0])
    pool.free(a)  # 2 to the free list, 1 parks cached; 1 never left
    assert pool.free_blocks == 4
    assert pool.alloc(5) is None, "over capacity: no partial grant"
    assert pool.free_blocks == 4, "a refused alloc evicts nothing"


# -- chain hashing and the index ---------------------------------------------


def test_chain_hashes_name_the_whole_prefix():
    p = np.arange(40, dtype=np.int32)
    hs = chain_hashes(p, 16)
    assert len(hs) == 2, "the trailing partial block is never hashed"
    q = p.copy()
    q[0] = 99  # perturb block 0: EVERY downstream hash must change
    ht = chain_hashes(q, 16)
    assert hs[0] != ht[0] and hs[1] != ht[1]
    r = p.copy()
    r[20] = 99  # perturb block 1 only: block 0's hash is unchanged
    hr = chain_hashes(r, 16)
    assert hs[0] == hr[0] and hs[1] != hr[1]


def test_prefix_claim_longest_run_and_divergence():
    with telemetry.scoped():
        pool = _pool(num_blocks=12)
        cache = PrefixCache(pool, 16)
        prompt = np.arange(100, 170, dtype=np.int32)  # 70 tokens
        blocks = pool.alloc(5)
        assert cache.publish(prompt, blocks) == 4, "4 full blocks indexed"
        pool.free(blocks)

        # same prompt: claims all 4 full blocks, final 6 tokens cold
        run, skip = cache.claim(prompt)
        assert run == blocks[:4] and skip == 64
        assert all(pool.refcount(b) == 1 for b in run)
        pool.free(run)

        # divergence inside block 2: only blocks 0-1 match
        div = prompt.copy()
        div[40] += 1
        run, skip = cache.claim(div)
        assert run == blocks[:2] and skip == 32
        pool.free(run)

        # block-aligned prompt: the LAST block stays cold so the final
        # chunk still produces the first token
        run, skip = cache.claim(prompt[:64])
        assert len(run) == 3 and skip == 48
        pool.free(run)

        # under one block: uncacheable, not a miss
        misses0 = cache.stats["misses"]
        assert cache.claim(prompt[:9]) == ([], 0)
        assert cache.stats["misses"] == misses0


def test_prefix_eviction_drops_index_entries():
    with telemetry.scoped():
        pool = _pool(num_blocks=4)  # 3 usable
        cache = PrefixCache(pool, 16)
        prompt = np.arange(33, dtype=np.int32)
        blocks = pool.alloc(3)
        cache.publish(prompt, blocks)
        pool.free(blocks)
        assert len(cache) == 2 and pool.cached_blocks == 2
        grant = pool.alloc(3)  # evicts both cached blocks
        assert grant is not None
        assert len(cache) == 0, "on_evict dropped the index entries"
        assert cache.claim(prompt) == ([], 0)
        assert cache.stats["evictions"] == 2
        pool.free(grant)


def test_prefix_claim_revalidates_block_regranted_mid_claim():
    """TOCTOU regression: between ``claim``'s lock-free index read and
    ``pool.ref``, a concurrent alloc (migration-receiver grant) can
    evict the refcount-0 cached block AND re-grant it to a new
    sequence within one lock hold — ``ref`` then lands on a foreign
    private block.  ``claim`` must re-validate ownership via
    ``_by_block`` after the ref and drop the share on mismatch."""
    with telemetry.scoped():
        pool = _pool(num_blocks=4)  # 3 usable
        cache = PrefixCache(pool, 16)
        prompt = np.arange(33, dtype=np.int32)
        blocks = pool.alloc(3)
        cache.publish(prompt, blocks)
        pool.free(blocks)
        assert pool.cached_blocks == 2
        # Interpose on ref to run the racing alloc at the worst
        # moment: after claim read the entry, before the ref lands.
        real_ref = pool.ref
        foreign = []

        def racing_ref(b):
            if not foreign:
                foreign.extend(pool.alloc(2))  # evicts + re-grants both
            real_ref(b)

        pool.ref = racing_ref
        try:
            run, skip = cache.claim(prompt)
        finally:
            pool.ref = real_ref
        assert run == [] and skip == 0, "foreign block must not be claimed"
        # the racing sequence's grant is untouched: still sole owner
        assert blocks[0] in foreign, "the contended block was re-granted"
        assert all(pool.refcount(b) == 1 for b in foreign)
        pool.free(foreign)


def test_pool_reset_invalidates_index_without_counting_evictions():
    """``reset()`` (engine re-warm) drops the index via the dedicated
    ``on_reset`` hook — NOT ``on_evict`` — so eviction stats keep
    meaning capacity pressure only."""
    with telemetry.scoped():
        pool = _pool(num_blocks=6)
        cache = PrefixCache(pool, 16)
        prompt = np.arange(50, dtype=np.int32)
        blocks = pool.alloc(3)
        cache.publish(prompt, blocks)
        pool.free(blocks)
        assert len(cache) == 3 and pool.cached_blocks == 3
        pool.reset()
        assert len(cache) == 0, "on_reset dropped the index"
        assert cache.claim(prompt) == ([], 0)
        assert cache.stats["evictions"] == 0, "a re-warm is not an eviction"
        assert pool.evictions == 0
        assert pool.free_blocks == pool.usable_blocks


def test_prefix_rekey_invalidates_atomically():
    with telemetry.scoped() as (_, rec):
        pool = _pool(num_blocks=8)
        cache = PrefixCache(pool, 16)
        assert cache.rekey((0, 0)) is False, "first bind: nothing to drop"
        prompt = np.arange(50, dtype=np.int32)
        blocks = pool.alloc(3)
        cache.publish(prompt, blocks)
        pool.free(blocks)
        assert cache.rekey((0, 0)) is False, "same key: index survives"
        assert len(cache) == 3  # 50 tokens cover 3 full 16-token blocks
        assert cache.rekey((1, 0)) is True, "new generation: invalidated"
        assert len(cache) == 0 and pool.cached_blocks == 0
        assert pool.free_blocks == pool.usable_blocks
        assert cache.claim(prompt) == ([], 0), "zero cross-generation reuse"
        kinds = [e.kind for e in rec.events()]
        assert "serve.prefix" in kinds


# -- end-to-end through the batcher ------------------------------------------


@pytest.mark.parametrize("name", ["transformer_lm", "moe_lm",
                                  "longcontext_lm"])
def test_warm_admission_bit_identical_per_family(name):
    """ISSUE 17 acceptance: reused-block decode is bit-identical to
    cold prefill, per LM family, under one seed — and the warm
    admission demonstrably skipped to the first cold block."""
    model = get_model(name, tiny=True)
    store = HostDRAMStore()
    store.save_async(_lm_state(model, 1, 1), generation=0)
    store.wait()
    engine = DecodeEngine(
        model, store, devices=jax.devices()[:1], max_batch=1,
        max_seqs=4, block_tokens=16, max_chunk_tokens=16,
    )
    assert engine.load()
    engine.warm()
    with telemetry.scoped():
        batcher = TokenContinuousBatcher(engine, refresh=False).start()
        try:
            rng = np.random.RandomState(1)
            prompt = model.synth_batch(rng, 1)["tokens"][0, :40]
            cold_t, cold_m = _gen(batcher, prompt)
            warm_t, warm_m = _gen(batcher, prompt)
            assert cold_m["reused_blocks"] == 0
            assert warm_m["reused_blocks"] == 2, "(40-1)//16 blocks claimed"
            assert warm_m["prefill_chunks"] < cold_m["prefill_chunks"]
            assert warm_t == cold_t
            w = engine.current_weights()
            ref = _reference_decode(model, w.params, list(prompt), 4, engine)
            assert warm_t == ref, "reused-block decode impure vs reference"
        finally:
            batcher.stop()
    assert engine.pool.used_blocks == 0


def test_divergent_tail_reuses_shared_run_only(prefix_lm):
    model, _, engine = prefix_lm
    with telemetry.scoped():
        batcher = TokenContinuousBatcher(engine, refresh=False).start()
        try:
            rng = np.random.RandomState(2)
            base = model.synth_batch(rng, 1)["tokens"][0, :48]
            tail = model.synth_batch(rng, 1)["tokens"][0, :10]
            _gen(batcher, base)
            div = list(base[:32]) + list(tail)
            toks, meta = _gen(batcher, div)
            assert meta["reused_blocks"] == 2, "shared 32-token run only"
            w = engine.current_weights()
            assert toks == _reference_decode(model, w.params, div, 4, engine)
        finally:
            batcher.stop()
    assert engine.pool.used_blocks == 0


def test_hot_swap_invalidates_pool_zero_cross_generation_reuse():
    """A swap between two same-prompt admissions must invalidate the
    index: the post-swap admission reuses NOTHING and its tokens are
    the new generation's pure decode."""
    model, store, engine = _build_engine()
    with telemetry.scoped():
        batcher = TokenContinuousBatcher(engine).start()
        try:
            rng = np.random.RandomState(3)
            prompt = model.synth_batch(rng, 1)["tokens"][0, :40]
            old_t, old_m = _gen(batcher, prompt)
            assert old_m["weights_step"] == 1
            store.save_async(_lm_state(model, 2, 2), generation=1)
            store.wait()
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                toks, meta = _gen(batcher, prompt)
                if meta["weights_step"] == 2:
                    break
                time.sleep(0.01)
            assert meta["weights_step"] == 2, "swap never observed"
            assert meta["reused_blocks"] == 0, "cross-generation reuse!"
            assert batcher.prefix.stats["invalidations"] >= 1
            w = engine.current_weights()
            ref = _reference_decode(model, w.params, list(prompt), 4, engine)
            assert toks == ref, "post-swap tokens impure"
        finally:
            batcher.stop()
    assert engine.pool.used_blocks == 0


def test_chaos_hash_skew_rejects_reuse_correctly(prefix_lm):
    """chaos[serve.prefix.hash.skew]: the verification path treats the
    lookup as colliding — a miss and a cold prefill, never wrong K/V."""
    model, _, engine = prefix_lm
    chaos = FaultSchedule(0, [FaultEvent(0, "serve.prefix.hash.skew")])
    chaos.advance(0)
    with telemetry.scoped() as (_, rec):
        batcher = TokenContinuousBatcher(
            engine, refresh=False, chaos=chaos
        ).start()
        try:
            rng = np.random.RandomState(4)
            prompt = model.synth_batch(rng, 1)["tokens"][0, :40]
            _gen(batcher, prompt)
            toks, meta = _gen(batcher, prompt)  # the skewed lookup
            assert meta["reused_blocks"] == 0
            assert batcher.prefix.stats["skew_rejected"] == 1
            toks2, meta2 = _gen(batcher, prompt)  # chaos consumed: hits
            assert meta2["reused_blocks"] == 2
            assert toks2 == toks
            evs = [e for e in rec.events() if e.kind == "serve.prefix"]
            assert any(
                e.data.get("outcome") == "hash_skew_rejected" for e in evs
            )
        finally:
            batcher.stop()
    assert chaos.pending() == []
    assert engine.pool.used_blocks == 0


def test_chaos_forced_eviction_refills_cold(prefix_lm):
    """chaos[serve.prefix.evicted]: cached blocks force-evict; the
    next same-prefix admission prefills the evicted run cold and
    still emits identical tokens."""
    model, _, engine = prefix_lm
    chaos = FaultSchedule(0, [FaultEvent(1, "serve.prefix.evicted", 99)])
    with telemetry.scoped() as (_, rec):
        batcher = TokenContinuousBatcher(
            engine, refresh=False, chaos=chaos
        ).start()
        try:
            rng = np.random.RandomState(5)
            prompt = model.synth_batch(rng, 1)["tokens"][0, :40]
            toks, _ = _gen(batcher, prompt)
            chaos.advance(1)
            # The worker runs chaos_tick at the top of the iteration
            # that admits the next request — the eviction lands BEFORE
            # its lookup, deterministically.
            toks2, meta2 = _gen(batcher, prompt)
            assert chaos.pending() == []
            assert meta2["reused_blocks"] == 0, "evicted: nothing to claim"
            assert toks2 == toks
            assert batcher.prefix.stats["evictions"] >= 1
            evs = [e for e in rec.events() if e.kind == "serve.prefix"]
            assert any(
                e.data.get("outcome") == "chaos_evicted" for e in evs
            )
        finally:
            batcher.stop()
    assert engine.pool.used_blocks == 0


def test_prefix_disabled_is_the_cold_baseline(prefix_lm):
    model, _, engine = prefix_lm
    with telemetry.scoped():
        batcher = TokenContinuousBatcher(
            engine, refresh=False, prefix_cache=False
        ).start()
        try:
            assert batcher.prefix is None
            rng = np.random.RandomState(6)
            prompt = model.synth_batch(rng, 1)["tokens"][0, :40]
            t1, m1 = _gen(batcher, prompt)
            t2, m2 = _gen(batcher, prompt)
            assert m2["reused_blocks"] == 0
            assert m2["prefill_chunks"] == m1["prefill_chunks"]
            assert t1 == t2
        finally:
            batcher.stop()
    assert engine.pool.used_blocks == 0


def test_prefix_pressure_never_starves_admission():
    """Fill the whole pool with cached prefix runs, then admit a
    prompt needing more blocks than the raw free list holds: the LRU
    eviction inside ``alloc`` must make room transparently."""
    model, store, engine = _build_engine(num_blocks=9)  # 8 usable
    with telemetry.scoped():
        batcher = TokenContinuousBatcher(engine, refresh=False).start()
        try:
            rng = np.random.RandomState(7)
            for i in range(3):  # 3 finished 2-block runs stay cached
                p = model.synth_batch(rng, 1)["tokens"][0, :33]
                _gen(batcher, p, n=2)
            assert engine.pool.cached_blocks >= 4
            long = model.synth_batch(rng, 1)["tokens"][0, :60]
            toks, meta = _gen(batcher, long, n=2)
            assert len(toks) == 2
            assert batcher.prefix.stats["evictions"] >= 1
        finally:
            batcher.stop()
    assert engine.pool.used_blocks == 0


# -- the seeded prefix soak ---------------------------------------------------


def _wait(cond, timeout=30.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.005)
    raise AssertionError(f"wait timed out: {what}")


def _run_prefix_soak(seed: int):
    """Mixed shared-prefix/divergent traffic across 2 hot swaps, with
    a forced hash skew riding round-1 traffic and a forced eviction
    riding round-3 traffic.  The worker only runs between admissions
    it has work for, and submission is sequential, so every trip's
    consumption point — and therefore every hit/miss — is
    deterministic.  Returns what must be bit-identical across
    same-seed runs."""
    events = [
        FaultEvent(1, "serve.prefix.hash.skew"),
        FaultEvent(2, "serve.prefix.evicted", 2),
    ]
    with telemetry.scoped() as (_, rec):
        schedule = FaultSchedule(seed, events)
        model, store, engine = _build_engine()
        batcher = TokenContinuousBatcher(engine, chaos=schedule).start()
        rng = np.random.RandomState(seed % 2**31)
        #: ONE shared system prefix across all three generations — the
        #: post-swap rounds resubmit it, so any nonzero reuse on their
        #: first admission would be cross-generation reuse.
        shared = model.synth_batch(rng, 1)["tokens"][0, :48]
        log = []
        try:
            for step in (1, 2, 3):
                if step > 1:  # hot swap (>= 2 over the soak)
                    store.save_async(
                        _lm_state(model, step, step), generation=step - 1
                    )
                    store.wait()
                    # The idle worker only notices the swap at its
                    # next admission — the round's first _gen below
                    # deterministically binds the new generation
                    # (refresh runs before the admission's lookup),
                    # so the post-swap asserts live after i == 0.
                for i in range(5):
                    if step == 1 and i == 2:
                        # Forced hash skew: THIS admission's claim (a
                        # warm shared-prefix one) must reject its
                        # match and prefill cold.
                        schedule.advance(1)
                    if step == 3 and i == 2:
                        # Forced eviction: the worker's chaos_tick at
                        # the top of THIS admission's iteration evicts
                        # the 2 LRU cached shared blocks before the
                        # lookup — the chain breaks at block 0 and the
                        # run re-prefills cold.
                        schedule.advance(2)
                    if i < 4:  # shared prefix, divergent tails
                        tail = model.synth_batch(rng, 1)["tokens"][0, :8]
                        prompt = list(shared) + list(tail)
                    else:  # fully divergent short prompt
                        prompt = list(
                            model.synth_batch(rng, 1)["tokens"][0, :24]
                        )
                    toks, meta = _gen(batcher, prompt, n=4)
                    assert meta["weights_step"] == step
                    if i == 0:
                        # The engine generation is a 1-based swap
                        # counter: first load binds (1, 0), each swap
                        # advances it by one — and each swap's rekey
                        # invalidated the whole index.
                        assert batcher.prefix.key == (step, 0)
                        assert (
                            batcher.prefix.stats["invalidations"]
                            == step - 1
                        )
                    w = engine.current_weights()
                    ref = _reference_decode(
                        model, w.params, prompt, 4, engine
                    )
                    assert toks == ref, "soak tokens diverge from cold ref"
                    log.append(
                        (step, i, meta["reused_blocks"], tuple(toks))
                    )
                # first admission of a post-swap round resubmitted the
                # SAME shared prefix the old generation published:
                assert log[-5][2] == 0, "cross-generation reuse"
        finally:
            batcher.stop()
        assert schedule.pending() == []
        assert engine.pool.used_blocks == 0
        stats = dict(batcher.prefix.stats)
        assert stats["invalidations"] == 2
        assert stats["skew_rejected"] == 1
        assert stats["evictions"] == 2
        # hits: i1/i3 every round + i2 in round 2 (round 1's i2 is the
        # skew, round 3's follows the forced eviction) = 7 admissions,
        # 3 shared blocks each
        assert stats["hits"] == 7 and stats["blocks_reused"] == 21
        return {"digest": rec.digest(), "log": log, "stats": stats}


def test_prefix_soak_bit_reproducible():
    """ISSUE 17 satellite: the seeded prefix soak — 2 hot swaps each
    invalidate the pool (zero cross-generation reuse), every sequence
    equals its cold-prefill reference, and two same-seed runs journal
    bit-identically (recorder digest + the structured log)."""
    r1 = _run_prefix_soak(seed=1709)
    r2 = _run_prefix_soak(seed=1709)
    assert r1["log"] == r2["log"], "soak logs diverged across reruns"
    assert r1["digest"] == r2["digest"], "journals diverged across reruns"
    assert r1["stats"] == r2["stats"]


# -- edl metrics: the operator view -------------------------------------------


def test_metrics_cli_prints_prefix_section(capsys):
    """ISSUE 17 satellite: `edl metrics` serving section surfaces the
    prefix-cache counters — hits, hit ratio, blocks reused,
    evictions."""
    from edl_tpu.cli import main
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.telemetry import MetricsRegistry

    coord = LocalCoordinator(target_world=1, max_world=2)
    coord.register("serve-0")
    reg = MetricsRegistry()
    reg.counter("edl_serve_requests_total").inc(3, status="ok")
    reg.counter("edl_serve_prefix_hits_total").inc(9)
    reg.counter("edl_serve_prefix_misses_total").inc(1)
    reg.counter("edl_serve_prefix_blocks_reused_total").inc(27)
    reg.counter("edl_serve_prefix_evictions_total").inc(2)
    reg.gauge("edl_serve_prefix_hit_ratio").set(0.9)
    coord.report_telemetry("serve-0", snapshot=reg.snapshot(), seq=1)
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start(
        evict=False
    )
    try:
        assert main(["metrics", f"127.0.0.1:{server.port}"]) == 0
        out = capsys.readouterr().out
        assert "prefix_hits" in out and "9" in out
        assert "prefix_hit_ratio" in out and "0.9" in out
        assert "prefix_blocks_reused" in out and "27" in out
        assert "prefix_evictions" in out and "2" in out
    finally:
        server.stop()
