"""Elastic protocol tests: coordinator membership/generations, graceful
resize with loss continuity, failure recovery with deterministic replay.

This is the capability the reference system imposes on its (external)
runtime — "tolerate membership churn at any time" (SURVEY.md §0) — and
the part SURVEY.md §7.4 calls the hard part: resize correctness with
reproducible loss continuation.
"""

import numpy as np
import optax
import pytest

from edl_tpu.models import get_model
from edl_tpu.runtime import ShardedDataIterator
from edl_tpu.runtime.coordinator import LocalCoordinator
from edl_tpu.runtime.data import synthetic_dataset
from edl_tpu.runtime.elastic import ElasticTrainer


def make_world(target_world=2, n_trainers=2, ckpt_interval=5, seed=0):
    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 512, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
    coord = LocalCoordinator(target_world=target_world, max_world=8)
    for i in range(n_trainers):
        coord.register(f"tr{i}")
    et = ElasticTrainer(
        model,
        optax.adam(1e-2),
        it,
        coord,
        checkpoint_interval=ckpt_interval,
        seed=seed,
    )
    return et, coord


# ---- coordinator unit tests ----------------------------------------------


def test_coordinator_membership_and_generations():
    c = LocalCoordinator(target_world=2)
    p1 = c.register("a")
    assert p1.world_size == 1 and p1.members == ("a",)
    p2 = c.register("b")
    assert p2.world_size == 2 and p2.members == ("a", "b")
    assert p2.generation > p1.generation
    # standby: target is 2, a third member waits in the wings
    p3 = c.register("c")
    assert p3.world_size == 2 and p3.members == ("a", "b")
    # leave of an active member promotes the standby
    c.deregister("a")
    p4 = c.plan()
    assert p4.members == ("b", "c")
    assert p4.world_size == 2


def test_coordinator_retarget():
    c = LocalCoordinator(target_world=4)
    for t in "abcd":
        c.register(t)
    g = c.plan().generation
    c.set_target_world(2)
    p = c.plan()
    assert p.world_size == 2 and p.generation > g
    c.set_target_world(2)  # no-op must not bump generation
    assert c.plan().generation == p.generation
    with pytest.raises(ValueError):
        c.set_target_world(0)


def test_coordinator_quantizes_to_legal_world_sizes():
    """3 live members with legal sizes {1,2,4} must plan world=2, never 3
    (global batch divisibility, SURVEY.md §7.4 slice quantization)."""
    c = LocalCoordinator(target_world=4, legal_sizes=[1, 2, 4])
    for t in "abc":
        c.register(t)
    p = c.plan()
    assert p.world_size == 2 and p.members == ("a", "b")
    c.register("d")
    assert c.plan().world_size == 4
    # No legal size fits 0 members... and with legal floor above members:
    c2 = LocalCoordinator(target_world=4, legal_sizes=[4])
    c2.register("x")
    assert c2.plan().world_size == 0  # hold at barrier, don't crash


def test_coordinator_heartbeat_eviction():
    fake_now = [0.0]
    c = LocalCoordinator(target_world=2, heartbeat_timeout=5.0, clock=lambda: fake_now[0])
    c.register("a")
    c.register("b")
    fake_now[0] = 3.0
    c.heartbeat("a")
    fake_now[0] = 7.0  # b last beat at 0 -> dead; a beat at 3 -> alive
    dead = c.evict_dead()
    assert dead == ["b"]
    assert c.plan().members == ("a",)
    with pytest.raises(KeyError):
        c.heartbeat("b")


# ---- elastic training ------------------------------------------------------


def test_elastic_run_fresh_start():
    et, coord = make_world(target_world=2, n_trainers=2)
    hist = et.run(10)
    assert [r.step for r in hist] == list(range(10))
    assert all(r.world_size == 2 for r in hist)
    assert len(et.resize_events) == 1  # initial mesh formation
    assert not et.resize_events[0].graceful  # fresh init, nothing restored


def test_graceful_resize_loss_continuity():
    """Scale 2 -> 4 mid-run; trajectory must be IDENTICAL to never
    resizing (sync DP + fixed global batch + deterministic data)."""
    # Uninterrupted reference run at world=2.
    ref, _ = make_world(target_world=2, n_trainers=2)
    ref_hist = ref.run(20)

    et, coord = make_world(target_world=2, n_trainers=4)
    et.run(10)
    coord.set_target_world(4)  # the autoscaler's Parallelism PUT analog
    hist = et.run(20)

    assert hist[9].world_size == 2 and hist[10].world_size == 4
    # No steps lost or duplicated at the graceful boundary.
    assert [r.step for r in hist] == list(range(20))
    np.testing.assert_allclose(
        [r.loss for r in hist], [r.loss for r in ref_hist], rtol=1e-5
    )
    # Two resizes: initial formation + the growth.
    assert len(et.resize_events) == 2
    grow = et.resize_events[1]
    assert grow.graceful and grow.world_size == 4 and grow.replayed_steps == 0


def test_scale_down_and_back_up_reuses_compiled_trainer():
    et, coord = make_world(target_world=4, n_trainers=4)
    et.run(5)
    coord.set_target_world(2)
    et.run(10)
    coord.set_target_world(4)
    et.run(15)
    assert [r.step for r in et.history] == list(range(15))
    # Trainer cache: worlds 4 and 2 compiled once each.
    assert sorted(et._trainers) == [2, 4]


def test_failure_recovery_replays_deterministically():
    """Kill the world mid-run; recovery restores the last async
    checkpoint and replays — final trajectory identical to a run that
    never failed."""
    ref, _ = make_world(target_world=2, n_trainers=2, ckpt_interval=5)
    ref_hist = ref.run(20)

    et, coord = make_world(target_world=2, n_trainers=2, ckpt_interval=5)
    et.run(13)  # last checkpoint at step 10
    et.store.wait()
    et.inject_failure()  # device state gone
    # Failure detection: trainer 1 dies with the host; coordinator evicts
    # it and re-plans (shrink to 1).
    coord.deregister("tr1")
    hist = et.run(20)

    ev = et.resize_events[-1]
    assert not ev.graceful
    assert ev.restored_step == 10
    assert ev.replayed_steps == 3  # steps 10,11,12 re-run
    # Steps replay: history contains 10..12 twice, identical losses.
    steps = [r.step for r in et.history]
    assert steps == list(range(13)) + list(range(10, 20))
    final = {r.step: r.loss for r in et.history[13:]}
    ref_final = {r.step: r.loss for r in ref_hist if r.step >= 10}
    for s in ref_final:
        np.testing.assert_allclose(final[s], ref_final[s], rtol=1e-5)


def test_precompile_makes_resize_cheap():
    et, coord = make_world(target_world=2, n_trainers=4)
    et.precompile([1, 2, 4])
    assert sorted(et._trainers) == [1, 2, 4]
    et.run(5)
    coord.set_target_world(4)
    et.run(8)
    # The growth resize must not have compiled anything new.
    assert sorted(et._trainers) == [1, 2, 4]
    grow = et.resize_events[-1]
    assert grow.seconds < 5.0  # no JIT in the window (CPU headroom-safe bound)


def test_mnist_elastic_smoke():
    """MNIST ConvNet elastic min=1 max=4 — benchmark config 2 shape."""
    model = get_model("mnist")
    ds = synthetic_dataset(model.synth_batch, 256, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=32, seed=0)
    coord = LocalCoordinator(target_world=1, max_world=4)
    coord.register("tr0")
    et = ElasticTrainer(model, optax.adam(1e-3), it, coord, checkpoint_interval=4)
    et.run(6)
    for t in ("tr1", "tr2", "tr3"):
        coord.register(t)
    coord.set_target_world(4)
    hist = et.run(12)
    assert hist[-1].world_size == 4
    assert np.isfinite(hist[-1].loss)


def test_coordinator_max_world_enforced():
    """max_world caps both retargeting and plan size (review finding:
    the cap existed but was unenforced)."""
    c = LocalCoordinator(target_world=1, max_world=2)
    for t in "abcd":
        c.register(t)
    c.set_target_world(100)  # clamped to max_world
    assert c.plan().world_size == 2


def test_hold_at_barrier_until_membership_recovers():
    """With legal_sizes=[2] and one member dead, there is no formable
    world: run() must hold (not step on the stale mesh), then resume
    when membership recovers (review finding: it previously kept
    stepping at the old generation)."""
    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 512, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
    coord = LocalCoordinator(target_world=2, max_world=2, legal_sizes=[2])
    coord.register("tr0")
    coord.register("tr1")
    et = ElasticTrainer(model, optax.adam(1e-2), it, coord, checkpoint_interval=5)
    et.run(6)
    steps_before = len(et.history)

    coord.deregister("tr1")  # world can no longer form
    assert coord.plan().world_size == 0
    et.barrier_timeout = 0.3
    with pytest.raises(RuntimeError, match="barrier"):
        et.run(20)
    assert len(et.history) == steps_before, "must not step while holding"

    coord.register("tr1")  # membership recovers
    et.barrier_timeout = 300.0
    et.run(12)
    assert int(et.state.step) == 12


def test_heartbeats_keep_members_alive_under_eviction():
    """The elastic runtime heartbeats its members, so an eviction sweep
    reaps only trainers that actually stopped (review finding: the
    deployed path previously never heartbeat at all)."""
    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 512, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
    coord = LocalCoordinator(target_world=2, max_world=2, heartbeat_timeout=0.2)
    coord.register("tr0")
    coord.register("tr1")
    et = ElasticTrainer(model, optax.adam(1e-2), it, coord, checkpoint_interval=5)
    et.heartbeat_ids = ["tr0", "tr1"]
    et.heartbeat_interval = 0.0  # every step
    et.run(5)
    import time as _t

    _t.sleep(0.3)  # past the timeout with no steps -> stale...
    et.run(10)  # ...but stepping heartbeats again before the sweep
    assert coord.evict_dead() == []
    assert sorted(coord.members()) == ["tr0", "tr1"]

    # a member that is NOT heartbeated gets reaped
    et.heartbeat_ids = ["tr0"]
    _t.sleep(0.3)
    et.run(12)
    assert coord.evict_dead() == ["tr1"]


def test_step_profiler_captures_trace(tmp_path, monkeypatch):
    """EDL_PROFILE_DIR triggers a bounded jax.profiler trace of the hot
    loop (SURVEY.md §5.1 — tracing the reference never had)."""
    import os

    import optax

    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
    from edl_tpu.runtime.elastic import ElasticTrainer

    monkeypatch.setenv("EDL_PROFILE_DIR", str(tmp_path / "trace"))
    monkeypatch.setenv("EDL_PROFILE_STEPS", "3")
    model = get_model("fit_a_line")
    coord = LocalCoordinator(target_world=1, max_world=1)
    coord.register("t0")
    et = ElasticTrainer(
        model,
        optax.sgd(0.01),
        ShardedDataIterator(
            synthetic_dataset(model.synth_batch, 64), global_batch_size=8
        ),
        coord,
        checkpoint_interval=0,
    )
    assert et.profiler.enabled
    et.run(5)
    produced = []
    for root, _dirs, files in os.walk(tmp_path / "trace"):
        produced += files
    assert any(f.endswith(".xplane.pb") for f in produced), produced


# ---- broken-world recovery (ungraceful peer death, in-process) ------------


def _sabotaged_world(devices8):
    """World-2 trainer with a world_builder set (the deployed-multipod
    marker that arms the broken-world survival path) and its compiled
    step sabotaged to raise like a mid-collective peer death."""
    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 512, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
    coord = LocalCoordinator(target_world=2, max_world=2)
    coord.register("a")
    coord.register("b")
    et = ElasticTrainer(
        model,
        optax.adam(1e-2),
        it,
        coord,
        devices=devices8[:2],
        checkpoint_interval=2,
        world_builder=lambda plan: devices8[:2],
    )
    et.heartbeat_ids = ["a", "b"]
    assert et.maybe_resize()  # form generation 1, compile the trainer

    def boom(state, batch):
        raise ValueError("simulated collective failure (peer died)")

    et._trainers[2].step = boom
    return et, coord


def test_broken_world_holds_until_generation_bump(devices8):
    """With no membership change (nothing evicted), a broken world must
    hold at the barrier — not crash, not spin on the dead plan — and
    eventually surface the hold as the barrier-timeout error."""
    et, coord = _sabotaged_world(devices8)
    et.barrier_timeout = 1.0
    et.barrier_poll_interval = 0.01
    with pytest.raises(RuntimeError, match="resize barrier"):
        et.run(int(et.state.step) + 3)


def test_broken_world_recovers_on_generation_bump(devices8):
    """After the failure, a generation bump (the coordinator evicting /
    re-admitting a member) releases the hold; the rebuilt world resumes
    from the last checkpoint and finishes the run."""
    import threading

    et, coord = _sabotaged_world(devices8)
    et.barrier_poll_interval = 0.01
    target = int(et.state.step) + 4

    # Bump the generation shortly after the failure lands (the multipod
    # analog: the lease reaper evicts the SIGKILLed pod).  The rebuilt
    # generation compiles a fresh (unsabotaged) trainer.
    threading.Timer(
        0.3, lambda: (coord.deregister("b"), coord.register("b"))
    ).start()
    history = et.run(target)
    assert int(et.state.step) >= target
    assert et._world_failures == 0  # reset by the completed steps
    # No step ever completed in the sabotaged generation 1: everything
    # recorded ran in a rebuilt (bumped) generation.
    gens = {r.generation for r in history}
    assert min(gens) > 1, f"expected only rebuilt generations, saw {gens}"


def test_deterministic_step_failure_exhausts_cap(devices8):
    """ADVICE r3: a deterministic error recurring at ONE step (e.g. a
    poisoned checkpoint path) must exhaust the broken-world cap and
    surface — the replayed interval's completed steps must NOT re-arm
    it (counter resets only on progress PAST the failing step), or the
    trainer loops teardown/replay forever pinned at that step."""
    import threading

    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 512, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
    coord = LocalCoordinator(target_world=2, max_world=2)
    coord.register("a")
    coord.register("b")
    et = ElasticTrainer(
        model,
        optax.adam(1e-2),
        it,
        coord,
        devices=devices8[:2],
        checkpoint_interval=2,
        world_builder=lambda plan: devices8[:2],
    )
    et.heartbeat_ids = ["a", "b"]
    et.barrier_poll_interval = 0.01

    FAIL_AT = 5  # odd: the restore point (ckpt step 4) forces a replay
    orig_trainer_for = et._trainer_for

    def poisoned_trainer_for(ws):
        tr = orig_trainer_for(ws)
        if not getattr(tr, "_poisoned", False):
            orig_step = tr.step

            def step(state, batch):
                if int(state.step) == FAIL_AT:
                    raise ValueError("deterministic failure at step 5")
                return orig_step(state, batch)

            tr.step = step
            tr._poisoned = True
        return tr

    et._trainer_for = poisoned_trainer_for

    # The reaper analog: keep bumping the generation so every broken
    # world gets re-admitted (otherwise the hold would mask the loop).
    stop = threading.Event()

    def bumper():
        while not stop.wait(0.25):
            coord.deregister("b")
            coord.register("b")

    th = threading.Thread(target=bumper, daemon=True)
    th.start()
    try:
        with pytest.raises(ValueError, match="deterministic failure"):
            et.run(FAIL_AT + 3)
    finally:
        stop.set()
        th.join(timeout=5)
    # The cap was exhausted by the SAME step failing repeatedly, even
    # though the replayed step 4 completed between failures.
    assert et._world_failures >= et.max_world_failures
    assert et._last_failed_step == FAIL_AT


def test_cold_start_restores_from_durable_dir(tmp_path):
    """Process restart with empty DRAM: the resize path must cold-load
    the spilled checkpoint (elastic._latest_or_disk) instead of
    re-initializing at step 0 (VERDICT r4 #2, single-process form)."""
    from edl_tpu.checkpoint import HostDRAMStore

    spill = str(tmp_path / "durable")

    def world(store):
        model = get_model("fit_a_line")
        ds = synthetic_dataset(model.synth_batch, 512, seed=0)
        it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
        coord = LocalCoordinator(target_world=2, max_world=8)
        for i in range(2):
            coord.register(f"tr{i}")
        return ElasticTrainer(
            model, optax.adam(1e-2), it, coord,
            store=store, checkpoint_interval=5,
        )

    first = world(HostDRAMStore(spill_dir=spill))
    first.run(12)
    first.store.wait()  # interval saves at steps 5 and 10 spilled

    # "Restart": fresh trainer, fresh (empty) DRAM store, same dir.
    second = world(HostDRAMStore(spill_dir=spill))
    hist = second.run(15)
    ev = second.resize_events[0]
    assert ev.restored_step == 10, ev
    assert ev.restore_source == "local"
    # Only the post-checkpoint steps run; nothing replays from 0.
    assert [r.step for r in hist] == list(range(10, 15))

    # A THIRD start now sees the second run's newer spill (step 15).
    second.store.wait()  # let the step-15 spill land (as for `first`)
    third = world(HostDRAMStore(spill_dir=spill))
    third.run(16)
    assert third.resize_events[0].restored_step == 15


# ---- zero-stall resize: AOT warming, prewarm hints, compile accounting ----


def test_coordinator_prewarm_hint_is_advisory():
    """set_prewarm rides the plan WITHOUT bumping the generation (a
    hint must never push trainers through a resize barrier), clamps to
    max_world, and survives plan rebuilds."""
    c = LocalCoordinator(target_world=2, max_world=4)
    c.register("a")
    c.register("b")
    g = c.plan().generation
    c.set_prewarm(3)
    p = c.plan()
    assert p.generation == g and p.prewarm == 3
    c.set_prewarm(99)  # clamped like set_target_world
    assert c.plan().prewarm == 4
    with pytest.raises(ValueError):
        c.set_prewarm(-1)
    c.deregister("b")  # an ACTIVE-world change rebuilds the plan...
    p2 = c.plan()
    assert p2.generation > g and p2.prewarm == 4  # ...hint carried over


def test_precompile_is_allocation_free(monkeypatch):
    """Satellite: precompile must warm N world sizes from ABSTRACT
    shapes — zero real init_state allocations (the old path paid one
    full device state per legal size just to lower)."""
    from edl_tpu.runtime.train import Trainer

    et, coord = make_world(target_world=2, n_trainers=4)

    def boom(self):
        raise AssertionError("precompile allocated a real init_state")

    monkeypatch.setattr(Trainer, "init_state", boom)
    et.precompile([1, 2, 4])
    assert sorted(et._trainers) == [1, 2, 4]
    assert all(et._trainers[w].step_warm for w in (1, 2, 4))


def test_warm_resize_zero_xla_compiles(monkeypatch):
    """The acceptance bar: a warm resize (precompiled step executables)
    performs ZERO XLA compiles anywhere in the resize window INCLUDING
    the first post-resize steps — asserted at the backend_compile seam
    (which persistent-cache hits bypass, so only true compiles count)."""
    import jax._src.compiler as compiler

    et, coord = make_world(target_world=2, n_trainers=4)
    et.precompile([2, 4])
    et.run(5)
    et.store.wait()  # the step-5 interval save warms the d2h copy jits

    compiles = []
    real = compiler.backend_compile

    def counting(*args, **kwargs):
        compiles.append(1)
        return real(*args, **kwargs)

    monkeypatch.setattr(compiler, "backend_compile", counting)
    coord.set_target_world(4)
    et.run(9)
    grow = et.resize_events[-1]
    assert grow.world_size == 4 and grow.graceful
    assert compiles == [], (
        f"{len(compiles)} XLA compile(s) inside a warm resize window"
    )
    # the window's phase record proves the warm path ran: the step was
    # already compiled, so the overlapped compile phase is ~free
    assert "compile" in grow.phase_seconds
    assert et._trainers[4].step_warm


def test_prewarm_hint_warms_hinted_size_in_background():
    """Satellite: the autoscaler's prewarm hint actually triggers a
    background warm of exactly the hinted size, with no resize and no
    step-loop interruption; the later retarget then reuses it."""
    et, coord = make_world(target_world=2, n_trainers=4)
    et.run(3)
    gen = et.generation
    coord.set_prewarm(4)
    assert coord.plan().generation == gen  # advisory, no barrier
    et.run(6)  # steady-state steps consume the hint
    th = et._prewarm_threads.get(4)
    assert th is not None, "hint did not start a prewarm thread"
    th.join(timeout=120)
    assert 4 in et._trainers and et._trainers[4].step_warm
    assert et.generation == gen, "prewarm must not resize"

    coord.set_target_world(4)
    et.run(9)
    grow = et.resize_events[-1]
    assert grow.world_size == 4 and grow.graceful
    # loss continuity across the prewarmed resize (steps never paused)
    assert [r.step for r in et.history] == list(range(9))


def test_resize_phase_seconds_record_overlap():
    """phase_seconds carries both sides of the overlapped work: the
    background flush hash/spill and the (possibly cold) step compile,
    plus the residual join each cost the window at the end."""
    et, coord = make_world(target_world=2, n_trainers=4)
    # Stop at step 6, past the interval save at 5: the resize flush is
    # then a FRESH flush (a step-5 resize would dedupe against the
    # interval checkpoint and skip the background thread entirely).
    et.run(6)
    et.store.wait()
    coord.set_target_world(4)  # NOT precompiled: a cold, overlapped compile
    et.run(9)
    grow = et.resize_events[-1]
    ph = grow.phase_seconds
    for key in ("flush", "remesh", "restore", "compile", "compile_join",
                "flush_bg", "flush_bg_join"):
        assert key in ph, (key, ph)
    # the cold step compile ran on the warm thread...
    assert ph["compile"] > 0
    # ...and the first post-resize step reused its executable
    assert et._trainers[4].step_warm
