"""Cluster-wide causal tracing (edl_tpu.telemetry.trace) + the goodput
ledger (edl_tpu.telemetry.ledger): clock-offset estimation, trace-id
propagation through the coordinator, the merged Chrome-trace timeline,
flight-recorder spill hardening, profiler re-arm, and the `edl trace`
CLI.  The 2-process end-to-end merged-trace test (real workers, one
trace id from retarget to first post-resize step) lives in
``tests/test_multipod.py``.
"""

import json

import pytest

from edl_tpu import telemetry
from edl_tpu.telemetry.ledger import GoodputLedger, goodput_decomposition
from edl_tpu.telemetry.recorder import FlightRecorder
from edl_tpu.telemetry.trace import (
    ClockOffsetEstimator,
    chrome_trace,
    load_journal,
    member_streams,
    merge_events,
    trace_chains,
)


# ---- clock-offset estimation ----------------------------------------------
def test_clock_offset_recovers_symmetric_skew():
    """A member whose wall clock runs 3.2s AHEAD of the coordinator:
    with symmetric network delay the classic NTP estimate recovers the
    offset exactly (offset = what to ADD to member time to get
    coordinator time = -3.2)."""
    est = ClockOffsetEstimator()
    skew = 3.2
    for t in (100.0, 101.0, 102.0):
        t0 = t + skew  # member stamps
        t1 = t + 0.010 + skew
        server = t + 0.005  # coordinator mid-handling
        est.add(t0, server, t1)
    assert est.offset() == pytest.approx(-skew, abs=1e-9)
    assert est.rtt() == pytest.approx(0.010)


def test_clock_offset_asymmetric_rtt_error_bounded():
    """Asymmetric delay (slow request, instant response) biases a
    single sample by at most RTT/2 — and the min-RTT filter prefers a
    later tight sample over an earlier congested one."""
    est = ClockOffsetEstimator()
    skew = -1.5  # member clock BEHIND the coordinator by 1.5s
    # congested, asymmetric sample: 0.4s to reach, instant back
    t0 = 200.0 + skew
    server = 200.4
    t1 = 200.4 + skew
    est.add(t0, server, t1)
    assert est.offset() == pytest.approx(1.5 + 0.2, abs=1e-9)
    assert abs(est.offset() - 1.5) <= est.rtt() / 2 + 1e-9
    # a tight symmetric sample arrives: it wins the min-RTT filter
    t0 = 300.0 + skew
    server = 300.001
    t1 = 300.002 + skew
    est.add(t0, server, t1)
    assert est.offset() == pytest.approx(1.5, abs=1e-6)


def test_clock_offset_empty_and_window():
    est = ClockOffsetEstimator(window=4)
    assert est.offset() is None and est.rtt() is None
    # a congested old sample eventually slides out of the window
    est.add(0.0, 5.0, 10.0)  # rtt 10
    for i in range(4):
        base = 20.0 + i
        est.add(base, base + 0.5 + 0.001, base + 0.002)
    assert est.rtt() == pytest.approx(0.002)
    assert est.sample_count() == 4


# ---- recorder: trace is a non-identity field ------------------------------
def test_trace_excluded_from_identity_and_digest():
    a, b = FlightRecorder(), FlightRecorder()
    a.record("resize", {"world_size": 2}, step=5, generation=1)
    b.set_trace("feedc0de00112233")
    b.record("resize", {"world_size": 2}, step=5, generation=1)
    assert a.digest() == b.digest()
    ev = b.events()[-1]
    assert ev.trace == "feedc0de00112233"
    assert ev.to_dict()["trace"] == "feedc0de00112233"
    assert "trace" not in ev.identity()
    # clearing the ambient trace stops stamping
    b.set_trace("")
    assert b.record("resize", {}, step=6, generation=1).trace == ""


def test_ingest_preserves_wall_and_trace():
    """The coordinator must NOT re-stamp member events with its own
    clock or drop their trace ids — the merged timeline's ordering
    and causal chains both depend on the originals."""
    member = FlightRecorder(clock=lambda: 1234.5)
    member.record("consensus.vote", {"proposed_stop": 9}, trace="abc123")
    coord = FlightRecorder(clock=lambda: 9999.0)
    coord.ingest([e.to_dict() for e in member.events()], origin="w1")
    got = coord.events()[-1]
    assert got.wall == pytest.approx(1234.5)
    assert got.trace == "abc123"
    assert got.data["origin"] == "w1"


# ---- recorder: spill hardening --------------------------------------------
def test_spill_rotation_bounds_file_size(tmp_path):
    path = str(tmp_path / "events.jsonl")
    rec = FlightRecorder(spill_path=path, spill_max_mb=0.001)  # ~1KB
    for i in range(200):
        rec.record("resize", {"world_size": i}, step=i, generation=0)
    import os

    live = os.path.getsize(path)
    assert live <= 1200  # bounded (one line of slack over 1KB)
    assert os.path.exists(path + ".1")  # rotated predecessor kept
    assert os.path.getsize(path + ".1") <= 1200
    # the ring still holds everything regardless of rotation
    assert len(rec) == 200


def test_spill_failure_counts_drops_and_recovers(tmp_path):
    clock = [100.0]
    with telemetry.scoped() as (reg, _):
        rec = FlightRecorder(
            spill_path=str(tmp_path / "nodir" / "x.jsonl"),
            clock=lambda: clock[0],
        )
        rec.record("resize", {}, step=1, generation=0)  # open fails
        rec.record("resize", {}, step=2, generation=0)  # in backoff
        drops = reg.counter("edl_flight_spill_dropped_total").value()
        assert drops == 2 and rec.spill_dropped == 2
        # the directory appears and the backoff window passes: the
        # spill recovers instead of staying disabled forever
        (tmp_path / "nodir").mkdir()
        clock[0] += 10.0
        rec.record("resize", {}, step=3, generation=0)
        spilled = load_journal(str(tmp_path / "nodir" / "x.jsonl"))
        assert [e["step"] for e in spilled] == [3]
        assert reg.counter("edl_flight_spill_dropped_total").value() == 2


# ---- goodput ledger --------------------------------------------------------
def test_goodput_ledger_transitions_and_decomposition():
    clock = [0.0]
    with telemetry.scoped() as (reg, _):
        led = GoodputLedger(registry=reg, clock=lambda: clock[0])
        led.transition("stepping")
        clock[0] = 8.0
        led.note_staging(2.0)  # 2 of the 8s were host batch stalls
        led.transition("resizing")
        clock[0] = 9.0
        led.split_resize({"flush": 0.25, "restore": 0.5})
        led.transition("replaying")
        clock[0] = 10.0
        led.transition("stepping")
        clock[0] = 14.0
        led.transition("holding")
        gp = goodput_decomposition(reg.snapshot())
    assert gp is not None
    s = gp["seconds"]
    assert s["stepping"] == pytest.approx(10.0)
    assert s["staging_stalled"] == pytest.approx(2.0)
    assert s["replaying"] == pytest.approx(1.0)
    # the resize second decomposes into its measured phases + remainder
    assert s["resizing:flush"] == pytest.approx(0.25)
    assert s["resizing:restore"] == pytest.approx(0.5)
    assert s["resizing"] == pytest.approx(0.25)
    assert gp["total_s"] == pytest.approx(14.0)
    assert gp["frac"] == pytest.approx(10.0 / 14.0)


def test_goodput_ledger_touch_keeps_counters_fresh():
    clock = [0.0]
    with telemetry.scoped() as (reg, _):
        led = GoodputLedger(registry=reg, clock=lambda: clock[0])
        led.transition("stepping")
        clock[0] = 5.0
        led.touch()  # long steady state, no transition
        gp = goodput_decomposition(reg.snapshot())
        assert gp["seconds"]["stepping"] == pytest.approx(5.0)
        assert reg.gauge("edl_goodput_frac").value() == pytest.approx(1.0)
    assert goodput_decomposition({"counters": {}}) is None


# ---- coordinator propagation ----------------------------------------------
def test_plan_trace_rides_prewarm_and_retarget():
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(target_world=1, max_world=4)
    coord.register("a")
    coord.register("b")
    join_trace = coord.plan().trace_id
    assert join_trace  # membership churn mints its own
    coord.set_prewarm(2, trace_id="aa11bb22cc33dd44")
    plan = coord.plan()
    assert plan.prewarm == 2
    assert plan.prewarm_trace == "aa11bb22cc33dd44"
    assert plan.trace_id == join_trace  # hint never changes the gen's id
    coord.set_target_world(2, trace_id="aa11bb22cc33dd44")
    plan = coord.plan()
    assert plan.trace_id == "aa11bb22cc33dd44"
    evs = coord.recorder().events()
    retarget = [e for e in evs if e.data.get("reason") == "retarget"]
    assert retarget and retarget[-1].trace == "aa11bb22cc33dd44"
    # world_acked journals under the same chain
    coord.ack_generation("a", plan.generation)
    coord.ack_generation("b", plan.generation)
    acked = [e for e in coord.recorder().events()
             if e.kind == "coord.world_acked"]
    assert acked and acked[-1].trace == "aa11bb22cc33dd44"


def test_scale_up_joins_inherit_the_actuation_trace():
    """Production scale-up order: the retarget lands BEFORE the new
    pods exist (the PUT creates them).  The join rebuilds that grow
    the world toward the target are that same decision landing — they
    must journal under its id, not a fresh join-minted one; once the
    target is reached (or an unrelated join arrives) minting resumes."""
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(target_world=1, max_world=4)
    coord.register("a")
    gen = coord.plan().generation
    coord.set_target_world(3, trace_id="deadbeefdeadbeef")
    # the active world is unchanged, so the retarget itself rebuilds
    # nothing (no spurious resize barrier) ...
    assert coord.plan().generation == gen
    # ... the decision's pods register: each growth join IS the
    # decision landing and continues its chain
    coord.register("b")
    assert coord.plan().trace_id == "deadbeefdeadbeef"
    assert coord.plan().world_size == 2
    coord.register("c")
    assert coord.plan().trace_id == "deadbeefdeadbeef"
    assert coord.plan().world_size == 3
    # target reached: a later (standby-breaking) membership change
    # mints its own id again
    coord.register("d")
    coord.deregister("c")
    plan = coord.plan()
    assert plan.trace_id and plan.trace_id != "deadbeefdeadbeef"
    # a no-op retarget must not leave a stale pending trace behind for
    # an unrelated later retarget to consume
    coord.set_prewarm(3, trace_id="aaaaaaaaaaaaaaaa")
    coord.set_target_world(3, trace_id="aaaaaaaaaaaaaaaa")  # no-op
    coord.set_target_world(2)
    assert coord.plan().trace_id != "aaaaaaaaaaaaaaaa"
    # ...nor may a pending trace survive a retarget whose rebuild
    # early-returned (active world unchanged — pods not yet
    # registered): after the scale-up CONVERGES via joins, a later
    # traceless retarget must not inherit the old decision's id
    coord2 = LocalCoordinator(target_world=1, max_world=4)
    coord2.register("x")
    coord2.set_target_world(3, trace_id="bbbbbbbbbbbbbbbb")
    coord2.register("y")
    coord2.register("z")  # converged: world 3, all under B
    assert coord2.plan().trace_id == "bbbbbbbbbbbbbbbb"
    coord2.set_target_world(2)  # unrelated, traceless shrink
    plan2 = coord2.plan()
    assert plan2.world_size == 2
    assert plan2.trace_id != "bbbbbbbbbbbbbbbb"
    # ...and a trace staged by a prewarm whose retarget PUT never
    # landed (conflict-storm give-up) must not bleed onto a later
    # traceless retarget by a different actor (operator CLI / chaos)
    coord3 = LocalCoordinator(target_world=2, max_world=4)
    coord3.register("p")
    coord3.register("q")
    coord3.set_prewarm(4, trace_id="cccccccccccccccc")
    coord3.set_target_world(1)  # different actor, traceless
    assert coord3.plan().world_size == 1
    assert coord3.plan().trace_id != "cccccccccccccccc"


def test_http_heartbeat_feeds_clock_and_telemetry_offsets():
    from edl_tpu.runtime.coord_service import (
        CoordinatorServer,
        HTTPCoordinator,
    )
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(target_world=1, max_world=2)
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start(
        evict=False
    )
    try:
        client = HTTPCoordinator(f"127.0.0.1:{server.port}")
        client.register("w1", address="127.0.0.1:9")
        client.heartbeat("w1", step=3)
        assert client.clock_estimator.sample_count() >= 1
        # same machine: the estimated offset is ~0
        assert abs(client.clock_estimator.offset()) < 1.0
        client.report_telemetry("w1", snapshot={}, seq=1, boot="b1")
        offs = coord.telemetry()["clock_offsets"]
        assert "w1" in offs and abs(offs["w1"]) < 1.0
        # the retargeted plan's trace id survives the HTTP round trip
        client.set_target_world(2, trace_id="0123456789abcdef")
        coord.register("w2")
        assert client.plan().trace_id
    finally:
        server.stop()


# ---- the merged timeline ---------------------------------------------------
def _ev(member, kind, wall, trace="", timing=None, seq=1, **data):
    d = {
        "seq": seq,
        "step": data.pop("step", 0),
        "generation": 1,
        "kind": kind,
        "data": data,
        "wall": wall,
    }
    if trace:
        d["trace"] = trace
    if timing:
        d["timing"] = timing
    return d


def test_merge_events_aligns_skewed_member_clocks():
    """w2's wall clock is 100s ahead; after applying its estimated
    offset the causal order (coordinator plan -> w2 vote -> w1 resize)
    is restored."""
    streams = {
        "coordinator": [_ev("c", "coord.plan", 1000.0, trace="t1")],
        "w1": [_ev("w1", "resize", 1002.0, trace="t1")],
        "w2": [_ev("w2", "consensus.vote", 1101.0, trace="t1")],
    }
    merged = merge_events(streams, offsets={"w2": -100.0})
    assert [e["kind"] for e in merged] == [
        "coord.plan",
        "consensus.vote",
        "resize",
    ]
    assert merged[1]["wall_aligned"] == pytest.approx(1001.0)
    chains = trace_chains(merged)
    assert set(chains) == {"t1"} and len(chains["t1"]) == 3


def test_chrome_trace_lanes_slices_and_filter():
    events = merge_events(
        {
            "w1": [
                _ev(
                    "w1",
                    "resize",
                    50.0,
                    trace="tt",
                    timing={
                        "seconds": 2.0,
                        "phases": {"flush": 0.5, "restore": 1.0,
                                   "compile": 1.2},
                    },
                    step=7,
                    world_size=2,
                ),
                _ev("w1", "step.first", 50.5, trace="tt", step=8),
            ],
            "w2": [_ev("w2", "consensus.quiesce", 49.5, trace="other")],
        }
    )
    doc = chrome_trace(events)
    evs = doc["traceEvents"]
    procs = {e["args"]["name"] for e in evs if e["name"] == "process_name"}
    assert procs == {"w1", "w2"}
    threads = {e["args"]["name"] for e in evs if e["name"] == "thread_name"}
    assert {"resize", "step", "consensus"} <= threads
    slices = [e for e in evs if e.get("ph") == "X"]
    names = {e["name"] for e in slices}
    # the window slice + serial phase children + the overlapped compile
    assert {"resize", "resize/flush", "resize/restore",
            "resize/compile"} <= names
    window = next(e for e in slices if e["name"] == "resize")
    flush = next(e for e in slices if e["name"] == "resize/flush")
    restore = next(e for e in slices if e["name"] == "resize/restore")
    assert window["dur"] == pytest.approx(2e6)
    # serial phases lay out back-to-back from the window start
    assert flush["ts"] == pytest.approx(window["ts"])
    assert restore["ts"] == pytest.approx(window["ts"] + 0.5e6)
    instants = [e for e in evs if e.get("ph") == "i"]
    assert {"step.first", "consensus.quiesce"} == {
        e["name"] for e in instants
    }
    # filtering to one causal chain drops the other member's event
    only = chrome_trace(events, trace_id="tt")
    kinds = {e["name"] for e in only["traceEvents"]
             if e["ph"] not in ("M",)}
    assert "consensus.quiesce" not in kinds
    assert "step.first" in kinds


def test_member_streams_splits_coordinator_journal():
    evs = [
        _ev("c", "coord.plan", 1.0),
        {**_ev("c", "resize", 2.0), "data": {"origin": "w1"}},
    ]
    streams = member_streams(evs)
    assert set(streams) == {"coordinator", "w1"}


# ---- in-process end-to-end: one trace id across a local resize -------------
def test_local_resize_events_share_minted_trace():
    import optax

    from edl_tpu.models import get_model
    from edl_tpu.runtime import ShardedDataIterator
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.data import synthetic_dataset
    from edl_tpu.runtime.elastic import ElasticTrainer

    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 512, seed=0)
    with telemetry.scoped() as (reg, rec):
        coord = LocalCoordinator(target_world=2, max_world=8)
        for i in range(4):
            coord.register(f"tr{i}")
        et = ElasticTrainer(
            model,
            optax.adam(1e-2),
            ShardedDataIterator(ds, global_batch_size=64, seed=0),
            coord,
            checkpoint_interval=5,
        )
        # Run past the step-5 interval save so the traced resize's
        # flush is a FRESH flush (a step-5 resize would dedupe against
        # the interval checkpoint and journal no flush of its own).
        et.run(6)
        # the autoscaler's half, in miniature: hint then retarget
        # under one minted trace id
        trace = "11fe11fe11fe11fe"
        coord.set_prewarm(4, trace_id=trace)
        et.run(8)  # steady state consumes the hint (background warm)
        th = et._prewarm_threads.get(4)
        if th is not None:
            th.join(timeout=120)
        coord.set_target_world(4, trace_id=trace)
        et.run(12)
        et.store.wait()
        events = rec.events()
        by_kind = {}
        for e in events:
            by_kind.setdefault(e.kind, []).append(e)
        # the resize into world 4 and its first step share the id
        assert any(
            e.trace == trace and e.data["world_size"] == 4
            for e in by_kind["resize"]
        )
        assert any(e.trace == trace for e in by_kind["step.first"])
        # the flush checkpoint journaled inside the window too
        assert any(
            e.trace == trace and e.data.get("kind") == "flush"
            for e in by_kind.get("checkpoint.save", [])
        )
        # the background warm journaled under the hint's trace
        assert any(
            e.trace == trace for e in by_kind.get("prewarm.hint", [])
        )
        # steady-state events after step.first are NOT charged to it
        last_first = max(
            e.seq for e in by_kind["step.first"] if e.trace == trace
        )
        later = [e for e in events if e.seq > last_first]
        assert all(e.trace != trace for e in later)
        # the goodput ledger attributed the run
        gp = goodput_decomposition(reg.snapshot())
        assert gp is not None and gp["seconds"]["stepping"] > 0
        assert 0.0 < gp["frac"] <= 1.0
        assert et.ledger.totals.get("resizing") is not None


# ---- profiler re-arm -------------------------------------------------------
def _fake_profiler(monkeypatch):
    import jax

    calls = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop", None))
    )
    return calls


def test_profiler_at_step_defers_window(tmp_path, monkeypatch):
    from edl_tpu.utils.profiling import StepProfiler

    calls = _fake_profiler(monkeypatch)
    p = StepProfiler(
        profile_dir=str(tmp_path), max_steps=2, at_step=10
    )
    p.maybe_start(0)
    assert not p.tracing and not calls
    p.maybe_start(10)
    assert p.tracing
    with p.step(10):
        pass
    with p.step(11):
        pass
    p.maybe_stop()
    assert not p.tracing
    assert [c[0] for c in calls] == ["start", "stop"]
    # window closed: no restart without a rearm
    p.maybe_start(12)
    assert not p.tracing


def test_profiler_rearm_on_resize_opens_second_window(
    tmp_path, monkeypatch
):
    from edl_tpu.utils.profiling import StepProfiler

    calls = _fake_profiler(monkeypatch)
    p = StepProfiler(
        profile_dir=str(tmp_path), max_steps=1, rearm_on_resize=True
    )
    p.maybe_start(0)
    with p.step(0):
        pass
    p.maybe_stop()
    assert [c[0] for c in calls] == ["start", "stop"]
    p.note_resize()  # the resize re-arms a fresh bounded window
    p.maybe_start(5)
    assert p.tracing
    with p.step(5):
        pass
    p.maybe_stop()
    assert [c[0] for c in calls] == ["start", "stop", "start", "stop"]


def test_profiler_windows_journal_flight_events(tmp_path, monkeypatch):
    from edl_tpu.utils.profiling import StepProfiler

    _fake_profiler(monkeypatch)
    with telemetry.scoped() as (_, rec):
        p = StepProfiler(profile_dir=str(tmp_path), max_steps=1)
        p.maybe_start(3)
        with p.step(3):
            pass
        p.maybe_stop()
        kinds = [
            (e.kind, e.data.get("phase")) for e in rec.events()
        ]
    assert ("profile.window", "open") in kinds
    assert ("profile.window", "close") in kinds


# ---- lint: flight-event kinds are registry-checked ------------------------
def test_lint_rejects_unregistered_event_kind(tmp_path):
    import sys as _sys

    _sys.path.insert(0, "tools")
    try:
        import lint
    finally:
        _sys.path.pop(0)

    bad = tmp_path / "edl_tpu" / "mod.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        'def f(rec, k):\n'
        '    rec.record("resize.oops")\n'
        '    rec.record(k)\n'
        '    rec.record("resize")\n'
    )
    msgs = [m for _, m in lint.lint_file(bad)]
    assert any("unregistered flight-event kind" in m for m in msgs)
    assert any("free-form event kind" in m for m in msgs)
    assert sum("event kind" in m for m in msgs) == 2


def test_known_event_kinds_covers_every_recorded_kind():
    """Every kind the runtime actually records must be cataloged (the
    lint gate enforces literals; this guards the catalog's claim that
    it is exhaustive for the in-tree writers)."""
    from edl_tpu.telemetry import KNOWN_EVENT_KINDS

    for kind in (
        "resize",
        "step.first",
        "consensus.vote",
        "consensus.stop",
        "consensus.quiesce",
        "coord.plan",
        "coord.world_acked",
        "autoscaler.decision",
        "prewarm.hint",
        "profile.window",
    ):
        assert kind in KNOWN_EVENT_KINDS


# ---- edl trace CLI ---------------------------------------------------------
def test_trace_cli_merges_journals_post_mortem(tmp_path, capsys):
    from edl_tpu.cli import main

    j1 = tmp_path / "w1.jsonl"
    j2 = tmp_path / "w2.jsonl"
    j1.write_text(
        json.dumps(
            _ev("w1", "resize", 10.0, trace="cafe", seq=1,
                timing={"seconds": 1.0}, world_size=2)
        )
        + "\n"
        + json.dumps(_ev("w1", "step.first", 10.2, trace="cafe", seq=2))
        + "\n"
    )
    j2.write_text(
        json.dumps(
            _ev("w2", "consensus.quiesce", 9.8, trace="cafe", seq=1)
        )
        + "\n"
    )
    out = tmp_path / "merged.json"
    rc = main(
        [
            "trace",
            "--journal", f"w1={j1}",
            "--journal", f"w2={j2}",
            "--out", str(out),
            "--summary",
        ]
    )
    assert rc == 0
    printed = capsys.readouterr().out
    assert "causal chains (1)" in printed
    assert "cafe" in printed
    assert "goodput" in printed
    doc = json.loads(out.read_text())
    procs = {
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e["name"] == "process_name"
    }
    assert procs == {"w1", "w2"}
    assert any(e.get("ph") == "X" for e in doc["traceEvents"])


def test_trace_cli_summary_prints_goodput_from_live_coordinator(
    tmp_path, capsys
):
    from edl_tpu.cli import main
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.telemetry import MetricsRegistry

    coord = LocalCoordinator(target_world=1, max_world=2)
    coord.register("a")
    reg = MetricsRegistry()
    m = reg.counter("edl_goodput_seconds_total")
    m.inc(9.0, state="stepping")
    m.inc(1.0, state="resizing")
    coord.report_telemetry(
        "a",
        snapshot=reg.snapshot(),
        seq=1,
        boot="b",
        clock={"offset": 0.001, "rtt": 0.002},
        events=[_ev("a", "resize", 5.0, trace="beef")],
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start(
        evict=False
    )
    out = tmp_path / "t.json"
    try:
        rc = main(
            [
                "trace",
                f"127.0.0.1:{server.port}",
                "--out", str(out),
                "--summary",
            ]
        )
    finally:
        server.stop()
    assert rc == 0
    printed = capsys.readouterr().out
    assert "frac" in printed and "0.9000" in printed
    assert "stepping" in printed
    assert "clock offset a" in printed
    assert out.exists()
