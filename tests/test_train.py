"""Trainer runtime core tests: mesh, data determinism, DP train step.

The reference has no trainer-side tests at all (training was external,
SURVEY.md §3.5); these cover the new half on an 8-device virtual CPU
mesh per SURVEY.md §4's recommendation.
"""

import numpy as np
import optax
import pytest


from edl_tpu.models import get_model
from edl_tpu.parallel import MeshSpec, build_mesh, dp_mesh
from edl_tpu.runtime import ShardedDataIterator, Trainer
from edl_tpu.runtime.data import synthetic_dataset


def test_mesh_spec():
    s = MeshSpec.create(dp=4, tp=2)
    assert s.size() == 8
    assert s.names == ("dp", "tp")  # canonical order
    assert s.axis_size("dp") == 4
    assert s.axis_size("pp") == 1
    with pytest.raises(ValueError):
        MeshSpec.create(bogus=2)
    with pytest.raises(ValueError):
        MeshSpec.create(dp=0)


def test_build_mesh(devices8):
    mesh = build_mesh(MeshSpec.create(dp=2, tp=2), devices8)
    assert mesh.devices.shape == (2, 2)
    with pytest.raises(ValueError):
        build_mesh(MeshSpec.create(dp=16), devices8)


def test_data_determinism():
    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 512, seed=3)
    it = ShardedDataIterator(ds, global_batch_size=64, seed=7)

    # Same step -> same global indices, independent of who asks.
    assert np.array_equal(it.global_indices(5), it.global_indices(5))
    # Distinct steps within an epoch are disjoint.
    a, b = it.global_indices(0), it.global_indices(1)
    assert not set(a) & set(b)
    # A world of 2 slices the same global batch that a world of 1 sees.
    full = it.host_batch(3, world=1, rank=0)
    r0 = it.host_batch(3, world=2, rank=0)
    r1 = it.host_batch(3, world=2, rank=1)
    assert np.array_equal(np.concatenate([r0["x"], r1["x"]]), full["x"])
    # Bad shapes are rejected.
    with pytest.raises(ValueError):
        it.host_batch(0, world=5, rank=0)  # 64 % 5 != 0
    with pytest.raises(ValueError):
        it.host_batch(0, world=2, rank=2)


def test_dp_training_learns(devices8):
    model = get_model("fit_a_line")
    mesh = dp_mesh(4, devices8)
    trainer = Trainer(model, optax.adam(1e-1), mesh, seed=0)
    state = trainer.init_state()
    ds = synthetic_dataset(model.synth_batch, 1024, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=256, seed=0)

    first_loss = None
    for step in range(60):
        batch = it.device_batch(step, mesh)
        state, metrics = trainer.step(state, batch)
        if first_loss is None:
            first_loss = float(metrics["loss"])
    final_loss = float(metrics["loss"])
    assert final_loss < first_loss * 0.05, (first_loss, final_loss)
    assert int(state.step) == 60


def test_dp_matches_single_device(devices8):
    """Gradient sync over the mesh must be mathematically identical to
    single-device training on the same global batch (the property the
    reference's async pserver could NOT give; ours is exact sync DP)."""
    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 256, seed=1)

    losses = {}
    for world in (1, 4):
        mesh = dp_mesh(world, devices8)
        trainer = Trainer(model, optax.sgd(1e-2), mesh, seed=0)
        state = trainer.init_state()
        it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
        trace = []
        for step in range(5):
            batch = it.device_batch(step, mesh)
            state, m = trainer.step(state, batch)
            trace.append(float(m["loss"]))
        losses[world] = trace
    np.testing.assert_allclose(losses[1], losses[4], rtol=1e-5)


def test_mnist_smoke(devices8):
    model = get_model("mnist")
    mesh = dp_mesh(2, devices8)
    trainer = Trainer(model, optax.adam(1e-3), mesh, seed=0)
    state = trainer.init_state()
    ds = synthetic_dataset(model.synth_batch, 256, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=32, seed=0)
    for step in range(8):
        batch = it.device_batch(step, mesh)
        state, metrics = trainer.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    acc0 = float(metrics["accuracy"])
    for step in range(8, 40):
        batch = it.device_batch(step, mesh)
        state, metrics = trainer.step(state, batch)
    # synthetic blobs are nearly separable; the net should beat chance.
    assert float(metrics["accuracy"]) > 0.3, (acc0, float(metrics["accuracy"]))


def test_model_registry():
    from edl_tpu.models import registered_models

    assert "fit_a_line" in registered_models()
    assert "mnist" in registered_models()
    with pytest.raises(ValueError):
        get_model("nope")
