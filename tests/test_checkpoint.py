"""Checkpoint subsystem tests: async host-DRAM save, resharding restore,
disk spill roundtrip, retention.
"""

import numpy as np
import optax
import pytest

import jax

from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.models import get_model
from edl_tpu.parallel import dp_mesh
from edl_tpu.runtime import ShardedDataIterator, Trainer
from edl_tpu.runtime.data import synthetic_dataset


@pytest.fixture()
def trained():
    model = get_model("fit_a_line")
    mesh = dp_mesh(4)
    trainer = Trainer(model, optax.adam(1e-2), mesh, seed=0)
    state = trainer.init_state()
    ds = synthetic_dataset(model.synth_batch, 512, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
    for step in range(10):
        state, _ = trainer.step(state, it.device_batch(step, mesh))
    return model, mesh, trainer, state, it


def test_save_async_and_latest(trained):
    model, mesh, trainer, state, it = trained
    store = HostDRAMStore(keep=2)
    store.save_async(state, generation=1)
    store.wait()
    ckpt = store.latest()
    assert ckpt is not None
    assert ckpt.step == 10
    assert ckpt.generation == 1
    assert ckpt.nbytes() > 0
    # leaves are real host numpy copies
    assert all(isinstance(x, np.ndarray) for x in ckpt.leaves)


def test_restore_onto_smaller_mesh_and_continue(trained):
    """Save from world=4, restore onto world=2, training continues with
    EXACTLY the same loss trajectory as never resizing (deterministic
    data + fixed global batch => bitwise-comparable continuation)."""
    model, mesh4, trainer4, state, it = trained
    store = HostDRAMStore()
    store.save_async(state)
    store.wait()

    # Continue on the original mesh for reference.
    ref_state = state
    ref_losses = []
    for step in range(10, 15):
        ref_state, m = trainer4.step(ref_state, it.device_batch(step, mesh4))
        ref_losses.append(float(m["loss"]))

    # Restore onto a *different* mesh (2 devices) and continue.
    mesh2 = dp_mesh(2)
    trainer2 = Trainer(model, optax.adam(1e-2), mesh2, seed=0)
    state2 = store.restore(store.latest(), mesh2)
    assert int(state2.step) == 10
    losses2 = []
    for step in range(10, 15):
        state2, m = trainer2.step(state2, it.device_batch(step, mesh2))
        losses2.append(float(m["loss"]))

    np.testing.assert_allclose(ref_losses, losses2, rtol=1e-5)


def test_restore_onto_larger_mesh(trained):
    model, mesh4, trainer4, state, it = trained
    store = HostDRAMStore()
    store.save_async(state)
    store.wait()
    mesh8 = dp_mesh(8)
    state8 = store.restore(store.latest(), mesh8)
    trainer8 = Trainer(model, optax.adam(1e-2), mesh8, seed=0)
    state8, m = trainer8.step(state8, it.device_batch(10, mesh8))
    assert np.isfinite(float(m["loss"]))
    assert int(state8.step) == 11


def test_retention(trained):
    model, mesh, trainer, state, it = trained
    store = HostDRAMStore(keep=2)
    for step in range(10, 14):
        state, _ = trainer.step(state, it.device_batch(step, mesh))
        store.save_async(state)
    store.wait()
    assert store.steps() == [13, 14][:2] or len(store.steps()) == 2
    assert store.latest().step == 14


def test_disk_spill_roundtrip(tmp_path, trained):
    model, mesh, trainer, state, it = trained
    store = HostDRAMStore(keep=1, spill_dir=str(tmp_path))
    store.save_async(state, generation=3)
    store.wait()

    # Fresh store (simulates host restart), rehydrate from disk.
    store2 = HostDRAMStore(keep=1, spill_dir=str(tmp_path))
    template = trainer.init_state()
    ckpt = store2.load_from_disk(template)
    assert ckpt.step == 10
    assert ckpt.generation == 3
    restored = store2.restore(ckpt, mesh)
    orig = jax.device_get(state)
    back = jax.device_get(restored)
    for a, b in zip(jax.tree_util.tree_leaves(orig), jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_load_from_disk_missing(tmp_path):
    store = HostDRAMStore(spill_dir=str(tmp_path))
    with pytest.raises(FileNotFoundError):
        store.load_from_disk(template_state={"w": np.zeros(3)})
    store2 = HostDRAMStore()
    with pytest.raises(ValueError):
        store2.load_from_disk(template_state={})


def test_restore_never_aliases_checkpoint_bytes(trained):
    """Restored state must live in DEVICE-OWNED buffers, never zero-copy
    aliases of the checkpoint's host numpy (``leaf_placer``'s owned-copy
    staging on CPU).  The chain this pins down: CPU device_put zero-
    copies aligned numpy, a replicated target then backs EVERY replica
    with the checkpoint's own bytes, and the train step's donated state
    input turns into an in-place write through them — with a
    persistent-compilation-cache DESERIALIZED step executable the write
    really lands (the fresh-compile path copies), so the step counter
    advanced by world_size per step (each replica incremented the one
    shared buffer) and the checkpoint silently tracked the live state."""
    model, mesh, trainer, state, it = trained
    store = HostDRAMStore()
    store.save_async(state)
    store.wait()
    ckpt = store.latest()
    before = [np.array(l) for l in ckpt.leaves]

    restored = store.restore(ckpt, mesh)
    # No restored leaf buffer may share memory with a checkpoint leaf.
    for host, dev in zip(
        ckpt.leaves, jax.tree_util.tree_leaves(restored)
    ):
        for shard in dev.addressable_shards:
            view = np.asarray(shard.data)
            assert not np.shares_memory(host, view), (
                "restored leaf aliases checkpoint host bytes"
            )
    # Stepping the restored state (donating executables) must advance
    # the counter by exactly 1 and leave the checkpoint bytes untouched.
    restored, _ = trainer.step(restored, it.device_batch(10, mesh))
    assert int(restored.step) == 11
    for b, l in zip(before, ckpt.leaves):
        np.testing.assert_array_equal(b, l)
