"""Decision-core tests, mirroring the reference's white-box suite
(``pkg/autoscaler_internal_test.go``: fabricated ClusterResource
literals, scale-up satisfied / starved variants, clamp-down, shed,
whole-plan fixed point, fulfillment math, sort order) plus the
TPU-native behaviors it couldn't have: slice/batch-quantized steps,
pending-demand shedding, livelock-free full-utilization fixed point.
"""


from edl_tpu.autoscaler.algorithm import (
    JobView,
    PendingDemand,
    elastic,
    fulfillment,
    needs_tpu,
    scale_all_jobs_dry_run,
    scale_dry_run,
    search_assignable_node,
    sorted_jobs,
)
from edl_tpu.cluster.resources import ClusterResource, Nodes
from edl_tpu.resource.training_job import TrainingJob


def make_view(
    name="j",
    cpu=1000,
    mem=1024,
    tpu=4,
    mn=1,
    mx=4,
    parallelism=1,
    legal=None,
):
    """Fixture builder (analog of the reference's ``makeJob``,
    ``pkg/autoscaler_internal_test.go:56-94``)."""
    return JobView(
        name=name,
        min_instance=mn,
        max_instance=mx,
        parallelism=parallelism,
        cpu_request_milli=cpu,
        mem_request_mega=mem,
        tpu_per_trainer=tpu,
        legal_sizes=list(legal) if legal else [],
        elastic=mn < mx,
    )


def roomy_cluster(n_nodes=4, cpu=8000, mem=32768, tpu=16) -> ClusterResource:
    names = [f"node-{i}" for i in range(n_nodes)]
    return ClusterResource(
        node_count=n_nodes,
        tpu_total=tpu * n_nodes,
        cpu_total_milli=cpu * n_nodes,
        memory_total_mega=mem * n_nodes,
        nodes=Nodes(
            cpu_idle_milli={n: cpu for n in names},
            memory_free_mega={n: mem for n in names},
            tpu_free={n: tpu for n in names},
        ),
    )


# ---- fulfillment + sort (ref :366-438) -------------------------------------


def test_fulfillment_math():
    assert fulfillment(make_view(mn=1, mx=1, parallelism=1)) == 1.0
    assert fulfillment(make_view(mn=1, mx=3, parallelism=1)) == 0.0
    assert fulfillment(make_view(mn=1, mx=3, parallelism=2)) == 0.5
    assert fulfillment(make_view(mn=1, mx=3, parallelism=3)) == 1.0


def test_sort_order_and_tiebreakers():
    a = make_view("a", parallelism=3, mn=1, mx=3)  # fulfillment 1.0
    b = make_view("b", parallelism=1, mn=1, mx=3)  # fulfillment 0.0
    c = make_view("c", parallelism=2, mn=1, mx=3)  # fulfillment 0.5
    assert [j.name for j in sorted_jobs([a, b, c])] == ["b", "c", "a"]
    # ties: fewer chips first, then cpu, then mem (all ascending)
    d = make_view("d", parallelism=1, mn=1, mx=3, tpu=8)
    e = make_view("e", parallelism=1, mn=1, mx=3, tpu=4)
    f = make_view("f", parallelism=1, mn=1, mx=3, tpu=4, cpu=500)
    assert [j.name for j in sorted_jobs([d, e, f])] == ["f", "e", "d"]


def test_filters():
    el = make_view("el", mn=1, mx=4)
    ne = make_view("ne", mn=2, mx=2)
    cpu_only = make_view("c", tpu=0, mn=1, mx=4)
    assert [j.name for j in sorted_jobs([el, ne], elastic)] == ["el"]
    assert [j.name for j in sorted_jobs([el, cpu_only], needs_tpu)] == ["el"]


# ---- search_assignable_node -------------------------------------------------


def test_search_assignable_node_checks_all_axes():
    r = roomy_cluster(n_nodes=2, cpu=2000, mem=2048, tpu=4)
    j = make_view(cpu=1500, mem=1024, tpu=4)
    assert search_assignable_node(r, j) == "node-0"
    r.nodes.tpu_free["node-0"] = 0
    assert search_assignable_node(r, j) == "node-1"
    r.nodes.cpu_idle_milli["node-1"] = 100
    assert search_assignable_node(r, j) is None


# ---- scale_dry_run: scale-up (ref :103-177, :238-254) -----------------------


def test_scale_up_satisfied():
    r = roomy_cluster()
    j = make_view(parallelism=1, mn=1, mx=4)
    assert scale_dry_run(r, j, 0) == 1
    # simulated inventory was charged
    assert r.tpu_limit == 4
    assert r.cpu_request_milli == 1000


def test_scale_up_insufficient_cpu():
    r = roomy_cluster(n_nodes=1, cpu=1000)  # one replica's worth already tight
    r.cpu_request_milli = 500
    j = make_view(parallelism=1, mn=1, mx=4, cpu=1000)
    assert scale_dry_run(r, j, 0, max_load_desired=1.0) == 0


def test_scale_up_insufficient_tpu():
    r = roomy_cluster(n_nodes=1, tpu=4)
    r.tpu_limit = 4  # all chips spoken for
    j = make_view(parallelism=1, mn=1, mx=4, tpu=4)
    assert scale_dry_run(r, j, 0) == 0


def test_scale_up_insufficient_memory():
    r = roomy_cluster(n_nodes=1, mem=1024)
    r.memory_request_mega = 512
    j = make_view(parallelism=1, mn=1, mx=4, mem=1024)
    assert scale_dry_run(r, j, 0) == 0


def test_scale_up_no_assignable_node():
    # Cluster-level totals fine, but no single node fits the replica.
    r = roomy_cluster(n_nodes=4, cpu=800)
    j = make_view(parallelism=1, mn=1, mx=4, cpu=1000)
    r.cpu_total_milli = 100_000  # plenty in aggregate
    assert scale_dry_run(r, j, 0) == 0


def test_scale_up_respects_max_load_desired():
    r = roomy_cluster(n_nodes=1, cpu=10_000)
    r.cpu_request_milli = 7500
    j = make_view(parallelism=1, mn=1, mx=4, cpu=1000, tpu=0)
    assert scale_dry_run(r, j, 0, max_load_desired=0.8) == 0
    assert scale_dry_run(r, j, 0, max_load_desired=1.0) == 1


def test_scale_up_clamps_at_max():
    r = roomy_cluster()
    j = make_view(parallelism=4, mn=1, mx=4)
    assert scale_dry_run(r, j, 0) == 0
    j2 = make_view(parallelism=6, mn=1, mx=4)
    assert scale_dry_run(r, j2, 0) == -2  # erroneously above max: clamp


# ---- scale_dry_run: scale-down (ref :179-236) -------------------------------


def test_scale_down_beyond_max_clamps():
    r = roomy_cluster()
    j = make_view(parallelism=6, mn=1, mx=4)
    assert scale_dry_run(r, j, 0, scale_down=True) == -2


def test_scale_down_on_cpu_pressure_steps_toward_min():
    r = roomy_cluster(n_nodes=1, cpu=4000)
    r.cpu_request_milli = 4000  # 100% > max_load 0.97
    j = make_view(parallelism=3, mn=1, mx=4, cpu=1000)
    assert scale_dry_run(r, j, 0, scale_down=True) == -1
    assert r.cpu_request_milli == 3000  # freed one replica


def test_scale_down_stops_at_min():
    r = roomy_cluster(n_nodes=1, cpu=1000)
    r.cpu_request_milli = 1000
    j = make_view(parallelism=1, mn=1, mx=4)
    assert scale_dry_run(r, j, 0, scale_down=True) == 0


def test_scale_down_idle_cluster_noop():
    r = roomy_cluster()
    j = make_view(parallelism=3, mn=1, mx=4)
    assert scale_dry_run(r, j, 0, scale_down=True) == 0


# ---- slice/batch quantization (TPU-native; SURVEY.md §7.4) ------------------


def test_quantized_step_up_requires_room_for_whole_step():
    j = make_view(parallelism=2, mn=1, mx=8, legal=[1, 2, 4, 8])
    # 16 chips total, 8 in use -> room for exactly 2 more replicas: 2 -> 4 OK
    r = roomy_cluster(n_nodes=4, tpu=4)
    r.tpu_limit = 8
    r.nodes.tpu_free["node-0"] = 0
    r.nodes.tpu_free["node-1"] = 0
    assert scale_dry_run(r, j, 0) == 2
    # room for only 1 more replica: cannot half-step to 3 -> no change
    r2 = roomy_cluster(n_nodes=4, tpu=4)
    r2.tpu_limit = 12
    for n in ("node-0", "node-1", "node-2"):
        r2.nodes.tpu_free[n] = 0
    assert scale_dry_run(r2, j, 0) == 0


def test_quantized_step_down_jumps_to_previous_legal_size():
    r = roomy_cluster(n_nodes=1, cpu=4000)
    r.cpu_request_milli = 4000
    j = make_view(parallelism=4, mn=1, mx=8, cpu=500, legal=[1, 2, 4, 8])
    assert scale_dry_run(r, j, 0, scale_down=True) == -2  # 4 -> 2


def test_legal_sizes_come_from_global_batch(tmp_path):
    job = TrainingJob.from_manifest(
        {
            "apiVersion": "edl.tpu.dev/v1",
            "kind": "TrainingJob",
            "metadata": {"name": "t"},
            "spec": {
                "fault_tolerant": True,
                "global_batch_size": 96,
                "trainer": {
                    "min_instance": 1,
                    "max_instance": 8,
                    "slice_topology": "v5e-4",
                },
            },
        }
    ).validate()
    v = JobView.from_job(job, parallelism=2)
    assert v.legal_sizes == [1, 2, 3, 4, 6, 8]
    assert v.tpu_per_trainer == 4
    assert v.next_size_up(4) == 6


# ---- whole-plan fixed point (ref :256-364) ----------------------------------


def test_plan_grows_all_jobs_to_max_when_idle():
    r = roomy_cluster(n_nodes=8, tpu=8)
    a = make_view("a", parallelism=1, mn=1, mx=3, tpu=8)
    b = make_view("b", parallelism=1, mn=1, mx=3, tpu=8)
    diff = scale_all_jobs_dry_run([a, b], r.deepcopy())
    assert diff == {"a": 2, "b": 2}


def test_plan_splits_scarce_chips_fairly():
    # 8 nodes x 4 chips = 32 chips; two jobs of 4-chip replicas, max 8
    # each -> 64 chips wanted.  The fixed point should balance them.
    r = roomy_cluster(n_nodes=8, tpu=4)
    # charge the two already-running replicas (InquiryResource would)
    r.tpu_limit = 8
    r.nodes.tpu_free["node-0"] = 0
    r.nodes.tpu_free["node-1"] = 0
    a = make_view("a", parallelism=1, mn=1, mx=8)
    b = make_view("b", parallelism=1, mn=1, mx=8)
    diff = scale_all_jobs_dry_run([a, b], r.deepcopy())
    ga = 1 + diff.get("a", 0)
    gb = 1 + diff.get("b", 0)
    assert ga + gb == 8  # all 32 chips used
    assert abs(ga - gb) <= 1


def test_plan_respects_max_load_partial(monkeypatch):
    # maxLoadDesired=0.8 on CPU-only jobs (ref :256-364's 0.8 case).
    r = roomy_cluster(n_nodes=1, cpu=10_000, tpu=0)
    r.cpu_request_milli = 1000  # the one running replica
    r.nodes.cpu_idle_milli["node-0"] -= 1000
    a = make_view("a", parallelism=1, mn=1, mx=10, cpu=1000, tpu=0)
    diff = scale_all_jobs_dry_run([a], r.deepcopy(), max_load_desired=0.8)
    # 1000m used + d * 1000m <= 0.8 * 10000m -> d = 7
    assert diff == {"a": 7}


def test_plan_noop_for_non_elastic():
    r = roomy_cluster()
    a = make_view("a", parallelism=2, mn=2, mx=2)
    assert scale_all_jobs_dry_run([a], r.deepcopy()) == {}


def test_shed_until_under_max_load():
    # CPU oversubscribed: elastic jobs shed, most-fulfilled first, until
    # the load drops under max_load_desired (ref :219-236 semantics).
    r = roomy_cluster(n_nodes=2, cpu=4000, tpu=0)
    r.cpu_request_milli = 9000  # way past 0.97 * 8000
    a = make_view("a", parallelism=4, mn=1, mx=4, cpu=1000, tpu=0)
    b = make_view("b", parallelism=3, mn=1, mx=4, cpu=1000, tpu=0)
    diff = scale_all_jobs_dry_run([a, b], r.deepcopy())
    # a (fulfillment 1.0) sheds to 3 -> 8000m still hot; b sheds to 2 ->
    # 7000m < 7760m -> stop.
    assert diff == {"a": -1, "b": -1}


def test_full_cluster_shed_reaches_min_under_extreme_pressure():
    r = roomy_cluster(n_nodes=2, cpu=4000, tpu=0)
    r.cpu_request_milli = 50_000  # shedding alone can never fix this
    a = make_view("a", parallelism=4, mn=1, mx=4, cpu=1000, tpu=0)
    b = make_view("b", parallelism=3, mn=1, mx=4, cpu=1000, tpu=0)
    diff = scale_all_jobs_dry_run([a, b], r.deepcopy())
    assert diff == {"a": -3, "b": -2}  # both pinned at min, loop terminates


def test_fixed_point_terminates_at_full_tpu_utilization():
    # chips at exactly 100%: the reference's up-to-100%/down-at-97%
    # conditions would oscillate forever; ours must terminate with no
    # change.
    r = roomy_cluster(n_nodes=2, tpu=4)
    r.tpu_limit = 8
    for n in r.nodes.tpu_free:
        r.nodes.tpu_free[n] = 0
    a = make_view("a", parallelism=2, mn=1, mx=4)
    assert scale_all_jobs_dry_run([a], r.deepcopy()) == {}


# ---- pending-demand shedding (TPU-native fix of ref's gap) ------------------


def test_pending_demand_sheds_running_elastic_jobs():
    # All 16 chips in use by an elastic job; a pending job needs 4.
    r = roomy_cluster(n_nodes=4, tpu=4)
    r.tpu_limit = 16
    for n in r.nodes.tpu_free:
        r.nodes.tpu_free[n] = 0
    a = make_view("a", parallelism=4, mn=1, mx=4)
    diff = scale_all_jobs_dry_run(
        [a], r.deepcopy(), pending=PendingDemand(tpu_chips=4)
    )
    assert diff == {"a": -1}


def test_pending_demand_suppresses_scale_up_only_while_starved():
    r = roomy_cluster(n_nodes=4, tpu=4)  # 16 chips, 12 free
    r.tpu_limit = 4
    a = make_view("a", parallelism=1, mn=1, mx=4)
    # demand 16 > 12 free: starved -> no growth
    diff = scale_all_jobs_dry_run(
        [a], r.deepcopy(), pending=PendingDemand(tpu_chips=16)
    )
    assert diff == {}
    # demand 4 <= 12 free: not starved -> growth proceeds, but only up
    # to what keeps the demand reserved (12 free - 4 reserved = 8 chips
    # = 2 replicas)
    diff = scale_all_jobs_dry_run(
        [a], r.deepcopy(), pending=PendingDemand(tpu_chips=4)
    )
    assert diff == {"a": 2}


def test_pending_demand_stops_shedding_once_satisfied():
    r = roomy_cluster(n_nodes=4, tpu=4)
    r.tpu_limit = 16
    for n in r.nodes.tpu_free:
        r.nodes.tpu_free[n] = 0
    a = make_view("a", parallelism=4, mn=1, mx=4)
    b = make_view("b", parallelism=4, mn=1, mx=4)
    diff = scale_all_jobs_dry_run(
        [a, b], r.deepcopy(), pending=PendingDemand(tpu_chips=4)
    )
    # one shed replica frees exactly 4 chips; the other job keeps its 4
    assert sum(diff.values()) == -1


def test_cpu_pending_demand_sheds_cpu_jobs():
    # CPU-only pending job must also force room (the reference only
    # handled this via load inflation; we do it explicitly).
    r = roomy_cluster(n_nodes=2, cpu=4000, tpu=0)
    r.cpu_request_milli = 7000  # 87.5% of 8000: under max_load, so only
    # the explicit demand can trigger the shed
    a = make_view("a", parallelism=3, mn=1, mx=4, cpu=1000, tpu=0)
    diff = scale_all_jobs_dry_run(
        [a], r.deepcopy(), pending=PendingDemand(cpu_milli=2000)
    )
    assert diff == {"a": -2}  # frees 2000m so the pending job fits


def test_memory_oversubscription_sheds():
    # Inventory shrank: memory requests exceed the total -> shed.
    r = roomy_cluster(n_nodes=1, mem=8192, tpu=0)
    r.memory_request_mega = 10000
    a = make_view("a", parallelism=3, mn=1, mx=4, mem=1024, tpu=0)
    assert scale_dry_run(r, a, 0, scale_down=True) == -1


# ---- JobView plumbing -------------------------------------------------------


def test_jobview_from_trainingjob_defaults():
    job = TrainingJob.from_manifest(
        {
            "apiVersion": "edl.tpu.dev/v1",
            "kind": "TrainingJob",
            "metadata": {"name": "x"},
            "spec": {
                "fault_tolerant": True,
                "trainer": {
                    "min_instance": 2,
                    "max_instance": 6,
                    "slice_topology": "v5e-8",
                    "resources": {"requests": {"cpu": "4", "memory": "8Gi"}},
                },
            },
        }
    ).validate()
    v = JobView.from_job(job)
    assert v.parallelism == 2  # defaults to min when no status
    assert v.cpu_request_milli == 4000
    assert v.mem_request_mega == 8192
    assert v.tpu_per_trainer == 8
    assert v.elastic


# ---- slice-aware placement (SURVEY.md §7.1 row 2) ---------------------------


def test_slice_shape_refuses_split_across_pools():
    """16 free chips across two v5e-8 pools cannot host one v5e-16
    replica: chips are only interchangeable within a slice's ICI."""
    r = roomy_cluster(n_nodes=2, tpu=8)
    r.nodes.pool_topology = {"node-0": "v5e-8", "node-1": "v5e-8"}
    j = make_view(tpu=16, mn=1, mx=2)
    j.slice_topology = "v5e-16"
    assert search_assignable_node(r, j) is None
    # A matching v5e-16 pool takes it.
    r2 = roomy_cluster(n_nodes=1, tpu=16)
    r2.nodes.pool_topology = {"node-0": "v5e-16"}
    assert search_assignable_node(r2, j) == "node-0"


def test_slice_topology_match_by_chip_count_for_untyped_jobs():
    """A JobView without a declared topology still refuses pools whose
    slice unit differs from its per-replica chip count."""
    r = roomy_cluster(n_nodes=1, tpu=16)
    r.nodes.pool_topology = {"node-0": "v5e-8"}
    j8 = make_view(tpu=8, mn=1, mx=2)
    j4 = make_view(tpu=4, mn=1, mx=2)
    assert search_assignable_node(r, j8) == "node-0"
    assert search_assignable_node(r, j4) is None  # 4-chip replica, 8-chip slices


def test_slice_aware_dry_run_refuses_cross_pool_growth():
    """End-to-end through scale_dry_run: the step is refused when no
    single pool can host the replica's slice, even with enough total
    free chips."""
    r = roomy_cluster(n_nodes=2, tpu=8)
    r.nodes.pool_topology = {"node-0": "v5e-8", "node-1": "v5e-8"}
    j = make_view(tpu=16, mn=1, mx=2, parallelism=1)
    j.slice_topology = "v5e-16"
    assert scale_dry_run(r, j, 0) == 0


def test_over_max_clamp_lands_on_legal_size():
    """An over-max job clamps to the largest LEGAL size, not bare
    max_instance (which may not be in legal_sizes)."""
    r = roomy_cluster()
    j = make_view(mn=1, mx=6, parallelism=8, legal=[1, 2, 4])
    # scale-up pass clamps over-max plans
    delta = scale_dry_run(r, j, 0, scale_down=False)
    assert j.parallelism + delta == 4  # not 6


# ---- shed capacity returns to victim nodes (VERDICT r3 weak-7) --------------


def test_shed_returns_capacity_to_victim_nodes():
    """A shed replica's capacity comes back on its NODE's maps
    (victim-first pod placement), not just cluster totals."""
    r = roomy_cluster(n_nodes=2, cpu=4000, tpu=4)
    r.cpu_request_milli = 8000  # hot: > 0.97 * 8000
    r.tpu_limit = 8
    r.nodes.tpu_free = {"node-0": 0, "node-1": 0}
    r.nodes.cpu_idle_milli = {"node-0": 0, "node-1": 0}
    a = make_view("a", parallelism=2, mn=1, mx=2, cpu=4000, tpu=4)
    a.pod_nodes = ["node-1", "node-0"]  # newest pod (the victim) on node-1
    assert scale_dry_run(r, a, 0, scale_down=True) == -1
    assert r.nodes.tpu_free == {"node-0": 0, "node-1": 4}
    assert r.nodes.cpu_idle_milli == {"node-0": 0, "node-1": 4000}


def test_freed_victim_node_is_replaceable_same_pass():
    """The fixed point re-places capacity a shed freed: a CPU-hot job
    sheds its newest pod off the TPU node, and the TPU job grows onto
    that node within the SAME dry-run pass (before this fix the node
    maps never got the capacity back and the growth was refused)."""
    r = ClusterResource(
        node_count=2,
        tpu_total=8,
        tpu_limit=4,
        cpu_total_milli=2400,
        cpu_request_milli=2400,  # hot: > 0.97 * 2400
        memory_total_mega=65536,
        nodes=Nodes(
            cpu_idle_milli={"node-0": 50, "node-1": -50},
            memory_free_mega={"node-0": 32768, "node-1": 32768},
            tpu_free={"node-0": 0, "node-1": 4},
        ),
    )
    a = make_view("a", parallelism=2, mn=1, mx=2, cpu=1150, mem=0, tpu=0)
    a.pod_nodes = ["node-1", "node-0"]
    c = make_view("c", parallelism=1, mn=1, mx=2, cpu=100, mem=0, tpu=4)
    c.pod_nodes = ["node-1"]
    assert scale_all_jobs_dry_run([a, c], r) == {"a": -1, "c": 1}


def test_sim_placed_shed_frees_simulated_nodes_not_live_pods():
    """A shed of a replica this dry run itself placed must free the
    simulated placement, leaving real pods' nodes untouched."""
    r = roomy_cluster(n_nodes=2, cpu=8000, tpu=4)
    j = make_view("j", parallelism=1, mn=1, mx=2, cpu=1000, mem=0, tpu=4)
    j.pod_nodes = ["node-0"]
    up = scale_dry_run(r, j, 0)  # grows 1 -> 2, placing on a node
    assert up == 1 and len(j._sim_placed) == 1
    placed = j._sim_placed[0]
    free_before = r.nodes.tpu_free[placed]
    # over-max clamp sheds the simulated replica (spec shrank scenario)
    j.max_instance = 1
    j.legal_sizes = []
    down = scale_dry_run(r, j, up, scale_down=True)
    assert down == -1
    assert r.nodes.tpu_free[placed] == free_before + 4
    assert j.pod_nodes == ["node-0"]  # the live pod was not "freed"


def test_shed_skips_nodes_gone_from_inventory():
    """A victim pod whose node left the inventory frees totals only —
    crediting the vanished node would fabricate schedulable capacity."""
    r = roomy_cluster(n_nodes=1, cpu=4000, tpu=4)
    r.cpu_request_milli = 8000  # hot
    a = make_view("a", parallelism=2, mn=1, mx=2, cpu=4000, tpu=4)
    a.pod_nodes = ["node-gone", "node-0"]
    assert scale_dry_run(r, a, 0, scale_down=True) == -1
    assert "node-gone" not in r.nodes.cpu_idle_milli
    assert "node-gone" not in r.nodes.tpu_free


# ---- actuation prewarm announcement (zero-stall resize) --------------------


def _elastic_job(name="j", lo=2, hi=8):
    return TrainingJob.from_manifest(
        {
            "apiVersion": "edl.tpu.dev/v1",
            "kind": "TrainingJob",
            "metadata": {"name": name},
            "spec": {
                "fault_tolerant": True,
                "trainer": {
                    "entrypoint": "mnist",
                    "min_instance": lo,
                    "max_instance": hi,
                    "slice_topology": "v5e-1",
                },
            },
        }
    ).validate()


def test_actuation_announces_prewarm_before_put():
    """The scaler announces its planned next parallelism through the
    coordinator BEFORE any retarget or parallelism PUT — trainers then
    warm exactly the incoming world size while the actuation is still
    in flight (the prewarm half of the zero-stall resize)."""
    from edl_tpu.autoscaler.scaler import Autoscaler

    log = []

    class RecCluster:
        def update_parallelism(self, job, n):
            log.append(("put", n))

        def delete_pod(self, name):
            return True

    class RecClient:
        def set_prewarm(self, w):
            log.append(("prewarm", w))

        def set_target_world(self, w):
            log.append(("target", w))

        def plan(self):
            return None

        def members(self):
            return []

    job = _elastic_job()
    sc = Autoscaler(RecCluster(), coord_client_factory=lambda j: RecClient())
    sc.jobs = {job.name: job}

    sc._actuate({job.name: 4}, {job.name: 2})  # scale-up
    assert log[0] == ("prewarm", 4)
    assert log.index(("prewarm", 4)) < log.index(("put", 4))
    log.clear()

    sc._actuate({job.name: 2}, {job.name: -2})  # scale-down
    assert log[0] == ("prewarm", 2)  # before retarget AND victim deletion
    assert log.index(("prewarm", 2)) < log.index(("target", 2))
    assert ("put", 2) in log


def test_actuation_tolerates_clients_without_prewarm():
    """Injected coordinator doubles (and older coordinators) may lack
    /prewarm: the announcement must silently no-op, never block the
    actuation itself."""
    from edl_tpu.autoscaler.scaler import Autoscaler

    log = []

    class RecCluster:
        def update_parallelism(self, job, n):
            log.append(("put", n))

        def delete_pod(self, name):
            return True

    class BareClient:  # no set_prewarm
        def set_target_world(self, w):
            log.append(("target", w))

        def plan(self):
            return None

        def members(self):
            return []

    job = _elastic_job()
    sc = Autoscaler(RecCluster(), coord_client_factory=lambda j: BareClient())
    sc.jobs = {job.name: job}
    sc._actuate({job.name: 4}, {job.name: 2})
    assert log == [("put", 4), ("target", 4)]
