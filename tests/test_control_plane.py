"""End-to-end control plane against a kubectl-shaped fake cluster.

The reference's deliverable was a daemon that watches TrainingJobs and
scales them (``cmd/edl/edl.go:47-50``) — but its creation path was a
logged TODO and nothing in-repo could run against a cluster.  This test
drives the FULL loop through the real ``KubectlAPI`` surface:

    edl submit -> CR stored -> edl controller (watch + create + scale)
    -> trainer Job + coordinator exist -> autoscaler grows the elastic
    job to max under an idle cluster -> edl kill -> objects destroyed

backed by ``edl_tpu.cluster.fake_kubectl`` (FakeKube semantics behind
the kubectl CLI, state in a JSON file).
"""

import json
import os
import stat
import subprocess
import sys

import pytest

from edl_tpu.cli import main as cli_main

JOB_YAML = """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: e2e-mnist}
spec:
  fault_tolerant: true
  global_batch_size: 64
  trainer:
    entrypoint: mnist
    min_instance: 1
    max_instance: 4
    slice_topology: v5e-4
    resources:
      requests: {cpu: "1", memory: 1Gi}
"""


@pytest.fixture
def fake_cluster(tmp_path, monkeypatch):
    """A 4-pool x 4-chip fake cluster behind a kubectl shim."""
    state = tmp_path / "kube-state.json"
    state.write_text(
        json.dumps(
            {
                "nodes": [
                    {
                        "name": f"pool-{i}",
                        "cpu_milli": 16000,
                        "memory_mega": 65536,
                        "tpu_chips": 4,
                        "tpu_topology": "2x2",
                    }
                    for i in range(4)
                ]
            }
        )
    )
    shim = tmp_path / "kubectl"
    shim.write_text(
        "#!/bin/sh\n"
        f'exec {sys.executable} -m edl_tpu.cluster.fake_kubectl "$@"\n'
    )
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("EDL_FAKE_KUBE_STATE", str(state))
    monkeypatch.setenv(
        "PYTHONPATH",
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    return {"state": state, "kubectl": str(shim)}


def _state(fake_cluster) -> dict:
    return json.loads(fake_cluster["state"].read_text())


def test_submit_controller_scale_kill(fake_cluster, tmp_path, capsys):
    spec = tmp_path / "job.yaml"
    spec.write_text(JOB_YAML)
    kubectl = fake_cluster["kubectl"]

    # submit: the CR lands in the (fake) API server
    assert cli_main(["submit", str(spec), "--kubectl", kubectl]) == 0
    crs = _state(fake_cluster)["trainingjobs"]
    assert [c["metadata"]["name"] for c in crs] == ["e2e-mnist"]
    capsys.readouterr()  # drop the kubectl apply echo

    # controller: watch sees the CR, creates trainer Job + coordinator,
    # autoscaler grows the elastic job toward max on the idle cluster
    assert (
        cli_main(
            [
                "controller",
                "--kubectl",
                kubectl,
                "--iterations",
                "6",
                "--interval",
                "0",
            ]
        )
        == 0
    )
    statuses = json.loads(capsys.readouterr().out)
    assert statuses[0]["name"] == "e2e-mnist"
    assert statuses[0]["state"] == "Running"

    st = _state(fake_cluster)
    workloads = {w["name"]: w for w in st["workloads"]}
    assert "e2e-mnist-trainer" in workloads
    assert "e2e-mnist-coordinator" in workloads
    assert [s["metadata"]["name"] for s in st["services"]] == [
        "e2e-mnist-coordinator"
    ]
    # Idle cluster, elastic 1..4, 4 chips/trainer on 4x4-chip pools:
    # the dry-run fixed point must fill the cluster (BASELINE config 2).
    assert workloads["e2e-mnist-trainer"]["parallelism"] == 4
    trainer_pods = [
        p for p in st["pods"] if p["job_name"] == "e2e-mnist"
    ]
    assert len(trainer_pods) == 4
    assert all(p["phase"] == "Running" for p in trainer_pods)

    # kill: CR deleted; the next controller pass destroys the objects
    assert cli_main(["kill", "e2e-mnist", "--kubectl", kubectl]) == 0
    capsys.readouterr()
    assert (
        cli_main(
            [
                "controller",
                "--kubectl",
                kubectl,
                "--iterations",
                "2",
                "--interval",
                "0",
            ]
        )
        == 0
    )
    st = _state(fake_cluster)
    assert st["workloads"] == []
    assert st["trainingjobs"] == []
    assert st["services"] == []


def test_kubectl_api_surface(fake_cluster):
    """KubectlAPI parsing against the kubectl-shaped responses."""
    from edl_tpu.cluster.kube import KubectlAPI, WorkloadInfo

    api = KubectlAPI(kubectl=fake_cluster["kubectl"])
    nodes = api.list_nodes()
    assert len(nodes) == 4
    assert nodes[0].cpu_milli == 16000
    assert nodes[0].tpu_chips == 4
    assert nodes[0].tpu_topology == "2x2"

    api.apply_manifests(
        [
            {
                "apiVersion": "batch/v1",
                "kind": "Job",
                "metadata": {"name": "t-trainer", "labels": {"edl-job": "t"}},
                "spec": {
                    "parallelism": 2,
                    "template": {
                        "spec": {
                            "containers": [
                                {
                                    "resources": {
                                        "requests": {
                                            "cpu": "500m",
                                            "memory": "1Gi",
                                        },
                                        "limits": {"google.com/tpu": "4"},
                                    }
                                }
                            ]
                        }
                    },
                },
            }
        ]
    )
    w = api.get_workload("t-trainer")
    assert w is not None and w.parallelism == 2 and w.tpu_limit == 4

    w.parallelism = 3
    api.update_workload(w)
    assert api.get_workload("t-trainer").parallelism == 3

    pods = api.list_pods()
    assert sum(1 for p in pods if p.job_name == "t") == 3

    # stale resourceVersion maps to ConflictError
    from edl_tpu.cluster.kube import ConflictError

    stale = WorkloadInfo(
        name="t-trainer", job_name="t", parallelism=5, resource_version=1
    )
    with pytest.raises(ConflictError):
        api.update_workload(stale)

    assert api.delete_workload("t-trainer") is True
    assert api.get_workload("t-trainer") is None
