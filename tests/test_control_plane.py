"""End-to-end control plane against a kubectl-shaped fake cluster.

The reference's deliverable was a daemon that watches TrainingJobs and
scales them (``cmd/edl/edl.go:47-50``) — but its creation path was a
logged TODO and nothing in-repo could run against a cluster.  This test
drives the FULL loop through the real ``KubectlAPI`` surface:

    edl submit -> CR stored -> edl controller (watch + create + scale)
    -> trainer Job + coordinator exist -> autoscaler grows the elastic
    job to max under an idle cluster -> edl kill -> objects destroyed

backed by ``edl_tpu.cluster.fake_kubectl`` (FakeKube semantics behind
the kubectl CLI, state in a JSON file).
"""

import json
import os
import stat
import sys

import pytest

from edl_tpu.cli import main as cli_main

JOB_YAML = """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: e2e-mnist}
spec:
  fault_tolerant: true
  global_batch_size: 64
  trainer:
    entrypoint: mnist
    min_instance: 1
    max_instance: 4
    slice_topology: v5e-4
    resources:
      requests: {cpu: "1", memory: 1Gi}
"""


@pytest.fixture
def fake_cluster(tmp_path, monkeypatch):
    """A 4-pool x 4-chip fake cluster behind a kubectl shim."""
    state = tmp_path / "kube-state.json"
    state.write_text(
        json.dumps(
            {
                "nodes": [
                    {
                        "name": f"pool-{i}",
                        "cpu_milli": 16000,
                        "memory_mega": 65536,
                        "tpu_chips": 4,
                        "tpu_topology": "2x2",
                    }
                    for i in range(4)
                ]
            }
        )
    )
    shim = tmp_path / "kubectl"
    shim.write_text(
        "#!/bin/sh\n"
        f'exec {sys.executable} -m edl_tpu.cluster.fake_kubectl "$@"\n'
    )
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("EDL_FAKE_KUBE_STATE", str(state))
    monkeypatch.setenv(
        "PYTHONPATH",
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    return {"state": state, "kubectl": str(shim)}


def _state(fake_cluster) -> dict:
    return json.loads(fake_cluster["state"].read_text())


def test_submit_controller_scale_kill(fake_cluster, tmp_path, capsys):
    spec = tmp_path / "job.yaml"
    spec.write_text(JOB_YAML)
    kubectl = fake_cluster["kubectl"]

    # submit: the CR lands in the (fake) API server
    assert cli_main(["submit", str(spec), "--kubectl", kubectl]) == 0
    crs = _state(fake_cluster)["trainingjobs"]
    assert [c["metadata"]["name"] for c in crs] == ["e2e-mnist"]
    capsys.readouterr()  # drop the kubectl apply echo

    # controller: watch sees the CR, creates trainer Job + coordinator,
    # autoscaler grows the elastic job toward max on the idle cluster
    assert (
        cli_main(
            [
                "controller",
                "--kubectl",
                kubectl,
                "--iterations",
                "6",
                "--interval",
                "0",
            ]
        )
        == 0
    )
    out = json.loads(capsys.readouterr().out)
    statuses = out["jobs"]
    assert statuses[0]["name"] == "e2e-mnist"
    assert statuses[0]["state"] == "Running"
    # north-star metrics ride the statuses JSON (BASELINE.md)
    assert out["cluster"]["tpu_utilization"] == 1.0  # 16/16 chips in use
    assert "pending_p50_s" in out["cluster"]

    st = _state(fake_cluster)
    # Status writeback: the CR's status subresource carries the state
    # machine (the reference declared TrainingJobStatus and never wrote
    # it) — `kubectl get trainingjobs` tells the truth.
    cr = next(c for c in st["trainingjobs"] if c["metadata"]["name"] == "e2e-mnist")
    assert cr.get("status", {}).get("state") == "Running"
    assert cr["status"]["parallelism"] == 4
    workloads = {w["name"]: w for w in st["workloads"]}
    assert "e2e-mnist-trainer" in workloads
    assert "e2e-mnist-coordinator" in workloads
    # workloads carry the CR's owner identity for GC (labels + k8s
    # ownerReferences via the CR uid fake-kubectl assigned on apply)
    assert workloads["e2e-mnist-trainer"]["owner"] == "e2e-mnist"
    assert workloads["e2e-mnist-coordinator"]["owner"] == "e2e-mnist"
    assert [s["metadata"]["name"] for s in st["services"]] == [
        "e2e-mnist-coordinator"
    ]
    # Idle cluster, elastic 1..4, 4 chips/trainer on 4x4-chip pools:
    # the dry-run fixed point must fill the cluster (BASELINE config 2).
    assert workloads["e2e-mnist-trainer"]["parallelism"] == 4
    trainer_pods = [
        p for p in st["pods"] if p["job_name"] == "e2e-mnist"
    ]
    assert len(trainer_pods) == 4
    assert all(p["phase"] == "Running" for p in trainer_pods)

    # kill: CR deleted; the next controller pass destroys the objects
    assert cli_main(["kill", "e2e-mnist", "--kubectl", kubectl]) == 0
    capsys.readouterr()
    assert (
        cli_main(
            [
                "controller",
                "--kubectl",
                kubectl,
                "--iterations",
                "2",
                "--interval",
                "0",
            ]
        )
        == 0
    )
    st = _state(fake_cluster)
    assert st["workloads"] == []
    assert st["trainingjobs"] == []
    assert st["services"] == []


def test_actuation_handshake_e2e(fake_cluster, tmp_path, capsys, monkeypatch):
    """The two halves form a system: submitting an elastic job and
    running ``edl controller`` grows the *coordinator's plan* to world
    4 — no test code calls ``set_target_world`` (VERDICT r2 #1).  The
    coordinator is a real ``CoordinatorServer``; the controller finds
    it through ``EDL_COORD_ADDR_TEMPLATE`` (the cluster-DNS stand-in)."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(target_world=1, max_world=4, heartbeat_timeout=60)
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start(evict=False)
    try:
        monkeypatch.setenv(
            "EDL_COORD_ADDR_TEMPLATE", f"127.0.0.1:{server.port}"
        )
        # the job's 4 trainer pods come up and register
        for i in range(4):
            coord.register(f"t{i}")
        assert coord.plan().world_size == 1  # capped by the initial target

        spec = tmp_path / "job.yaml"
        spec.write_text(JOB_YAML)
        kubectl = fake_cluster["kubectl"]
        assert cli_main(["submit", str(spec), "--kubectl", kubectl]) == 0
        assert (
            cli_main(
                [
                    "controller",
                    "--kubectl",
                    kubectl,
                    "--iterations",
                    "6",
                    "--interval",
                    "0",
                ]
            )
            == 0
        )
        capsys.readouterr()
        # the autoscaler's PUT reached parallelism 4 AND the handshake
        # retargeted the coordinator: the plan itself is world 4
        assert coord.target_world() == 4
        assert coord.plan().world_size == 4
        # and the actuation announced its prewarm hint over the new
        # /prewarm endpoint before actuating (zero-stall resize):
        # trainers polling the plan see the incoming size to warm
        assert coord.prewarm_hint() == 4
        assert coord.plan().prewarm == 4
    finally:
        server.stop()


FIT_A_LINE_YAML = """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: fit-a-line}
spec:
  trainer:
    entrypoint: fit_a_line
    min_instance: 1
    max_instance: 1
    slice_topology: v5e-1
    resources:
      requests: {cpu: "1", memory: 1Gi}
"""


def test_completion_via_coordinator(monkeypatch):
    """BASELINE config 1 (min=max=1, run to completion): the trainer
    reports completion through the coordinator; the controller marks
    Succeed and tears the coordinator down, keeping the trainer
    workload (ref Complete, pkg/trainingjober.go:126-132 — which the
    reference never wired; VERDICT r2 #6)."""
    from edl_tpu.autoscaler.scaler import Autoscaler
    from edl_tpu.cluster.cluster import Cluster
    from edl_tpu.cluster.kube import FakeKube, NodeInfo
    from edl_tpu.controller.controller import Controller
    from edl_tpu.resource.training_job import TrainingJob
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(target_world=1, max_world=1)
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start(evict=False)
    try:
        monkeypatch.setenv(
            "EDL_COORD_ADDR_TEMPLATE", f"127.0.0.1:{server.port}"
        )
        kube = FakeKube(
            [NodeInfo(name="pool-0", cpu_milli=8000, memory_mega=32768, tpu_chips=4)]
        )
        cluster = Cluster(kube)
        ctrl = Controller(cluster, Autoscaler(cluster))
        ctrl.on_add(TrainingJob.from_yaml(FIT_A_LINE_YAML))
        ctrl.run_once()
        assert ctrl.jobs["fit-a-line"].status.state.value == "Running"

        # the launcher finishes the job's passes -> reports completion
        coord.report_complete(step=100)
        ctrl.run_once()
        assert ctrl.jobs["fit-a-line"].status.state.value == "Succeed"
        # coordinator gone, trainer workload kept (ref Complete semantics)
        assert kube.get_workload("fit-a-line-coordinator") is None
        assert kube.get_workload("fit-a-line-trainer") is not None
    finally:
        server.stop()


def test_completion_via_terminal_pods():
    """Completion without a reachable coordinator: every trainer pod
    ran to completion (RestartPolicy Never) -> Succeed."""
    from edl_tpu.autoscaler.scaler import Autoscaler
    from edl_tpu.cluster.cluster import Cluster
    from edl_tpu.cluster.kube import FakeKube, NodeInfo
    from edl_tpu.controller.controller import Controller
    from edl_tpu.resource.training_job import TrainingJob

    kube = FakeKube(
        [NodeInfo(name="pool-0", cpu_milli=8000, memory_mega=32768, tpu_chips=4)]
    )
    cluster = Cluster(kube)
    ctrl = Controller(cluster, Autoscaler(cluster))
    ctrl.on_add(TrainingJob.from_yaml(FIT_A_LINE_YAML))
    ctrl.run_once()
    assert ctrl.jobs["fit-a-line"].status.state.value == "Running"

    kube.complete_pods("fit-a-line")
    ctrl.run_once()
    assert ctrl.jobs["fit-a-line"].status.state.value == "Succeed"


def test_kubectl_api_surface(fake_cluster):
    """KubectlAPI parsing against the kubectl-shaped responses."""
    from edl_tpu.cluster.kube import KubectlAPI, WorkloadInfo

    api = KubectlAPI(kubectl=fake_cluster["kubectl"])
    nodes = api.list_nodes()
    assert len(nodes) == 4
    assert nodes[0].cpu_milli == 16000
    assert nodes[0].tpu_chips == 4
    assert nodes[0].tpu_topology == "2x2"

    api.apply_manifests(
        [
            {
                "apiVersion": "batch/v1",
                "kind": "Job",
                "metadata": {"name": "t-trainer", "labels": {"edl-job": "t"}},
                "spec": {
                    "parallelism": 2,
                    "template": {
                        "spec": {
                            "containers": [
                                {
                                    "resources": {
                                        "requests": {
                                            "cpu": "500m",
                                            "memory": "1Gi",
                                        },
                                        "limits": {"google.com/tpu": "4"},
                                    }
                                }
                            ]
                        }
                    },
                },
            }
        ]
    )
    w = api.get_workload("t-trainer")
    assert w is not None and w.parallelism == 2 and w.tpu_limit == 4

    w.parallelism = 3
    api.update_workload(w)
    assert api.get_workload("t-trainer").parallelism == 3

    pods = api.list_pods()
    assert sum(1 for p in pods if p.job_name == "t") == 3

    # stale resourceVersion maps to ConflictError
    from edl_tpu.cluster.kube import ConflictError

    stale = WorkloadInfo(
        name="t-trainer", job_name="t", parallelism=5, resource_version=1
    )
    with pytest.raises(ConflictError):
        api.update_workload(stale)

    assert api.delete_workload("t-trainer") is True
    assert api.get_workload("t-trainer") is None
