"""Deployable parallelism layouts (VERDICT r4 #1): the TrainingJob's
``parallelism`` field drives the elastic runtime's mesh — tp/fsdp/sp
layouts reachable from a submitted job, not just from tests.

Reference contrast: the trainer spec was the reference's entire
parallelism interface and it only expressed a flat data-parallel pool
(``pkg/resource/training_job.go:128-134``); our spec generalizes it to
dp x fsdp x tp x sp x ep x pp meshes with dp as the elastic remainder.
"""

import numpy as np
import optax
import pytest

from edl_tpu.models.base import bind_model
from edl_tpu.resource.training_job import (
    ParallelismSpec,
    TrainingJob,
    ValidationError,
    quantized_world_sizes,
)
from edl_tpu.runtime.coordinator import LocalCoordinator
from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
from edl_tpu.runtime.elastic import ElasticTrainer


def _job(parallelism=None, **spec_over):
    spec = {
        "image": "edl-tpu/trainer:latest",
        "fault_tolerant": True,
        "global_batch_size": spec_over.pop("global_batch_size", 64),
        "trainer": {
            "entrypoint": "mnist",
            "min_instance": spec_over.pop("min_instance", 1),
            "max_instance": spec_over.pop("max_instance", 4),
            "slice_topology": spec_over.pop("slice_topology", "v5e-4"),
            **({"parallelism": parallelism} if parallelism else {}),
        },
    }
    spec.update(spec_over)
    return TrainingJob.from_manifest(
        {
            "apiVersion": "edl.tpu.dev/v1",
            "kind": "TrainingJob",
            "metadata": {"name": "layout-job"},
            "spec": spec,
        }
    )


# ---- spec parsing + validation --------------------------------------------


def test_parallelism_spec_roundtrip():
    p = ParallelismSpec.from_dict({"fsdp": 2, "tp": 2})
    assert p.axes() == {"fsdp": 2, "tp": 2}
    assert p.product() == 4
    assert p.nonbatch_product() == 2  # fsdp carries batch rows
    assert ParallelismSpec.from_env(p.env_value()).axes() == p.axes()
    assert ParallelismSpec.from_env("").trivial()


def test_parallelism_spec_rejects_unknown_axis():
    with pytest.raises(ValidationError, match="dp is implicit"):
        ParallelismSpec.from_dict({"dp": 2})
    with pytest.raises(ValidationError):
        ParallelismSpec.from_dict({"zz": 2})


def test_validate_layout_must_divide_world_devices():
    # v5e-4 chips, min=1: 4 devices at min; product 8 cannot divide.
    with pytest.raises(ValidationError, match="must divide"):
        _job({"fsdp": 8}).validate()
    # product 4 divides both endpoints (4 and 16 devices)
    _job({"fsdp": 2, "tp": 2}).validate()


def test_validate_layout_batch_extent():
    # tp replicates the batch: extent at min is 4*1/2 = 2 -> 64 ok;
    # a batch of 6 is not divisible by extent 2... use an odd batch.
    _job({"tp": 2}, global_batch_size=64).validate()
    with pytest.raises(ValidationError, match="batch"):
        _job({"tp": 4}, global_batch_size=6).validate()


def test_manifest_roundtrip_preserves_layout():
    job = _job({"fsdp": 2, "tp": 2}).validate()
    m = job.to_manifest()
    back = TrainingJob.from_manifest(m)
    assert back.spec.trainer.parallelism.axes() == {"fsdp": 2, "tp": 2}


def test_legal_world_sizes_quantize_on_layout():
    # chips=4/replica, fsdp=2, tp=2 (product 4): every world's devices
    # factor (w*4 % 4 == 0); batch extent = w*4/2 = 2w -> gbs 64 needs
    # 2w | 64.
    job = _job({"fsdp": 2, "tp": 2}, max_instance=4).validate()
    assert job.legal_world_sizes() == [1, 2, 4]
    # With 1 device per trainer (local sim), product 4 must divide w.
    assert job.legal_world_sizes(chips_per_replica=1) == [4]
    # Direct helper: sp replicates batch; product 3 quantizes worlds.
    assert quantized_world_sizes(
        1, 6, 1, 0, ParallelismSpec.from_dict({"sp": 3})
    ) == [3, 6]


def test_pod_env_renders_parallelism():
    from edl_tpu.controller.jobparser import pod_env

    job = _job({"fsdp": 2}).validate()
    env = {e["name"]: e.get("value") for e in pod_env(job)}
    assert env["EDL_PARALLELISM"] == "fsdp=2"


# ---- bind_model ------------------------------------------------------------


def test_bind_model_rejects_unsupported_axes():
    # fit_a_line: no partition rules -> tp/fsdp layouts shard nothing
    with pytest.raises(ValueError, match="partition rules"):
        bind_model("fit_a_line", {"fsdp": 2})
    # and no sp_mesh kwarg -> sp layout unsupported
    with pytest.raises(ValueError, match="does not support"):
        bind_model("fit_a_line", {"sp": 2})
    with pytest.raises(ValueError, match="unknown model"):
        bind_model("no_such_model", {})


def test_bind_model_passes_mesh_to_sp_family(devices8):
    from edl_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec.create(dp=2, sp=2), devices8[:4])
    factory = bind_model("transformer_lm", {"sp": 2}, tiny=True)
    probe = factory(None)  # mesh-free instance for data shapes
    assert probe.name == "transformer_lm"
    bound = factory(mesh)
    assert bound.name == "transformer_lm"


# ---- elastic runtime with a layout ----------------------------------------


def _elastic_with_layout(layout, gbs=32, target=4, legal=(2, 4, 8)):
    factory = bind_model("mnist", layout)
    model = factory(None)
    ds = synthetic_dataset(model.synth_batch, 256, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=gbs, seed=0)
    coord = LocalCoordinator(
        target_world=target, max_world=8, legal_sizes=list(legal)
    )
    for i in range(8):
        coord.register(f"tr{i}")
    et = ElasticTrainer(
        factory,
        optax.adam(1e-3),
        it,
        coord,
        checkpoint_interval=5,
        layout=layout,
    )
    return et, coord


def test_elastic_layout_builds_sharded_mesh(devices8):
    et, coord = _elastic_with_layout({"fsdp": 2})
    et.run(4)
    sizes = dict(zip(et.mesh.axis_names, et.mesh.devices.shape))
    assert sizes == {"dp": 2, "fsdp": 2}
    k = et.state.params["Dense_0"]["kernel"]
    # params actually sharded over fsdp (half the rows per shard)
    assert k.addressable_shards[0].data.shape[0] == k.shape[0] // 2


def test_elastic_layout_graceful_resize_loss_continuity(devices8):
    """dp x fsdp world resizes 4 -> 8 -> 4 trainers gracefully with a
    loss trajectory identical to never resizing (VERDICT r4 #1 done
    criterion, single-process form; the cross-process form is
    tests/test_multipod.py::test_multipod_layout_fsdp_1_2_1)."""
    ref, _ = _elastic_with_layout({"fsdp": 2})
    ref_hist = ref.run(18)

    et, coord = _elastic_with_layout({"fsdp": 2})
    et.run(6)
    coord.set_target_world(8)
    et.run(12)
    coord.set_target_world(4)
    hist = et.run(18)

    assert [r.step for r in hist] == list(range(18))
    worlds = [r.world_size for r in hist]
    assert worlds[5] == 4 and worlds[6] == 8 and worlds[-1] == 4
    # Bit-identical while the world is unchanged; after a world change
    # the dp allreduce's reduction ORDER differs, so the bf16 convnet's
    # trajectories diverge at ~1e-4 absolute — continuity, not equality,
    # is the invariant (fit_a_line's f32 exactness is asserted in
    # test_elastic.py::test_graceful_resize_loss_continuity).
    np.testing.assert_allclose(
        [r.loss for r in hist[:6]], [r.loss for r in ref_hist[:6]], rtol=0
    )
    np.testing.assert_allclose(
        [r.loss for r in hist], [r.loss for r in ref_hist], atol=2e-3
    )
    assert hist[-1].loss < hist[0].loss * 0.05  # converged through resizes
    # every post-formation resize was graceful with zero replay
    for ev in et.resize_events[1:]:
        assert ev.graceful and ev.replayed_steps == 0


def test_elastic_layout_world_not_factoring_is_config_error(devices8):
    # legal sizes that do NOT quantize on the layout (product 2 cannot
    # divide world 3) must surface as a loud config error, not a hang
    et, coord = _elastic_with_layout({"fsdp": 2}, target=3, legal=(3,))
    with pytest.raises(RuntimeError, match="does not factor"):
        et.run(2)


def test_elastic_sp_layout_ring_attention_trains(devices8):
    """A deployed sp layout: transformer_lm rebuilt per mesh with ring
    attention bound to each generation's mesh (the model-factory path)."""
    factory = bind_model("transformer_lm", {"sp": 2}, tiny=True)
    model = factory(None)
    ds = synthetic_dataset(model.synth_batch, 64, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=8, seed=0)
    coord = LocalCoordinator(target_world=4, max_world=8, legal_sizes=[2, 4, 8])
    for i in range(8):
        coord.register(f"tr{i}")
    et = ElasticTrainer(
        factory,
        optax.adam(1e-3),
        it,
        coord,
        checkpoint_interval=4,
        layout={"sp": 2},
    )
    et.run(3)
    sizes = dict(zip(et.mesh.axis_names, et.mesh.devices.shape))
    assert sizes == {"dp": 2, "sp": 2}
    # model instance is mesh-bound: the sp family was rebuilt per mesh
    coord.set_target_world(8)
    hist = et.run(6)
    assert dict(zip(et.mesh.axis_names, et.mesh.devices.shape)) == {
        "dp": 4,
        "sp": 2,
    }
    assert all(np.isfinite(r.loss) for r in hist)
    assert [r.step for r in hist] == list(range(6))
