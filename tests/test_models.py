"""Model-family tests: Transformer-base and ResNet-50 (BASELINE.md
configs 3-4) — forward/loss correctness, learnability, and real
tensor-parallel sharding on a dp x fsdp x tp mesh."""

import jax
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from edl_tpu.models import get_model
from edl_tpu.parallel.mesh import MeshSpec, build_mesh, dp_mesh
from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
from edl_tpu.runtime.train import Trainer


@pytest.fixture(scope="module")
def tiny_transformer():
    return get_model("transformer_base", tiny=True)


@pytest.fixture(scope="module")
def tiny_resnet():
    return get_model("resnet50", tiny=True)


def test_transformer_forward_shapes(tiny_transformer):
    m = tiny_transformer
    params = m.init_params(jax.random.key(0))
    batch = m.synth_batch(np.random.RandomState(0), 4)
    loss, aux = m.loss_fn(params, batch, jax.random.key(1))
    assert np.isfinite(float(loss))
    assert 0.0 <= float(aux["token_accuracy"]) <= 1.0


def test_transformer_learns(tiny_transformer):
    m = tiny_transformer
    mesh = dp_mesh(2)
    tr = Trainer(m, optax.adam(3e-3), mesh)
    state = tr.init_state()
    data = ShardedDataIterator(
        synthetic_dataset(m.synth_batch, 256), global_batch_size=32
    )
    first = None
    for step in range(30):
        batch = data.device_batch(step, mesh)
        state, metrics = tr.step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first - 0.5, f"no learning: {first} -> {last}"


def test_transformer_partition_rules_cover_all_leaves(tiny_transformer):
    m = tiny_transformer
    params = m.init_params(jax.random.key(0))
    specs = m.param_partition(params)
    p_leaves = jax.tree_util.tree_leaves(params)
    s_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    assert len(p_leaves) == len(s_leaves)
    # every spec is rank-compatible with its tensor
    for leaf, spec in zip(p_leaves, s_leaves):
        assert isinstance(spec, P)
        assert len(spec) <= leaf.ndim


def test_transformer_tensor_parallel_sharding():
    """On a dp2 x fsdp2 x tp2 mesh the FFN kernels must actually be
    sharded (local shard smaller than the global tensor) and one train
    step must run."""
    m = get_model("transformer_base", tiny=True)
    mesh = build_mesh(MeshSpec.create(dp=2, fsdp=2, tp=2))
    tr = Trainer(m, optax.sgd(1e-3), mesh)
    state = tr.init_state()

    wi = state.params["enc_0"]["mlp"]["wi"]["kernel"]
    shard = wi.addressable_shards[0].data
    assert shard.shape[0] * shard.shape[1] < wi.shape[0] * wi.shape[1], (
        f"FFN kernel not sharded: global {wi.shape}, shard {shard.shape}"
    )

    data = ShardedDataIterator(
        synthetic_dataset(m.synth_batch, 128), global_batch_size=16
    )
    batch = data.device_batch(0, mesh, batch_axes=("dp", "fsdp"))
    state2, metrics = tr.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    # shardings preserved across the step
    wi2 = state2.params["enc_0"]["mlp"]["wi"]["kernel"]
    assert wi2.sharding == wi.sharding


def test_transformer_elastic_resize_with_sharded_state():
    """Resize a model-sharded job 2 -> 4 devices: restore must re-lay
    out every leaf onto the new mesh (SURVEY.md §7.4's hard part)."""
    import optax

    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.elastic import ElasticTrainer

    m = get_model("transformer_base", tiny=True)
    data = ShardedDataIterator(
        synthetic_dataset(m.synth_batch, 256), global_batch_size=32
    )
    coord = LocalCoordinator(target_world=2, max_world=4)
    for i in range(4):
        coord.register(f"t{i}")
    et = ElasticTrainer(m, optax.adam(1e-3), data, coord, checkpoint_interval=4)
    et.run(6)
    l_before = et.history[-1].loss
    coord.set_target_world(4)
    et.run(12)
    assert et.resize_events[-1].world_size == 4
    assert et.history[-1].loss < l_before + 0.5  # continuity


def test_resnet_forward_and_step(tiny_resnet):
    m = tiny_resnet
    mesh = dp_mesh(2)
    tr = Trainer(m, optax.adam(1e-3), mesh)
    state = tr.init_state()
    data = ShardedDataIterator(
        synthetic_dataset(m.synth_batch, 128), global_batch_size=16
    )
    first = last = None
    for step in range(10):
        batch = data.device_batch(step, mesh)
        state, metrics = tr.step(state, batch)
        if first is None:
            first = float(metrics["loss"])
        last = float(metrics["loss"])
    assert np.isfinite(last)
    assert last < first, f"no learning: {first} -> {last}"


def test_full_size_models_construct():
    """Full-size configs build (shape math only — no full init)."""
    t = get_model("transformer_base")
    r = get_model("resnet50")
    assert t.flops_per_example > 1e9
    assert r.flops_per_example > 1e9
    # abstract init to validate shapes without allocating
    shapes = jax.eval_shape(t.init_params, jax.random.key(0))
    n_params = sum(
        np.prod(l.shape) for l in jax.tree_util.tree_leaves(shapes)
    )
    assert 4e7 < n_params < 1.2e8, f"transformer-base params {n_params:,}"
    shapes = jax.eval_shape(r.init_params, jax.random.key(0))
    n_params = sum(
        np.prod(l.shape) for l in jax.tree_util.tree_leaves(shapes)
    )
    assert 2e7 < n_params < 4e7, f"resnet50 params {n_params:,}"
