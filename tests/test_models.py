"""Model-family tests: Transformer-base and ResNet-50 (BASELINE.md
configs 3-4) — forward/loss correctness, learnability, and real
tensor-parallel sharding on a dp x fsdp x tp mesh."""

import jax
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from edl_tpu.models import get_model
from edl_tpu.parallel.mesh import MeshSpec, build_mesh, dp_mesh
from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
from edl_tpu.runtime.train import Trainer


@pytest.fixture(scope="module")
def tiny_transformer():
    return get_model("transformer_base", tiny=True)


@pytest.fixture(scope="module")
def tiny_resnet():
    return get_model("resnet50", tiny=True)


def test_transformer_forward_shapes(tiny_transformer):
    m = tiny_transformer
    params = m.init_params(jax.random.key(0))
    batch = m.synth_batch(np.random.RandomState(0), 4)
    loss, aux = m.loss_fn(params, batch, jax.random.key(1))
    assert np.isfinite(float(loss))
    assert 0.0 <= float(aux["token_accuracy"]) <= 1.0


def test_transformer_learns(tiny_transformer):
    m = tiny_transformer
    mesh = dp_mesh(2)
    tr = Trainer(m, optax.adam(3e-3), mesh)
    state = tr.init_state()
    data = ShardedDataIterator(
        synthetic_dataset(m.synth_batch, 256), global_batch_size=32
    )
    first = None
    for step in range(30):
        batch = data.device_batch(step, mesh)
        state, metrics = tr.step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first - 0.5, f"no learning: {first} -> {last}"


def test_transformer_partition_rules_cover_all_leaves(tiny_transformer):
    m = tiny_transformer
    params = m.init_params(jax.random.key(0))
    specs = m.param_partition(params)
    p_leaves = jax.tree_util.tree_leaves(params)
    s_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    assert len(p_leaves) == len(s_leaves)
    # every spec is rank-compatible with its tensor
    for leaf, spec in zip(p_leaves, s_leaves):
        assert isinstance(spec, P)
        assert len(spec) <= leaf.ndim


def test_transformer_tensor_parallel_sharding():
    """On a dp2 x fsdp2 x tp2 mesh the FFN kernels must actually be
    sharded (local shard smaller than the global tensor) and one train
    step must run."""
    m = get_model("transformer_base", tiny=True)
    mesh = build_mesh(MeshSpec.create(dp=2, fsdp=2, tp=2))
    tr = Trainer(m, optax.sgd(1e-3), mesh)
    state = tr.init_state()

    wi = state.params["enc_0"]["mlp"]["wi"]["kernel"]
    shard = wi.addressable_shards[0].data
    assert shard.shape[0] * shard.shape[1] < wi.shape[0] * wi.shape[1], (
        f"FFN kernel not sharded: global {wi.shape}, shard {shard.shape}"
    )

    data = ShardedDataIterator(
        synthetic_dataset(m.synth_batch, 128), global_batch_size=16
    )
    batch = data.device_batch(0, mesh, batch_axes=("dp", "fsdp"))
    state2, metrics = tr.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    # shardings preserved across the step
    wi2 = state2.params["enc_0"]["mlp"]["wi"]["kernel"]
    assert wi2.sharding == wi.sharding


def test_transformer_elastic_resize_with_sharded_state():
    """Resize a model-sharded job 2 -> 4 devices: restore must re-lay
    out every leaf onto the new mesh (SURVEY.md §7.4's hard part)."""
    import optax

    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.elastic import ElasticTrainer

    m = get_model("transformer_base", tiny=True)
    data = ShardedDataIterator(
        synthetic_dataset(m.synth_batch, 256), global_batch_size=32
    )
    coord = LocalCoordinator(target_world=2, max_world=4)
    for i in range(4):
        coord.register(f"t{i}")
    et = ElasticTrainer(m, optax.adam(1e-3), data, coord, checkpoint_interval=4)
    et.run(6)
    l_before = et.history[-1].loss
    coord.set_target_world(4)
    et.run(12)
    assert et.resize_events[-1].world_size == 4
    assert et.history[-1].loss < l_before + 0.5  # continuity


def test_resnet_forward_and_step(tiny_resnet):
    m = tiny_resnet
    mesh = dp_mesh(2)
    tr = Trainer(m, optax.adam(1e-3), mesh)
    state = tr.init_state()
    data = ShardedDataIterator(
        synthetic_dataset(m.synth_batch, 128), global_batch_size=16
    )
    first = last = None
    for step in range(10):
        batch = data.device_batch(step, mesh)
        state, metrics = tr.step(state, batch)
        if first is None:
            first = float(metrics["loss"])
        last = float(metrics["loss"])
    assert np.isfinite(last)
    assert last < first, f"no learning: {first} -> {last}"


def test_full_size_models_construct():
    """Full-size configs build (shape math only — no full init)."""
    t = get_model("transformer_base")
    r = get_model("resnet50")
    assert t.flops_per_example > 1e9
    assert r.flops_per_example > 1e9
    # abstract init to validate shapes without allocating
    shapes = jax.eval_shape(t.init_params, jax.random.key(0))
    n_params = sum(
        np.prod(l.shape) for l in jax.tree_util.tree_leaves(shapes)
    )
    assert 4e7 < n_params < 1.2e8, f"transformer-base params {n_params:,}"
    shapes = jax.eval_shape(r.init_params, jax.random.key(0))
    n_params = sum(
        np.prod(l.shape) for l in jax.tree_util.tree_leaves(shapes)
    )
    assert 2e7 < n_params < 4e7, f"resnet50 params {n_params:,}"


# ---- MoE / expert parallelism (beyond the reference's scope) ----------------


def test_moe_dispatch_combine_exact_vs_dense():
    """With one expert and ample capacity, the dispatch/combine einsum
    routing must reproduce a plain dense MLP exactly: every token goes
    to expert 0 at gate 1.0, so MoEMlp(x) == gelu(x @ wi[0]) @ wo[0]."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.models.moe import MoEMlp

    mod = MoEMlp(d_model=16, d_ff=32, num_experts=1, capacity_factor=2.0,
                 dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    params = mod.init(jax.random.PRNGKey(1), x)["params"]
    out = mod.apply({"params": params}, x)
    import flax.linen as nn

    ref = nn.gelu(x @ params["wi"][0]) @ params["wo"][0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_moe_capacity_drop_passes_through():
    """Tokens beyond an expert's capacity get ZERO MLP delta (their
    residual stream passes through unchanged) — the static-shape
    capacity contract."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.models.moe import MoEMlp

    # 1 expert, capacity_factor tiny -> capacity 1: only the first
    # token is processed, the rest are dropped.
    mod = MoEMlp(d_model=8, d_ff=16, num_experts=1, capacity_factor=0.01,
                 dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 6, 8))
    params = mod.init(jax.random.PRNGKey(1), x)["params"]
    out = np.asarray(mod.apply({"params": params}, x))
    assert np.abs(out[0, 0]).max() > 0  # first token processed
    np.testing.assert_array_equal(out[0, 1:], 0)  # rest dropped


def test_moe_expert_parallel_sharding_and_step():
    """dp2 x ep4: expert weights shard over the ep axis (local shard
    carries 1 of 4 experts), the compiled step carries the
    token->expert all-to-all (ep is load-bearing, not just declared),
    and a train step runs with finite loss."""
    mesh = build_mesh(MeshSpec.create(dp=2, ep=4))
    m = get_model("moe_lm", tiny=True, ep_mesh=mesh)
    tr = Trainer(m, optax.adam(1e-3), mesh)
    state = tr.init_state()

    wi = state.params["layer_0"]["moe"]["wi"]
    assert wi.shape[0] == 4  # experts
    shard = wi.addressable_shards[0].data
    assert shard.shape[0] == 1, f"experts not sharded over ep: {shard.shape}"

    data = ShardedDataIterator(
        synthetic_dataset(m.synth_batch, 128), global_batch_size=16
    )
    batch = data.device_batch(0, mesh, batch_axes=("dp",))
    # The compiled step must run the expert MLP on LOCAL expert shards
    # (e dim 1 of 4 per device) — never on the full expert dim, which
    # would mean GSPMD all-gathered the experts and ep is decorative.
    # (The redistribution collective itself is the partitioner's
    # choice: all-to-all on TPU topologies, gather-based elsewhere.)
    import re as _re

    hlo = tr.lower_step(state, batch).as_text()
    assert _re.search(r"bf16\[\d+,1,\d+,128\]", hlo), (
        "no ep-local expert matmul operand in the compiled step"
    )
    assert not _re.search(r"bf16\[\d+,4,\d+,128\]", hlo), (
        "found a FULL-expert-dim d_ff operand: experts were gathered"
    )
    state2, metrics = tr.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert np.isfinite(float(metrics["moe_aux_loss"]))
    assert state2.params["layer_0"]["moe"]["wi"].sharding == wi.sharding


def test_moe_lm_learns():
    """The tiny MoE LM trains end-to-end (loss falls) on one device."""
    import optax as _optax

    m = get_model("moe_lm", tiny=True)
    mesh = dp_mesh(1)
    tr = Trainer(m, _optax.adam(1e-3), mesh)
    state = tr.init_state()
    data = ShardedDataIterator(
        synthetic_dataset(m.synth_batch, 256), global_batch_size=16
    )
    losses = []
    for s in range(25):
        state, metrics = tr.step(state, data.device_batch(s, mesh))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses[::6]


def test_workspace_fallback_errors_are_loud(tmp_path):
    """Unregistered entrypoints without a usable workspace fail with
    actionable messages (not a silent fall-through to step 0)."""
    import pytest

    from edl_tpu.models.base import get_model

    with pytest.raises(ValueError, match="trainer.workspace"):
        get_model("no_such_model")
    ws = tmp_path / "empty_ws"
    ws.mkdir()
    with pytest.raises(ValueError, match="no model.py"):
        get_model("no_such_model", workspace=str(ws))
    (ws / "model.py").write_text("x = 1\n")
    with pytest.raises(ValueError, match="build"):
        get_model("no_such_model", workspace=str(ws))
    (ws / "model.py").write_text("def build(**kw):\n    return 42\n")
    # stale import cache: same path hash -> same module name; force new file
    ws2 = tmp_path / "ws2"
    ws2.mkdir()
    (ws2 / "model.py").write_text("def build(**kw):\n    return 42\n")
    with pytest.raises(ValueError, match="not a ModelDef"):
        get_model("no_such_model", workspace=str(ws2))
    # registered names NEVER fall through to the workspace
    m = get_model("fit_a_line", workspace=str(ws2))
    assert m.name == "fit_a_line"
