"""Live KV sequence migration (ISSUE 16): drains and preemptions that
never wait on a generation.

Key guarantees under test:

- **mid-generation resume, bit-identical**: a sequence frozen at a
  token boundary, pushed (chunked TCP, per-chunk + per-block crc) and
  adopted by a survivor emits EXACTLY the tokens an unmigrated
  same-seed run would — and the survivor performs ZERO prefills for
  it (the KV moved; nothing was recomputed);
- **drain latency is O(KV transfer)**: ``drain(migrate_to=...)`` acks
  while a deliberately long generation is still mid-flight on the
  survivor — the victim never waits a generation out;
- **the fallback ladder, rung by rung**: torn push / refused dest /
  KV-exhausted dest / generation skew each degrade to a cold
  re-prefill on the survivor (restart event, tokens regenerate in
  full); an unreachable survivor readmits locally and the PR 15
  bounded wait covers it.  Never a hang, never a mixed-generation
  token;
- **satellites**: budget-missed drain retries carry per-sequence
  progress; half-prefilled sequences requeue cold immediately (no
  restart event, no budget claim); the direct ``/drain`` ack completes
  migration with the coordinator dark; the lane passes the surviving
  replica as ``migrate_to``; the seeded migration soak journals
  bit-identically across same-seed runs.
"""

import json
import time
import urllib.request

import jax
import numpy as np
import pytest

from edl_tpu import telemetry
from edl_tpu.chaos.schedule import FaultEvent, FaultSchedule
from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.models.base import get_model
from edl_tpu.serving import (
    DecodeEngine,
    MigrationReceiver,
    ServingReplica,
    ServingServer,
    TokenContinuousBatcher,
    migrate_out,
)
from tests.test_decode_serving import _lm_state, _reference_decode


def _build_engine(step=1, seed=1, **kw):
    model = get_model("transformer_lm", tiny=True)
    store = HostDRAMStore()
    store.save_async(_lm_state(model, step, seed), generation=0)
    store.wait()
    engine = DecodeEngine(
        model,
        store,
        devices=jax.devices()[:1],
        max_batch=1,
        max_seqs=4,
        block_tokens=16,
        **kw,
    )
    assert engine.load()
    engine.warm()
    return model, store, engine


@pytest.fixture(scope="module")
def mig_pair():
    """One warmed source + destination DecodeEngine on IDENTICAL
    weights (step 1 / seed 1) — every test mounts fresh batchers and
    receivers on them and must leave both pools empty."""
    model, _, src = _build_engine()
    _, _, dst = _build_engine()
    return model, src, dst


def _wait(cond, timeout=20.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.002)
    raise AssertionError(f"wait timed out: {what}")


def _chaos_of(point):
    c = FaultSchedule(0, [FaultEvent(0, point)])
    c.advance(0)
    return c


# -- the KV wire, roundtrip ---------------------------------------------------


def test_kv_export_import_roundtrip_bit_exact(mig_pair):
    """engine.export_kv -> engine.import_kv moves block contents
    bit-exactly (the device->host->device path under every push)."""
    _, src, _ = mig_pair
    pool = src.pool
    ids = pool.alloc(2)
    assert ids is not None
    try:
        shape = pool._shape  # (layers, blocks, bt, heads, hd)
        rng = np.random.RandomState(7)
        k = rng.randn(shape[0], 2, shape[2], shape[3], shape[4]).astype(
            np.dtype(pool._dtype)
        )
        v = rng.randn(*k.shape).astype(k.dtype)
        src.import_kv(ids, k, v)
        k2, v2 = src.export_kv(ids)
        np.testing.assert_array_equal(k, k2)
        np.testing.assert_array_equal(v, v2)
    finally:
        pool.free(list(ids))
    assert pool.used_blocks == 0


# -- the acceptance criterion: mid-generation resume, bit-identical -----------


def test_migration_resumes_mid_generation_bit_identical(mig_pair):
    """A decoding sequence migrates at a token boundary and the
    survivor CONTINUES it: final tokens equal the unmigrated reference
    run, the survivor prefilled NOTHING for it, and the client's event
    stream is continuous (every index once, no restart)."""
    model, src, dst = mig_pair
    with telemetry.scoped() as (reg, _):
        src_b = TokenContinuousBatcher(src, refresh=False).start()
        dst_b = TokenContinuousBatcher(dst, refresh=False).start()
        recv = MigrationReceiver(dst, dst_b, replica_id="dst").start()
        try:
            prompt, n = list(range(1, 9)), 24
            events = []
            t = src_b.submit_generate(
                {"tokens": prompt},
                max_new_tokens=n,
                deadline_s=60.0,
                on_event=events.append,
            )
            _wait(lambda: len(t.tokens) >= 5, what="5 tokens pre-migration")
            src_b.close_admission()
            s = migrate_out(
                src, src_b, f"tcp://127.0.0.1:{recv.port}", replica_id="src"
            )
            assert s["migrated"] == 1 and s["failed"] == 0
            assert s["bytes"] > 0
            assert t.migrated
            assert src_b.in_flight == 0  # the drain wait would be instant
            tokens, meta = t.result(timeout=30)
            ref = _reference_decode(
                model, src.current_weights().params, prompt, n, src
            )
            assert tokens == ref, "migrated tokens diverged from reference"
            assert meta.get("migrated") is True
            assert meta["restarts"] == 0
            # ZERO survivor prefills: the sequence resumed mid-
            # generation off the imported KV, nothing was recomputed
            assert dst_b.stats["prefills"] == 0
            idx = [e["i"] for e in events if "token" in e]
            assert idx == list(range(n)), "stream not continuous"
            assert not any(e.get("restart") for e in events)
            assert (
                reg.counter("edl_serve_migrations_total").value(outcome="ok")
                == 1
            )
            assert (
                reg.counter("edl_serve_migrations_bytes_total").value()
                == s["bytes"]
            )
        finally:
            src_b.stop()
            dst_b.stop()
            recv.stop()
        assert src.pool.used_blocks == 0
        assert dst.pool.used_blocks == 0


# -- the fallback ladder, rung by rung ----------------------------------------


def _chaos_case(mig_pair, src_chaos=None, recv_chaos=None):
    """One migration under a chaos point.  Returns (summary, tokens,
    reference, events, dst_prefills)."""
    model, src, dst = mig_pair
    src_b = TokenContinuousBatcher(src, refresh=False).start()
    dst_b = TokenContinuousBatcher(dst, refresh=False).start()
    recv = MigrationReceiver(dst, dst_b, chaos=recv_chaos).start()
    try:
        prompt, n = list(range(1, 9)), 12
        events = []
        t = src_b.submit_generate(
            {"tokens": prompt},
            max_new_tokens=n,
            deadline_s=60.0,
            on_event=events.append,
        )
        _wait(lambda: len(t.tokens) >= 3, what="3 tokens pre-migration")
        src_b.close_admission()
        s = migrate_out(
            src, src_b, f"tcp://127.0.0.1:{recv.port}", chaos=src_chaos
        )
        tokens, _ = t.result(timeout=30)
        ref = _reference_decode(
            model, dst.current_weights().params, prompt, n, dst
        )
        prefills = dst_b.stats["prefills"]
    finally:
        src_b.stop()
        dst_b.stop()
        recv.stop()
    assert src.pool.used_blocks == 0, "source leaked KV blocks"
    assert dst.pool.used_blocks == 0, "dest leaked KV blocks"
    return s, tokens, ref, events, prefills


def test_torn_push_falls_back_to_cold_survivor_prefill(mig_pair):
    """chaos[serve.migrate.torn]: one corrupted chunk -> the per-chunk
    crc refuses the import, poisoned K/V never scatters, and the
    ladder's next rung re-prefills the sequence COLD on the survivor
    (streamed tokens voided by a restart event)."""
    s, tokens, ref, events, prefills = _chaos_case(
        mig_pair, recv_chaos=_chaos_of("serve.migrate.torn")
    )
    assert s["fallback"] == 1 and s["migrated"] == 0 and s["failed"] == 0
    assert sum(1 for e in events if e.get("restart")) == 1
    assert prefills == 1  # the survivor re-prefilled it
    assert tokens == ref and len(tokens) == 12


def test_dest_kv_exhaustion_refused_at_offer_then_cold(mig_pair):
    """chaos[serve.migrate.exhausted]: the dest refuses the KV offer
    BEFORE any bytes move; the source degrades to a cold push."""
    s, tokens, ref, events, prefills = _chaos_case(
        mig_pair, recv_chaos=_chaos_of("serve.migrate.exhausted")
    )
    assert s["fallback"] == 1 and s["bytes"] == 0
    assert prefills == 1
    assert tokens == ref and len(tokens) == 12


def test_kill_during_push_falls_back_cold(mig_pair):
    """chaos[serve.migrate.kill]: the push dies mid-stream (source
    side); the dest's crc accounting sees a torn image and the
    sequence re-prefills cold on the survivor."""
    s, tokens, ref, events, prefills = _chaos_case(
        mig_pair, src_chaos=_chaos_of("serve.migrate.kill")
    )
    assert s["fallback"] == 1 and s["migrated"] == 0
    assert prefills == 1
    assert tokens == ref and len(tokens) == 12


def test_swap_during_migration_reprefills_on_dest(mig_pair):
    """chaos[serve.migrate.swap]: a hot swap lands between the import
    grant and token-boundary adoption.  The push itself SUCCEEDS; the
    worker's generation-key check catches the skew at adoption and
    routes the sequence down the re-prefill rung — a restart event and
    a full regeneration, never a mixed-generation token."""
    s, tokens, ref, events, prefills = _chaos_case(
        mig_pair, recv_chaos=_chaos_of("serve.migrate.swap")
    )
    assert s["migrated"] == 1  # the wire transfer was clean
    assert sum(1 for e in events if e.get("restart")) == 1
    assert prefills == 1  # ...but adoption re-prefilled under the skew
    assert tokens == ref and len(tokens) == 12


def test_generation_skew_refused_at_import_never_mixed(mig_pair):
    """A survivor on DIFFERENT weights refuses the KV offer (the
    weights-generation check at import): the sequence re-prefills cold
    under the SURVIVOR's weights and its tokens equal the survivor's
    own reference — KV from one generation never decodes under
    another."""
    model, src, _ = mig_pair
    _, _, skew = _build_engine(step=2, seed=2)
    with telemetry.scoped():
        src_b = TokenContinuousBatcher(src, refresh=False).start()
        dst_b = TokenContinuousBatcher(skew, refresh=False).start()
        recv = MigrationReceiver(skew, dst_b).start()
        try:
            prompt, n = list(range(1, 9)), 12
            t = src_b.submit_generate(
                {"tokens": prompt}, max_new_tokens=n, deadline_s=60.0
            )
            _wait(lambda: len(t.tokens) >= 3, what="tokens pre-migration")
            src_b.close_admission()
            s = migrate_out(src, src_b, f"tcp://127.0.0.1:{recv.port}")
            assert s["fallback"] == 1 and s["migrated"] == 0
            assert s["bytes"] == 0  # refused at the offer, pre-bytes
            tokens, _ = t.result(timeout=30)
            ref = _reference_decode(
                model, skew.current_weights().params, prompt, n, skew
            )
            assert tokens == ref, "tokens not pure under survivor weights"
        finally:
            src_b.stop()
            dst_b.stop()
            recv.stop()
        assert src.pool.used_blocks == 0
        assert skew.pool.used_blocks == 0


def test_unreachable_survivor_readmits_locally(mig_pair):
    """The ladder's LAST rung: no survivor at all — the sequence comes
    back to the local queue (restart event, tokens voided) and the
    PR 15 bounded wait covers it locally."""
    model, src, _ = mig_pair
    with telemetry.scoped():
        src_b = TokenContinuousBatcher(src, refresh=False).start()
        try:
            prompt, n = list(range(1, 9)), 12
            events = []
            t = src_b.submit_generate(
                {"tokens": prompt},
                max_new_tokens=n,
                deadline_s=60.0,
                on_event=events.append,
            )
            _wait(lambda: len(t.tokens) >= 3, what="tokens pre-migration")
            src_b.close_admission()
            s = migrate_out(src, src_b, "tcp://127.0.0.1:9")
            assert s["failed"] == 1 and s["migrated"] == 0
            assert not t.migrated  # back on the local books
            tokens, meta = t.result(timeout=30)
            assert len(tokens) == n
            ref = _reference_decode(
                model, src.current_weights().params, prompt, n, src
            )
            assert tokens == ref
            assert meta["restarts"] == 1
            assert any(e.get("restart") for e in events)
        finally:
            src_b.stop()
        assert src.pool.used_blocks == 0


# -- drain rides migration: O(KV transfer), not O(longest generation) ---------


def test_drain_migrate_to_acks_before_long_generation_finishes(mig_pair):
    """The tentpole's latency claim: a drain with a DELIBERATELY long
    generation in flight acks once the KV moved — while the survivor
    is still decoding the handed-over sequence — instead of waiting
    the generation out.  The survivor is addressed by its HTTP
    address (GET /migrate advertises the receiver port)."""
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.serving import ContinuousBatcher

    model, src, dst = mig_pair
    with telemetry.scoped() as (_, rec):
        coord = LocalCoordinator(target_world=2, max_world=4)
        victim = ServingReplica(
            src,
            coordinator=coord,
            replica_id="victim",
            heartbeat_interval=60.0,
            telemetry_interval=1e9,
        ).start()
        dst_gb = TokenContinuousBatcher(dst, refresh=False)
        dst_srv = ServingServer(
            ContinuousBatcher(dst),
            host="127.0.0.1",
            gen_batcher=dst_gb,
        )
        survivor = ServingReplica(
            dst,
            batcher=dst_srv.batcher,
            server=dst_srv,
            gen_batcher=dst_gb,
            coordinator=coord,
            replica_id="survivor",
            heartbeat_interval=60.0,
            telemetry_interval=1e9,
        ).start()
        try:
            prompt, n = list(range(1, 9)), 48
            t = victim.gen_batcher.submit_generate(
                {"tokens": prompt}, max_new_tokens=n, deadline_s=120.0
            )
            _wait(lambda: len(t.tokens) >= 2, what="generation in flight")
            r = victim.drain(
                budget_s=60.0,
                migrate_to=f"http://127.0.0.1:{dst_srv.port}",
            )
            at_ack = len(t.tokens)
            assert r["drained"] and r["in_flight"] == 0
            assert r["migrate"]["migrated"] == 1
            assert r["progress"] == {
                "total": 1,
                "migrated": 1,
                "remaining": 0,
            }
            # the ack arrived while the generation was still running
            assert at_ack < n, "drain waited the generation out"
            # ...and the victim deregistered without dropping it
            assert "victim" not in coord.members()
            tokens, meta = t.result(timeout=60)
            assert len(tokens) == n
            ref = _reference_decode(
                model, src.current_weights().params, prompt, n, src
            )
            assert tokens == ref
            assert meta.get("migrated") is True
            done = [
                e
                for e in rec.events()
                if e.kind == "serve.drain" and e.data.get("phase") == "done"
            ]
            assert done and done[-1].data["migrated"] == 1
        finally:
            victim.stop()
            survivor.stop()
        assert src.pool.used_blocks == 0
        assert dst.pool.used_blocks == 0


def test_budget_missed_drain_retry_carries_progress(mig_pair):
    """ISSUE 16 satellite: a drain that misses its budget reports
    per-sequence progress; the RETRY re-waits only still-local,
    still-unresolved sequences — and a retry that migrates counts the
    moved sequences, converging monotonically."""
    model, src, dst = mig_pair
    with telemetry.scoped():
        victim = ServingReplica(
            src,
            replica_id="victim",
            heartbeat_interval=60.0,
            telemetry_interval=1e9,
        ).start()
        dst_b = TokenContinuousBatcher(dst, refresh=False).start()
        recv = MigrationReceiver(dst, dst_b).start()
        try:
            tickets = [
                victim.gen_batcher.submit_generate(
                    {"tokens": list(range(1 + i, 9 + i))},
                    max_new_tokens=48,
                    deadline_s=120.0,
                )
                for i in range(2)
            ]
            _wait(
                lambda: all(t.tokens for t in tickets),
                what="both generations in flight",
            )
            # 48-token generations cannot finish in ~1ms: budget missed
            r1 = victim.drain(budget_s=0.001)
            assert not r1["drained"]
            assert r1["progress"]["total"] == 2
            assert r1["progress"]["remaining"] >= 1
            # the retry (the next autoscaler tick) rides migration and
            # acks without re-waiting anything already resolved
            r2 = victim.drain(
                budget_s=60.0, migrate_to=f"tcp://127.0.0.1:{recv.port}"
            )
            assert r2["drained"]
            assert r2["progress"]["total"] == 2  # snapshot preserved
            assert r2["progress"]["remaining"] == 0
            assert r2["progress"]["migrated"] >= 1
            assert (
                r2["progress"]["remaining"] <= r1["progress"]["remaining"]
            )
            for t in tickets:
                tokens, _ = t.result(timeout=60)
                assert len(tokens) == 48  # dropped == 0
        finally:
            victim.stop()
            dst_b.stop()
            recv.stop()
        assert src.pool.used_blocks == 0
        assert dst.pool.used_blocks == 0


def test_half_prefilled_drain_requeues_cold_no_restart(mig_pair):
    """ISSUE 16 satellite: a half-prefilled sequence (mid-chunking at
    the freeze) streamed NOTHING — it requeues on the survivor as a
    cold prompt immediately: its local KV frees the same moment (no
    claim on the drain budget), no restart event reaches the client,
    and the survivor prefills it from scratch."""
    model, src, dst = mig_pair
    from edl_tpu.serving.batcher import _PREFILLING

    with telemetry.scoped():
        # Worker deliberately NOT started: fabricate the exact state
        # the chunked scheduler holds mid-prompt (one block written,
        # 16 of 48 prompt positions prefilled) so the test is
        # deterministic — a live worker races through small prompts.
        src_b = TokenContinuousBatcher(src, refresh=False)
        rng = np.random.RandomState(11)
        prompt = model.synth_batch(rng, 1)["tokens"][0, :48].tolist()
        events = []
        t = src_b.submit_generate(
            {"tokens": prompt},
            max_new_tokens=8,
            deadline_s=60.0,
            on_event=events.append,
        )
        with src_b._cv:
            assert src_b._queue.popleft() is t
            src_b._queued_tokens -= len(prompt)
        got = src.pool.alloc(1)
        assert got is not None
        t.state = _PREFILLING
        t.blocks = list(got)
        t.table = np.zeros(src.blocks_per_seq, np.int32)
        t.table[0] = got[0]
        t.prefilled = 16
        src_b._prefilling.append(t)
        src_b._prefilling_tokens += len(prompt) - 16
        dst_b = TokenContinuousBatcher(dst, refresh=False).start()
        recv = MigrationReceiver(dst, dst_b).start()
        try:
            s = migrate_out(src, src_b, f"tcp://127.0.0.1:{recv.port}")
            assert s["cold"] == 1 and s["attempted"] == 1
            # KV freed IMMEDIATELY — nothing for a drain wait to hold
            assert src.pool.used_blocks == 0
            assert src_b.in_flight == 0
            tokens, meta = t.result(timeout=30)
            assert len(tokens) == 8
            ref = _reference_decode(
                model, dst.current_weights().params, prompt, 8, dst
            )
            assert tokens == ref
            # it streamed nothing, so nothing was voided: NO restart
            assert meta["restarts"] == 0
            assert not any(e.get("restart") for e in events)
            assert dst_b.stats["prefills"] == 1
        finally:
            src_b.stop()
            dst_b.stop()
            recv.stop()
        assert dst.pool.used_blocks == 0


# -- coordinator blackout: the control plane is not on the data path ----------


def test_drain_migration_completes_with_coordinator_dark(mig_pair):
    """ISSUE 16 satellite: a direct POST /drain (the kubelet preStop
    shape) completes the migration and acks while the serving
    coordinator is DARK — the KV push is replica-to-replica, the
    control plane is not on the data path.  The un-deregisterable
    victim stays a member (lease expiry reconverges later), and the
    lane's patch gate fails CLOSED while the coordinator is dark."""
    from edl_tpu.autoscaler.serving import ServingLane
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.serving import ContinuousBatcher

    model, src, dst = mig_pair

    class _DarkableCoord:
        def __init__(self, inner):
            self.inner = inner
            self.dark = False

        def __getattr__(self, name):
            if self.dark:
                raise ConnectionError("coordinator unreachable")
            return getattr(self.inner, name)

    with telemetry.scoped():
        coord = _DarkableCoord(
            LocalCoordinator(target_world=2, max_world=4)
        )
        src_srv = ServingServer(ContinuousBatcher(src), host="127.0.0.1")
        victim = ServingReplica(
            src,
            batcher=src_srv.batcher,
            server=src_srv,
            coordinator=coord,
            replica_id="victim",
            heartbeat_interval=60.0,
            telemetry_interval=1e9,
        ).start()
        dst_b = TokenContinuousBatcher(dst, refresh=False).start()
        recv = MigrationReceiver(dst, dst_b).start()
        try:
            prompt, n = list(range(1, 9)), 24
            t = victim.gen_batcher.submit_generate(
                {"tokens": prompt}, max_new_tokens=n, deadline_s=120.0
            )
            _wait(lambda: len(t.tokens) >= 2, what="generation in flight")
            coord.dark = True  # serve.coord.unreachable, held dark
            body = json.dumps(
                {
                    "budget_ms": 30000,
                    "wait": True,
                    "migrate_to": f"tcp://127.0.0.1:{recv.port}",
                }
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{src_srv.port}/drain",
                data=body,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=40) as resp:
                r = json.loads(resp.read())
            assert r["drained"] and r["in_flight"] == 0
            assert r["progress"]["migrated"] == 1
            tokens, meta = t.result(timeout=60)
            assert len(tokens) == n and meta.get("migrated") is True
            # deregistration could NOT reach the dark coordinator: the
            # victim stays a member until lease expiry
            assert "victim" in coord.inner.members()
            # ...and a lane watching through the dark coordinator
            # fails CLOSED: an unobservable fleet is never patched
            patches = []
            lane = ServingLane(
                coord,
                min_replicas=1,
                max_replicas=4,
                hold_ticks=1,
                on_scale=lambda old, new: patches.append((old, new)),
            )
            assert lane.run_once() is None and patches == []
        finally:
            victim.stop()
            dst_b.stop()
            recv.stop()
        assert src.pool.used_blocks == 0
        assert dst.pool.used_blocks == 0


# -- the lane hands drains a survivor -----------------------------------------


def test_lane_drain_victims_passes_survivor_as_migrate_to():
    """drain_victims picks the plan's first surviving addressed member
    and every victim's POST /drain body carries it as ``migrate_to`` —
    fleet scale-downs (and market preemptions through ServingBidder)
    ride the migration path with zero extra wiring."""
    from edl_tpu.autoscaler.serving import ServingLane
    from tests.test_serving_drain import _DrainCoord, _FakeDrainReplica

    with telemetry.scoped():
        survivor = _FakeDrainReplica(drained=True)
        victim = _FakeDrainReplica(drained=True)
        try:
            coord = _DrainCoord(
                2,
                ["r0", "r1"],
                [survivor.address, victim.address],
            )
            lane = ServingLane(
                coord,
                min_replicas=1,
                max_replicas=4,
                hold_ticks=1,
                victim_drain_timeout=5.0,
            )
            entry = lane.run_once()
            assert entry["actuated"]
            assert entry["drain"]["migrate_to"] == survivor.address
            assert [p for p, _ in victim.hits] == ["/drain"]
            assert victim.hits[0][1]["migrate_to"] == survivor.address
            assert survivor.hits == []  # survivors are never drained
        finally:
            survivor.stop()
            victim.stop()


# -- edl metrics: the operator view -------------------------------------------


def test_metrics_cli_prints_migration_counters(capsys):
    """ISSUE 16 satellite: `edl metrics` serving section surfaces the
    migration counters — migrations, KV bytes moved, fallback
    re-prefills, p95 migrate seconds."""
    from edl_tpu.cli import main
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.telemetry import MetricsRegistry

    coord = LocalCoordinator(target_world=1, max_world=2)
    coord.register("serve-0")
    reg = MetricsRegistry()
    reg.counter("edl_serve_requests_total").inc(3, status="ok")
    reg.counter("edl_serve_migrations_total").inc(4, outcome="ok")
    reg.counter("edl_serve_migrations_total").inc(1, outcome="fallback")
    reg.counter("edl_serve_migrations_bytes_total").inc(8192)
    reg.histogram("edl_serve_migrate_seconds").observe(0.05)
    coord.report_telemetry("serve-0", snapshot=reg.snapshot(), seq=1)
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start(
        evict=False
    )
    try:
        assert main(["metrics", f"127.0.0.1:{server.port}"]) == 0
        out = capsys.readouterr().out
        assert "migrations_total" in out and "5" in out
        assert "migrate_fallbacks" in out
        assert "migrated_kv_bytes" in out and "8192" in out
        assert "migrate_p95" in out
    finally:
        server.stop()


# -- the seeded migration soak ------------------------------------------------


def _soak_round(schedule, model, src, dst, dst_b, rnd, prompt):
    """One migration under whatever chaos is due: fresh source
    batcher, one generation, freeze-and-migrate, resolve.  Returns the
    deterministic per-round evidence."""
    src_b = TokenContinuousBatcher(src, refresh=False).start()
    recv = MigrationReceiver(dst, dst_b, chaos=schedule).start()
    try:
        t = src_b.submit_generate(
            {"tokens": prompt}, max_new_tokens=10, deadline_s=60.0
        )
        _wait(lambda: len(t.tokens) >= 2, what=f"round {rnd} tokens")
        src_b.close_admission()
        s = migrate_out(
            src, src_b, f"tcp://127.0.0.1:{recv.port}", chaos=schedule
        )
        tokens, _ = t.result(timeout=30)
        dropped = 0 if len(tokens) == 10 else 1
        return (
            rnd,
            s["migrated"],
            s["fallback"],
            s["cold"],
            s["failed"],
            tuple(tokens),
            dropped,
        )
    finally:
        src_b.stop()
        recv.stop()


def _run_migration_soak(seed: int):
    """Kill-during-push, torn block, dest exhaustion, swap-during-
    migration, then one clean migration — all against one surviving
    destination.  Returns what must be bit-identical across same-seed
    runs."""
    events = [
        FaultEvent(1, "serve.migrate.kill"),
        FaultEvent(2, "serve.migrate.torn"),
        FaultEvent(3, "serve.migrate.exhausted"),
        FaultEvent(4, "serve.migrate.swap"),
    ]
    with telemetry.scoped() as (_, rec):
        schedule = FaultSchedule(seed, events)
        model, _, src = _build_engine()
        _, _, dst = _build_engine()
        dst_b = TokenContinuousBatcher(dst, refresh=False).start()
        log = []
        dropped = 0
        try:
            for rnd in range(1, 6):  # round 5 is chaos-free
                schedule.advance(rnd)
                entry = _soak_round(
                    schedule,
                    model,
                    src,
                    dst,
                    dst_b,
                    rnd,
                    list(range(rnd, rnd + 8)),
                )
                dropped += entry[-1]
                log.append(entry)
        finally:
            dst_b.stop()
        assert schedule.pending() == []
        assert src.pool.used_blocks == 0
        assert dst.pool.used_blocks == 0
        return {"digest": rec.digest(), "log": log, "dropped": dropped}


def test_migration_soak_bit_reproducible():
    """ISSUE 16 acceptance: the seeded migration soak — every chaos
    point fires once, every sequence completes in full (dropped == 0),
    the ladder's outcomes are the scheduled ones, and two same-seed
    runs journal BIT-IDENTICALLY (recorder digest + the structured
    log, tokens included)."""
    r1 = _run_migration_soak(seed=1609)
    assert r1["dropped"] == 0
    by_round = {e[0]: e[1:5] for e in r1["log"]}
    # (migrated, fallback, cold, failed) per scheduled chaos point
    assert by_round[1] == (0, 1, 0, 0)  # kill-during-push -> fallback
    assert by_round[2] == (0, 1, 0, 0)  # torn block -> fallback
    assert by_round[3] == (0, 1, 0, 0)  # dest exhaustion -> fallback
    assert by_round[4] == (1, 0, 0, 0)  # swap -> clean push, dest re-prefill
    assert by_round[5] == (1, 0, 0, 0)  # chaos-free -> clean migration
    r2 = _run_migration_soak(seed=1609)
    assert r1["digest"] == r2["digest"], "journals diverged across reruns"
    assert r1["log"] == r2["log"], "soak evidence diverged across reruns"


# -- prefix-cache interplay (ISSUE 17) ----------------------------------------


def test_migrate_with_shared_prefix_copies_blocks_lands_private(mig_pair):
    """ISSUE 17 satellite: migrating a sequence whose leading KV blocks
    are SHARED through the prefix cache (refcount > 1) must export a
    host COPY — the source keeps the blocks for the other claimants and
    the cache — and the granted blocks land PRIVATE on the dest (never
    published into ITS prefix index).

    Two live claimants (B, C) share a 2-block prefix published by a
    finished sequence A, so the shared blocks sit at refcount 2 when
    ``migrate_out`` snapshots them.  Both migrate; the source must end
    with the shared blocks refcount-0 AND still parked + claimable in
    its cache, and the dest must end with nothing cached at all."""
    model, src, dst = mig_pair
    # Stale published marks from earlier tests' batchers would make the
    # cached-block counts below nondeterministic — start both pools
    # with an empty cache tier.
    src.pool.drop_published()
    dst.pool.drop_published()
    with telemetry.scoped():
        src_b = TokenContinuousBatcher(src, refresh=False).start()
        dst_b = TokenContinuousBatcher(dst, refresh=False).start()
        recv = MigrationReceiver(dst, dst_b, replica_id="dst").start()
        try:
            shared = list(range(1, 33))  # 32 tokens = 2 full blocks
            pa = shared + [101, 102, 103, 104]
            pb = shared + [111, 112, 113, 114]
            pc = shared + [121, 122, 123, 124]
            # A publishes the shared run, finishes, parks it cached.
            src_b.submit_generate(
                {"tokens": pa}, max_new_tokens=2, deadline_s=60.0
            ).result(timeout=60)
            tb = src_b.submit_generate(
                {"tokens": pb}, max_new_tokens=10, deadline_s=60.0
            )
            tc = src_b.submit_generate(
                {"tokens": pc}, max_new_tokens=10, deadline_s=60.0
            )
            _wait(
                lambda: len(tb.tokens) >= 2 and len(tc.tokens) >= 2,
                what="both claimants decoding pre-migration",
            )
            assert tb.reused_blocks == 2 and tc.reused_blocks == 2
            sblocks = list(tb.blocks[:2])
            assert sblocks == list(tc.blocks[:2]), "claimants not sharing"
            assert all(src.pool.refcount(b) == 2 for b in sblocks)
            src_b.close_admission()
            s = migrate_out(src, src_b, f"tcp://127.0.0.1:{recv.port}")
            assert s["migrated"] == 2 and s["fallback"] == 0
            assert s["failed"] == 0
            w = src.current_weights()
            toks_b, meta_b = tb.result(timeout=30)
            toks_c, meta_c = tc.result(timeout=30)
            assert toks_b == _reference_decode(model, w.params, pb, 10, src)
            assert toks_c == _reference_decode(model, w.params, pc, 10, src)
            assert meta_b.get("migrated") is True
            assert meta_b["reused_blocks"] == 2
            assert meta_c["reused_blocks"] == 2
            # Source: the two detaches DECREMENTED (2 -> 1 -> 0); the
            # published blocks parked cached, index intact, claimable.
            assert all(src.pool.refcount(b) == 0 for b in sblocks)
            assert src.pool.cached_blocks == 2
            assert len(src_b.prefix) == 2
            run, skip = src_b.prefix.claim(np.asarray(pb, dtype=np.int32))
            assert list(run) == sblocks and skip == 32
            src.pool.free(list(run))  # return the probe's refs
            # Dest: the grants landed PRIVATE — nothing entered its
            # prefix index, so every freed block went to the free list.
            assert len(dst_b.prefix) == 0
            assert dst.pool.cached_blocks == 0
        finally:
            src_b.stop()
            dst_b.stop()
            recv.stop()
        assert src.pool.used_blocks == 0
        assert dst.pool.used_blocks == 0
