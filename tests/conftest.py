"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax import.

Mirrors the reference's test philosophy (SURVEY.md §4): fabricate
multi-node state without a cluster.  For the trainer half, the
"fabricated cluster" is 8 virtual CPU devices, enough for dp*tp*pp
meshes and elastic resize tests (1 -> 2 -> 4 -> 8 trainers).
"""

import os

# Must run before jax initializes any backend.  NOTE: this environment's
# sitecustomize imports jax at interpreter start (TPU plugin), so the
# env var alone is too late — jax.config.update below is authoritative.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# Persistent XLA compilation cache (same default as ci.sh): the suite
# builds fresh Trainer/jit objects per test, so identical HLO is
# otherwise recompiled over and over WITHIN one run — the
# content-addressed disk cache dedupes those, and the multipod tests'
# subprocess worker pods (which inherit this environment) stop paying
# the whole model's cold compile per pod per test.  Env vars, not
# jax.config: they must propagate to the spawned workers.
if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    os.environ["JAX_COMPILATION_CACHE_DIR"] = os.path.join(
        os.environ.get("TMPDIR") or "/tmp", "edl-xla-cache"
    )
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.makedirs(os.environ["JAX_COMPILATION_CACHE_DIR"], exist_ok=True)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs[:8]


def pytest_sessionfinish(session, exitstatus):
    """EDL_METRICS_ARTIFACT: spill the suite's accumulated telemetry
    (the process-global registry's Prometheus exposition + the flight
    recorder's tail) as a CI artifact — ci.sh sets the path and points
    at it after the tier-1 run."""
    import json
    import os as _os

    path = _os.environ.get("EDL_METRICS_ARTIFACT")
    if not path:
        return
    try:
        from edl_tpu import telemetry

        with open(path, "w") as f:
            f.write(telemetry.get_registry().render())
        base = path[:-5] if path.endswith(".prom") else path
        with open(base + ".events.jsonl", "w") as f:
            for ev in telemetry.get_recorder().events():
                f.write(json.dumps(ev.to_dict()) + "\n")
    except Exception:  # the artifact must never fail the suite
        import traceback

        traceback.print_exc()
