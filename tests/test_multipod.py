"""Multi-pod elasticity: real OS processes forming, resizing, and
re-forming one JAX world through the HTTP coordinator.

This is the capability the reference delegated to master/etcd +
pserver re-registration (``pkg/jobparser.go:174-191``): trainer pods
join and leave at any time and the surviving world keeps training with
loss continuity.  Here each "pod" is a subprocess running the real
launcher on the CPU platform (gloo collectives); the world is re-formed
per generation by ``jax.distributed`` re-initialization
(``edl_tpu.launcher.make_world_builder``).
"""

import json
import math
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Step budget far above what any phase consumes: workers are stopped by
# SIGTERM (the graceful-leave handshake), never by running out of steps,
# so phase timing can't race a worker's natural exit.
STEPS = 200_000


def _read_lines(path):
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # partially written tail line
    return out


def _read_history(path):
    """Step records only (the launcher also logs formation timings)."""
    return [r for r in _read_lines(path) if "step" in r]


def _read_formations(path):
    return [r["formation"] for r in _read_lines(path) if "formation" in r]


def _read_resizes(path):
    return [r["resize"] for r in _read_lines(path) if "resize" in r]


def _wait_for(pred, timeout, what, procs=()):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        for p in procs:
            if p.poll() is not None and p.returncode != 0:
                out = p.stdout.read() if p.stdout else ""
                raise AssertionError(
                    f"worker died (rc={p.returncode}) while waiting for "
                    f"{what}:\n{out[-4000:]}"
                )
        time.sleep(0.25)
    # Timed out: kill the workers and dump their output so a flake under
    # CI load is diagnosable from the failure message alone.
    dumps = []
    for p in procs:
        if p.poll() is None:
            p.kill()
            try:
                p.wait(timeout=10)
            except Exception:
                pass
        out = p.stdout.read() if p.stdout else ""
        dumps.append(f"--- worker rc={p.returncode} ---\n{out[-3000:]}")
    raise AssertionError(
        f"timed out waiting for {what}\n" + "\n".join(dumps)
    )



def _spawn_worker(
    procs, hist, name, base_port, caddr, checkpoint_interval=2, devices=1,
    gbs=8, extra_env=None, entrypoint="fit_a_line", parallelism="",
    lr="1e-2",
):
    """Launch one real launcher 'pod' subprocess against the HTTP
    coordinator (shared by the multipod tests).  ``devices`` forces the
    pod's local CPU device count — >1 simulates a multi-chip TPU pod
    (e.g. the default v5e-4 slice).  ``parallelism`` is the deployed
    layout string ("fsdp=2"), normally EDL_PARALLELISM."""
    env = dict(os.environ)
    env["EDL_POD_NAME"] = name
    if extra_env:
        env.update(extra_env)
    # The pytest process runs on 8 virtual CPU devices (conftest);
    # each worker pod must have exactly its own local device count.
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    if devices > 1:
        flags.append(f"--xla_force_host_platform_device_count={devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    p = subprocess.Popen(
        [
            sys.executable, "-u", "-m", "edl_tpu.launcher",
            "--entrypoint", entrypoint,
            "--steps", str(STEPS),
            "--coordinator", caddr,
            "--address", f"127.0.0.1:{base_port}",
            "--platform", "cpu",
            "--global-batch-size", str(gbs),
            "--checkpoint-interval", str(checkpoint_interval),
            "--history-file", str(hist[name]),
            "--parallelism", parallelism,
            # fit_a_line at the default 1e-3 descends too shallowly for
            # the convergence asserts once resizes stop stalling the
            # step stream (fewer steps elapse per test phase); 1e-2
            # matches the chaos suite's optimizer for the same model.
            "--lr", lr,
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    procs.append(p)
    return p


def test_multipod_elastic_1_2_1(tmp_path):
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(
        target_world=1, max_world=2, heartbeat_timeout=60.0, legal_sizes=[1, 2]
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    hist = {w: tmp_path / f"{w}.jsonl" for w in ("w1", "w2")}
    procs = []

    def spawn(name, base_port):
        return _spawn_worker(procs, hist, name, base_port, caddr)

    try:
        w1 = spawn("w1", 10100)
        _wait_for(
            lambda: len(_read_history(hist["w1"])) >= 5,
            180,
            "w1 to step at world 1",
            procs,
        )

        # Scale up: admit a second pod and retarget.
        w2 = spawn("w2", 10160)
        coord.set_target_world(2)
        _wait_for(
            lambda: any(
                r["world_size"] == 2 for r in _read_history(hist["w1"])
            )
            and any(r["world_size"] == 2 for r in _read_history(hist["w2"])),
            240,
            "the 2-pod world to step",
            procs,
        )

        # Scale down: w2 drops to standby, w1 re-forms alone.
        down_mark = len(_read_history(hist["w1"]))
        coord.set_target_world(1)
        _wait_for(
            lambda: any(
                r["world_size"] == 1
                for r in _read_history(hist["w1"])[down_mark:]
            ),
            240,
            "w1 back at world 1",
            procs,
        )

        # Graceful leave: SIGTERM must deregister both synchronously
        # (no lease wait — the scale-down handshake, VERDICT r1
        # missing-3).  w2 leaves from standby, w1 from an active world.
        assert "w2" in coord.members()
        w2.send_signal(signal.SIGTERM)
        w2.wait(timeout=60)
        _wait_for(
            lambda: "w2" not in coord.members(), 30, "w2 deregistered", procs
        )
        assert "w1" in coord.members()
        w1.send_signal(signal.SIGTERM)
        w1.wait(timeout=60)
        _wait_for(
            lambda: "w1" not in coord.members(), 30, "w1 deregistered", procs
        )

        # -- history checks -------------------------------------------------
        h1 = _read_history(hist["w1"])
        worlds = {r["world_size"] for r in h1}
        assert worlds == {1, 2}, f"w1 saw worlds {worlds}"
        # Deterministic data + graceful resizes: every step up to the
        # last is covered exactly once (contiguous, no gaps, no loss).
        steps_done = sorted(r["step"] for r in h1)
        top = steps_done[-1]
        assert steps_done == list(range(top + 1)), "step stream has gaps"
        assert all(math.isfinite(r["loss"]) for r in h1), "non-finite loss"
        # Loss continuity across both resizes: fit_a_line converges, so
        # the tail must sit far below the head.
        head = sum(r["loss"] for r in h1[:5]) / 5
        tail = sum(r["loss"] for r in h1[-5:]) / 5
        assert tail < head * 0.5, f"no convergence: head={head} tail={tail}"

        # World formation is timed and bounded: every teardown+init must
        # fit well inside the <60s resize budget (BASELINE.md) — the
        # multi-pod formation path is its dominant unknown at scale.
        formations = _read_formations(hist["w1"]) + _read_formations(
            hist["w2"]
        )
        assert formations, "no formation timings recorded"
        totals = []
        for f in formations:
            total = f["teardown_s"] + f["init_s"]
            totals.append(total)
            print(
                f"formation gen={f['generation']} world={f['world_size']} "
                f"rank={f['rank']}: teardown={f['teardown_s']}s "
                f"init={f['init_s']}s"
            )
            # Hard bound: one formation attempt's budget (launcher's
            # _FORMATION_TIMEOUT_S) — generous enough for a loaded CI
            # host, far inside the <60s resize budget.
            assert total < 30.0, f"world formation took {total}s: {f}"
        totals.sort()
        median = totals[len(totals) // 2]
        assert median < 15.0, f"median formation {median}s (all: {totals})"

        # The two pods agree on the overlapping (world=2) steps' losses:
        # one world, one loss stream — proof of a shared process group
        # rather than two duplicated single-pod worlds.
        h2 = {r["step"]: r for r in _read_history(hist["w2"])}
        shared = [
            (r, h2[r["step"]])
            for r in h1
            if r["world_size"] == 2 and r["step"] in h2
        ]
        assert shared, "no overlapping world-2 steps recorded"
        for a, b in shared:
            assert abs(a["loss"] - b["loss"]) < 1e-5, (
                f"step {a['step']}: w1 loss {a['loss']} != w2 loss {b['loss']}"
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


def test_multipod_scale_down_delayed_poll_same_boundary(tmp_path):
    """THE deadlock-shaped regression test for the consensus step bus:
    at a retarget, one member's plan poll is chaos-delayed
    (``consensus.vote.delayed``) — the exact poll-skew the pre-consensus
    runtime raced on (the early poller stood down into the shutdown
    barrier while the oblivious peer's dispatched gloo collective waited
    for it forever; measured 2/5 hangs of ``test_multipod_elastic_1_2_1``
    at ``bb253ec`` on a loaded box).  With the bus, the on-time member's
    vote rides the data plane: BOTH members must agree on one stop step
    in their flight-recorder journals and leave the old world at that
    exact boundary — the delayed member included, steps before it ever
    sees the new plan."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(
        target_world=1, max_world=2, heartbeat_timeout=60.0, legal_sizes=[1, 2]
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    hist = {w: tmp_path / f"{w}.jsonl" for w in ("p1", "p2")}
    events = {w: tmp_path / f"{w}.events.jsonl" for w in ("p1", "p2")}
    procs = []

    def read_events(name, kind):
        return [
            e["data"]
            for e in _read_lines(events[name])
            if e.get("kind") == kind
        ]

    try:
        p1 = _spawn_worker(
            procs, hist, "p1", 12100, caddr,
            extra_env={"EDL_FLIGHT_RECORDER_FILE": str(events["p1"])},
        )
        _wait_for(
            lambda: len(_read_history(hist["p1"])) >= 5,
            180, "p1 stepping at world 1", procs,
        )
        # p2's plan poll will be suppressed 3s at the NEXT retarget it
        # observes on a live multi-member world (the scale-down below).
        p2 = _spawn_worker(
            procs, hist, "p2", 12160, caddr,
            extra_env={
                "EDL_FLIGHT_RECORDER_FILE": str(events["p2"]),
                "EDL_CHAOS_SPEC": json.dumps(
                    {
                        "seed": 0,
                        "events": [
                            {
                                "step": 0,
                                "point": "consensus.vote.delayed",
                                "arg": 3.0,
                            }
                        ],
                    }
                ),
            },
        )
        coord.set_target_world(2)
        _wait_for(
            lambda: any(
                r["world_size"] == 2 for r in _read_history(hist["p1"])
            )
            and any(r["world_size"] == 2 for r in _read_history(hist["p2"])),
            240, "the 2-pod world to step", procs,
        )

        down_mark = len(_read_history(hist["p1"]))
        coord.set_target_world(1)
        _wait_for(
            lambda: any(
                r["world_size"] == 1
                for r in _read_history(hist["p1"])[down_mark:]
            ),
            240, "p1 back at world 1 (past the delayed-poll window)", procs,
        )
        for name, proc in (("p2", p2), ("p1", p1)):
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)

        # -- the agreement, from the journals alone -----------------------
        stops1 = read_events("p1", "consensus.stop")
        stops2 = read_events("p2", "consensus.stop")
        assert stops1 and stops2, (stops1, stops2)
        s1, s2 = stops1[-1], stops2[-1]
        assert s1["stop_step"] == s2["stop_step"], (s1, s2)
        assert s1["for_generation"] == s2["for_generation"], (s1, s2)
        stop = s1["stop_step"]
        # Both members' old-world step streams end at EXACTLY stop-1:
        # same boundary, zero skew — including the member that had not
        # yet seen the plan when it quiesced.
        last1 = max(
            r["step"]
            for r in _read_history(hist["p1"])
            if r["world_size"] == 2
        )
        last2 = max(
            r["step"]
            for r in _read_history(hist["p2"])
            if r["world_size"] == 2
        )
        assert last1 == last2 == stop - 1, (last1, last2, stop)
        # The survivor's scale-down resize journaled the same boundary,
        # and the new world resumed AT it (no replay, no gap).
        down = [
            rz
            for rz in _read_resizes(hist["p1"])
            if rz["world_size"] == 1 and rz["generation"] > 2
        ]
        assert down and down[-1]["stop_step"] == stop, (down, stop)
        h1 = _read_history(hist["p1"])
        steps_done = sorted(r["step"] for r in h1)
        assert steps_done == list(range(steps_done[-1] + 1)), "step gaps"
        assert all(math.isfinite(r["loss"]) for r in h1)
        # The chaos really delayed the poll (journaled injection).
        chaos_fired = read_events("p2", "chaos")
        assert any(
            c["point"] == "consensus.vote.delayed" for c in chaos_fired
        ), chaos_fired
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


def test_multipod_multichip_pods_1_2_1(tmp_path):
    """The deployed flagship shape: trainer pods that own a multi-chip
    slice (the spec's default ``slice_topology: v5e-4`` gives 4 chips
    per pod — ref trainer spec ``pkg/resource/training_job.go:128-134``).
    Two worker processes with 4 forced CPU devices each must form ONE
    dp world over all 8 devices (not the first 2), resize 1 -> 2 -> 1
    pods, and keep a contiguous loss stream.  VERDICT r3 missing-1: the
    mesh previously covered only the first ``world_size`` global
    devices, so pods >= 1 owned no mesh devices and the step could not
    run."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(
        target_world=1, max_world=2, heartbeat_timeout=60.0, legal_sizes=[1, 2]
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    hist = {w: tmp_path / f"{w}.jsonl" for w in ("m1", "m2")}
    procs = []

    def spawn(name, base_port):
        return _spawn_worker(procs, hist, name, base_port, caddr, devices=4)

    try:
        m1 = spawn("m1", 10500)
        _wait_for(
            lambda: len(_read_history(hist["m1"])) >= 3,
            180,
            "m1 stepping at world 1 (4 chips)",
            procs,
        )
        m2 = spawn("m2", 10560)
        coord.set_target_world(2)
        _wait_for(
            lambda: any(
                r["world_size"] == 2 for r in _read_history(hist["m1"])
            )
            and any(r["world_size"] == 2 for r in _read_history(hist["m2"])),
            240,
            "the 2-pod x 4-chip world to step",
            procs,
        )
        down_mark = len(_read_history(hist["m1"]))
        coord.set_target_world(1)
        _wait_for(
            lambda: any(
                r["world_size"] == 1
                for r in _read_history(hist["m1"])[down_mark:]
            ),
            240,
            "m1 back at world 1",
            procs,
        )
        for name, proc in (("m2", m2), ("m1", m1)):
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)
            _wait_for(
                lambda n=name: n not in coord.members(),
                30,
                f"{name} deregistered",
                procs,
            )

        h1 = _read_history(hist["m1"])
        # world_size in records counts TRAINER PODS (mesh devices /
        # devices_per_trainer), not devices: {1, 2}, never 4 or 8.
        assert {r["world_size"] for r in h1} == {1, 2}
        steps_done = sorted(r["step"] for r in h1)
        assert steps_done == list(range(steps_done[-1] + 1)), "step gaps"
        assert all(math.isfinite(r["loss"]) for r in h1)

        # The formation log proves the world really spanned all chips:
        # a 2-pod formation must carry 8 global devices (4 local each).
        formations = _read_formations(hist["m1"]) + _read_formations(
            hist["m2"]
        )
        two_pod = [f for f in formations if f["world_size"] == 2]
        assert two_pod, "no 2-pod formation recorded"
        for f in two_pod:
            assert f["devices"] == 8, f"2-pod world saw {f['devices']} devices"
            assert f["local_devices"] == 4
        one_pod = [f for f in formations if f["world_size"] == 1]
        assert all(f["devices"] == 4 for f in one_pod)

        # One world, one loss stream: both pods agree on shared steps.
        h2 = {r["step"]: r for r in _read_history(hist["m2"])}
        shared = [
            (r, h2[r["step"]])
            for r in h1
            if r["world_size"] == 2 and r["step"] in h2
        ]
        assert shared, "no overlapping world-2 steps recorded"
        for a, b in shared:
            assert abs(a["loss"] - b["loss"]) < 1e-5
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


@pytest.mark.skipif(
    os.environ.get("EDL_RUN_JOINER_RESTORE") != "1",
    reason="pre-existing jaxlib std::bad_cast on peer drop (not an edl "
    "regression; fails at pre-telemetry HEAD too — tracked in "
    "COVERAGE.md 'Known environment-limited skips'): the 3->2 "
    "scale-down's dropped peer trips jaxlib's coordination-service "
    "error path and kills a survivor before the world re-forms, "
    "most reliably on low-core boxes but reproducible under CI load "
    "anywhere.  Opt in with EDL_RUN_JOINER_RESTORE=1.",
)
def test_multipod_joiner_only_restore(tmp_path):
    """Graceful resizes must not broadcast the full state (VERDICT r3
    weak-1): survivors of a scale-down all hold the identical flushed
    checkpoint (agreed via the (step, digest) all-gather), so each
    restores from its LOCAL store — at transformer scale a per-resize
    full-model DCN broadcast would eat the <60s budget.  A fresh joiner
    still receives the state by broadcast."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(
        target_world=1,
        max_world=3,
        heartbeat_timeout=60.0,
        legal_sizes=[1, 2, 3],
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    hist = {w: tmp_path / f"{w}.jsonl" for w in ("a", "b", "c")}
    procs = []

    def spawn(name, base_port):
        # gbs=12: divisible by every legal world (1, 2, 3).
        return _spawn_worker(procs, hist, name, base_port, caddr, gbs=12)

    try:
        a = spawn("a", 10700)
        _wait_for(
            lambda: len(_read_history(hist["a"])) >= 3,
            180, "a stepping at world 1", procs,
        )
        b = spawn("b", 10760)
        coord.set_target_world(2)
        _wait_for(
            lambda: any(
                r["world_size"] == 2 for r in _read_history(hist["b"])
            ),
            240, "the 2-pod world to step", procs,
        )
        c = spawn("c", 10820)
        coord.set_target_world(3)
        _wait_for(
            lambda: any(
                r["world_size"] == 3 for r in _read_history(hist["c"])
            ),
            240, "the 3-pod world to step", procs,
        )
        # Scale down 3 -> 2: a and b survive, c stands by.
        coord.set_target_world(2)
        _wait_for(
            lambda: sum(
                rz["world_size"] == 2 for rz in _read_resizes(hist["a"])
            ) >= 2,
            240, "a's scale-down resize record", procs,
        )
        _wait_for(
            lambda: sum(
                rz["world_size"] == 2 for rz in _read_resizes(hist["b"])
            ) >= 2,
            240, "b's scale-down resize record", procs,
        )
        for name, proc in (("c", c), ("b", b), ("a", a)):
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)

        # -- restore-source assertions --------------------------------------
        ra = _read_resizes(hist["a"])
        rb = _read_resizes(hist["b"])
        rc = _read_resizes(hist["c"])
        # a started the job fresh.
        assert ra[0]["world_size"] == 1 and ra[0]["restore_source"] == "init"
        # Joiners receive state over the wire: b's 2-member world has
        # one holder (the fabric routes to the single-source stream,
        # "broadcast"); c's 3-member world has two holders, so the
        # parallel shard fabric feeds it ("fabric").
        first_b = next(rz for rz in rb if rz["world_size"] == 2)
        assert first_b["restore_source"] == "broadcast", rb
        first_c = next(rz for rz in rc if rz["world_size"] == 3)
        assert first_c["restore_source"] in ("broadcast", "fabric"), rc
        # The graceful scale-down (3 -> 2) moved NO state: survivors
        # restored locally from their own flushed checkpoint.
        down_a = [
            rz
            for rz in ra
            if rz["world_size"] == 2 and rz is not ra[0]
        ][-1]
        down_b = [rz for rz in rb if rz["world_size"] == 2][-1]
        assert down_a["restore_source"] == "local", ra
        assert down_b["restore_source"] == "local", rb
        assert down_a["graceful"] and down_b["graceful"]
        assert down_a["replayed_steps"] == 0, down_a

        # Step stream still contiguous on the rank-0 survivor.
        h1 = _read_history(hist["a"])
        steps_done = sorted(set(r["step"] for r in h1))
        assert steps_done == list(range(steps_done[-1] + 1))
        assert all(math.isfinite(r["loss"]) for r in h1)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


def test_multipod_multihost_replica_spans_processes(tmp_path):
    """Multi-host slice replicas (VERDICT r3 missing-2): one trainer
    replica = ``hosts`` pods, each its own process.  Two worker
    processes with 2 forced devices each form ONE replica (hosts=2,
    the v5e-16 shape); two more join as replica 1 and the world scales
    1 -> 2 replicas (4 processes, 8 devices), then back to 1.  The
    coordinator's replica grouping must hold the world at 0 until the
    first replica has BOTH hosts, count world_size in replicas, and
    drop the highest replica on scale-down."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(
        target_world=1,
        max_world=2,
        heartbeat_timeout=60.0,
        legal_sizes=[1, 2],
        hosts_per_replica=2,
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    names = ("r0h0", "r0h1", "r1h0", "r1h1")
    hist = {w: tmp_path / f"{w}.jsonl" for w in names}
    procs = []

    def spawn(name, base_port, replica, host):
        return _spawn_worker(
            procs, hist, name, base_port, caddr, devices=2, gbs=8,
            extra_env={
                "EDL_REPLICA": str(replica),
                "EDL_HOST_INDEX": str(host),
            },
        )

    try:
        spawn("r0h0", 10900, 0, 0)
        time.sleep(3)
        # half a replica: no formable world, no steps
        assert _read_history(hist["r0h0"]) == []
        assert coord.plan() is None or coord.plan().world_size == 0

        spawn("r0h1", 10960, 0, 1)
        _wait_for(
            lambda: len(_read_history(hist["r0h0"])) >= 3,
            180, "replica 0 stepping as one world", procs,
        )
        # world_size counts REPLICAS (1), not processes (2)
        assert all(
            r["world_size"] == 1 for r in _read_history(hist["r0h0"])
        )

        spawn("r1h0", 11020, 1, 0)
        spawn("r1h1", 11080, 1, 1)
        coord.set_target_world(2)
        _wait_for(
            lambda: any(
                r["world_size"] == 2 for r in _read_history(hist["r1h1"])
            ),
            240, "the 2-replica world to step", procs,
        )
        down_mark = len(_read_history(hist["r0h0"]))
        coord.set_target_world(1)
        _wait_for(
            lambda: any(
                r["world_size"] == 1
                for r in _read_history(hist["r0h0"])[down_mark:]
            ),
            240, "replica 0 back alone", procs,
        )
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            p.wait(timeout=60)

        h = _read_history(hist["r0h0"])
        assert {r["world_size"] for r in h} == {1, 2}
        steps_done = sorted(set(r["step"] for r in h))
        assert steps_done == list(range(steps_done[-1] + 1)), "step gaps"
        assert all(math.isfinite(r["loss"]) for r in h)

        # formation proof: a 2-replica world spans 4 processes x 2
        # devices = 8 global devices; a 1-replica world spans 4.
        fs = []
        for n in names:
            fs += _read_formations(hist[n])
        two = [f for f in fs if f["world_size"] == 2]
        assert two and all(
            f["devices"] == 8 and f["local_devices"] == 2 for f in two
        )
        one = [f for f in fs if f["world_size"] == 1]
        assert one and all(f["devices"] == 4 for f in one)

        # one world, one loss stream across ALL four pods at world 2
        base = {
            r["step"]: r["loss"]
            for r in h
            if r["world_size"] == 2
        }
        agreed = 0
        for n in names[1:]:
            for r in _read_history(hist[n]):
                if r["world_size"] == 2 and r["step"] in base:
                    assert abs(r["loss"] - base[r["step"]]) < 1e-5
                    agreed += 1
        assert agreed > 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


def test_multipod_ungraceful_kill_evicts_and_reforms(tmp_path):
    """Failure detection end-to-end with real processes: SIGKILL (no
    graceful handshake) one member of a 2-pod world.  The survivor must
    hold at the resize barrier until the dead pod's heartbeat lease
    expires, get readmitted by the eviction-bumped generation, re-form
    a world-1 process group, and keep training with step continuity —
    the reference delegated all of this to master/etcd re-registration
    (SURVEY.md §5.3)."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(
        target_world=1, max_world=2, heartbeat_timeout=8.0, legal_sizes=[1, 2]
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    hist = {w: tmp_path / f"{w}.jsonl" for w in ("k1", "k2")}
    procs = []

    def spawn(name, base_port):
        return _spawn_worker(procs, hist, name, base_port, caddr)

    try:
        k1 = spawn("k1", 10300)
        _wait_for(
            lambda: len(_read_history(hist["k1"])) >= 3,
            180, "k1 stepping at world 1", procs,
        )
        k2 = spawn("k2", 10360)
        coord.set_target_world(2)
        _wait_for(
            lambda: any(
                r["world_size"] == 2 for r in _read_history(hist["k1"])
            ),
            240, "the 2-pod world to step", procs,
        )

        # Hard kill: no SIGTERM handshake, no deregister, no flush.
        mark = len(_read_history(hist["k1"]))
        k2.kill()
        k2.wait(timeout=30)
        procs.remove(k2)
        assert "k2" in coord.members(), "kill must NOT deregister"

        # Lease reaper evicts k2; k1 re-forms alone and keeps stepping.
        _wait_for(lambda: "k2" not in coord.members(), 60, "k2 evicted")
        _wait_for(
            lambda: any(
                r["world_size"] == 1
                for r in _read_history(hist["k1"])[mark:]
            ),
            240, "k1 training again at world 1", procs,
        )

        h1 = _read_history(hist["k1"])
        # Step stream is contiguous: the replayed window after the
        # ungraceful loss re-runs the same deterministic steps.
        steps_done = sorted(set(r["step"] for r in h1))
        assert steps_done == list(range(steps_done[-1] + 1))
        assert all(math.isfinite(r["loss"]) for r in h1)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


def test_broken_world_teardown_skips_shutdown_barrier(monkeypatch):
    """After a mid-step collective failure the next teardown must NOT
    run jax.distributed.shutdown: its barrier cannot complete (dead
    peers never arrive) and the coordination service's barrier-failure
    propagation can terminate() the surviving process from a C++ thread
    (std::bad_cast observed under CI load).  The dead world's handles
    are leaked instead — inert, because the per-generation port window
    never reuses the dead world's port."""
    import jax

    from edl_tpu.launcher import make_world_builder
    from edl_tpu.runtime.elastic import ElasticTrainer

    build = make_world_builder("t0")
    assert callable(build.mark_broken)

    from jax._src import distributed

    gs = distributed.global_state
    sentinel_client, sentinel_service = object(), object()
    monkeypatch.setattr(gs, "client", sentinel_client, raising=False)
    monkeypatch.setattr(gs, "service", sentinel_service, raising=False)
    calls = []
    monkeypatch.setattr(
        jax.distributed, "shutdown", lambda: calls.append("barrier")
    )

    # _world_broken forwards the signal through the builder attribute
    import threading

    et = ElasticTrainer.__new__(ElasticTrainer)
    et.world_builder = build
    et._trainers = {}
    et._trainer_lock = threading.Lock()
    et._cache_epoch = 0
    et._failed_prewarms = set()
    et.state = None
    et.mesh = None
    et._world_broken()

    class Plan:
        members = ["someone-else"]  # not t0: teardown-only path
        addresses = []
        generation = 7
        world_size = 1

    assert build(Plan()) is None  # standby: teardown ran, no re-init
    assert calls == [], "broken teardown must not enter the barrier"
    assert gs.client is None and gs.service is None  # handles dropped

    # A GRACEFUL teardown (no broken mark) still uses the barrier.
    monkeypatch.setattr(gs, "client", sentinel_client, raising=False)
    assert build(Plan()) is None
    assert calls == ["barrier"]

    # Fatal-exit path: leak_dead_world abandons handles barrier-free
    # (no next build() will run teardown for the re-raising trainer).
    monkeypatch.setattr(gs, "client", sentinel_client, raising=False)
    build.leak_dead_world()
    assert calls == ["barrier"]  # no new barrier entry
    assert gs.client is None

    # Leak budget: the cap raises FatalWorldError AFTER securing the
    # handles (a budget-exhausted process must exit with a traceback,
    # not a destructor-triggered barrier abort), and never barriers.
    import pytest

    from edl_tpu.runtime.elastic import FatalWorldError

    with pytest.raises(FatalWorldError, match="budget exhausted"):
        for _ in range(40):
            monkeypatch.setattr(gs, "client", object(), raising=False)
            build.leak_dead_world()
    assert gs.client is None  # secured before the raise
    assert calls == ["barrier"]


def test_multipod_layout_fsdp_1_2_1(tmp_path):
    """Deployable dp x fsdp layout across real pods (VERDICT r4 #1+#3):
    two 2-chip pods train mnist with ``EDL_PARALLELISM=fsdp=2`` — params
    sharded over each pod's intra-pod fsdp axis, replicated over the
    cross-pod dp axis — and resize 1 -> 2 -> 1 pods.  Every
    post-formation resize must be GRACEFUL with ZERO replayed steps:
    the flush assembles the full state from local shards
    (``hostdram._cover_regions``) instead of skipping because leaves
    aren't fully addressable (the r4 ``_can_flush_without_collectives``
    degradation)."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(
        target_world=1, max_world=2, heartbeat_timeout=60.0, legal_sizes=[1, 2]
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    hist = {w: tmp_path / f"{w}.jsonl" for w in ("f1", "f2")}
    procs = []

    def spawn(name, base_port):
        return _spawn_worker(
            procs, hist, name, base_port, caddr,
            devices=2, gbs=16, entrypoint="mnist", parallelism="fsdp=2",
            checkpoint_interval=50,  # far apart: zero replay must come
        )                            # from the flush, not a lucky interval

    try:
        f1 = spawn("f1", 11100)
        _wait_for(
            lambda: len(_read_history(hist["f1"])) >= 3,
            240,
            "f1 stepping at world 1 (dp1 x fsdp2)",
            procs,
        )
        f2 = spawn("f2", 11160)
        coord.set_target_world(2)
        _wait_for(
            lambda: any(
                r["world_size"] == 2 for r in _read_history(hist["f1"])
            )
            and any(r["world_size"] == 2 for r in _read_history(hist["f2"])),
            300,
            "the dp2 x fsdp2 world to step",
            procs,
        )
        down_mark = len(_read_history(hist["f1"]))
        coord.set_target_world(1)
        _wait_for(
            lambda: any(
                r["world_size"] == 1
                for r in _read_history(hist["f1"])[down_mark:]
            ),
            300,
            "f1 back at world 1",
            procs,
        )
        for name, proc in (("f2", f2), ("f1", f1)):
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)
            _wait_for(
                lambda n=name: n not in coord.members(),
                30,
                f"{name} deregistered",
                procs,
            )

        h1 = _read_history(hist["f1"])
        assert {r["world_size"] for r in h1} == {1, 2}
        steps_done = sorted(r["step"] for r in h1)
        assert steps_done == list(range(steps_done[-1] + 1)), "step gaps"
        assert all(math.isfinite(r["loss"]) for r in h1)
        # Convergence through both sharded resizes (loss continuity).
        head = sum(r["loss"] for r in h1[:3]) / 3
        tail = sum(r["loss"] for r in h1[-3:]) / 3
        assert tail < head * 0.7, f"no convergence: head={head} tail={tail}"

        # THE criterion (VERDICT r4 #3): every resize after the initial
        # formation is graceful with zero replayed steps, even though
        # the fsdp-sharded leaves are not fully addressable.
        resizes = _read_resizes(hist["f1"])
        assert len(resizes) >= 3, f"expected >= 3 resizes, got {resizes}"
        for ev in resizes[1:]:
            assert ev["graceful"], f"non-graceful sharded resize: {ev}"
            assert ev["replayed_steps"] == 0, f"replay on resize: {ev}"
        # Survivor restores locally (no cross-pod state motion).
        assert all(
            ev["restore_source"] in ("local", "broadcast", "fabric")
            for ev in resizes[1:]
        )
        down = [ev for ev in resizes if ev["world_size"] == 1][-1:]
        assert down and down[0]["restore_source"] == "local"

        # Sharded world really spanned 4 devices (2 pods x 2 chips).
        formations = _read_formations(hist["f1"])
        two_pod = [f for f in formations if f["world_size"] == 2]
        assert two_pod and all(f["devices"] == 4 for f in two_pod)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


def test_multipod_fabric_joiner_parallel_restore_no_full_sender(tmp_path):
    """Sharded p2p checkpoint fabric (ROADMAP item 3): a joiner
    restoring a dp x fsdp layout is fed by MULTIPLE peers in parallel
    with NO single peer sending the full state — asserted from the
    per-peer wire-byte accounting in the joiner's resize record, the
    same proof style as PR 2's delta accounting.  Three 2-chip pods
    run mnist with ``EDL_PARALLELISM=fsdp=2``; the world grows 1 -> 2
    (one holder: the fabric deterministically routes to the PR 2
    single-source stream) -> 3 (two holders: the parallel fabric)."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(
        target_world=1,
        max_world=3,
        heartbeat_timeout=60.0,
        legal_sizes=[1, 2, 3],
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    names = ("p1", "p2", "p3")
    hist = {w: tmp_path / f"{w}.jsonl" for w in names}
    procs = []

    def spawn(name, base_port):
        return _spawn_worker(
            procs, hist, name, base_port, caddr,
            devices=2, gbs=12, entrypoint="mnist", parallelism="fsdp=2",
            checkpoint_interval=50,
            # Tiny shards so even mnist's state spreads over many
            # owners (production default is 32MB).
            extra_env={"EDL_FABRIC_SHARD_BYTES": "2048"},
        )

    try:
        p1 = spawn("p1", 12700)
        _wait_for(
            lambda: len(_read_history(hist["p1"])) >= 3,
            240, "p1 stepping at world 1", procs,
        )
        p2 = spawn("p2", 12760)
        coord.set_target_world(2)
        _wait_for(
            lambda: any(
                r["world_size"] == 2 for r in _read_history(hist["p2"])
            ),
            300, "the 2-pod world to step", procs,
        )
        p3 = spawn("p3", 12820)
        coord.set_target_world(3)
        _wait_for(
            lambda: any(
                r["world_size"] == 3 for r in _read_history(hist["p3"])
            ),
            300, "the 3-pod world to step", procs,
        )
        # Assert from the journals while the world is still up, then
        # tear every pod down TOGETHER: sequential SIGTERMs would
        # drive the survivors through a 3 -> 2 resize first (the shape
        # the known jaxlib bad_cast issue lives in — see
        # test_multipod_joiner_only_restore's gate).

        # One holder at p2's join: the single-source stream.
        first_2 = next(
            rz
            for rz in _read_resizes(hist["p2"])
            if rz["world_size"] == 2
        )
        assert first_2["restore_source"] == "broadcast", first_2

        # Two holders at p3's join: THE fabric claim.
        first_3 = next(
            rz
            for rz in _read_resizes(hist["p3"])
            if rz["world_size"] == 3
        )
        assert first_3["restore_source"] == "fabric", first_3
        t = first_3["transfer"]
        assert t["mode"] == "fabric", t
        per_peer = t["per_peer_bytes"]
        assert len(per_peer) >= 2, per_peer
        assert sum(per_peer.values()) == t["bytes_received"], t
        # NO single peer sent the full state.
        assert max(per_peer.values()) < t["bytes_received"], per_peer
        assert min(per_peer.values()) > 0, per_peer

        # Step stream stays contiguous and finite on the first pod.
        h1 = _read_history(hist["p1"])
        steps_done = sorted(set(r["step"] for r in h1))
        assert steps_done == list(range(steps_done[-1] + 1))
        assert all(math.isfinite(r["loss"]) for r in h1)

        for proc in (p3, p2, p1):
            proc.send_signal(signal.SIGTERM)
        for proc in (p3, p2, p1):
            try:
                proc.wait(timeout=90)
            except subprocess.TimeoutExpired:
                proc.kill()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


def test_multipod_durable_checkpoint_survives_whole_world_loss(tmp_path):
    """Whole-world loss (full slice preemption: EVERY pod SIGKILLed at
    once) must resume from the durable checkpoint dir, not restart at
    step 0 (VERDICT r4 #2).  Both pods run with EDL_CHECKPOINT_DIR on a
    shared volume; after the massacre the restarted pods' first resize
    cold-loads the spilled step and training continues past it."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    ckpt_dir = tmp_path / "durable"
    coord = LocalCoordinator(
        target_world=2, max_world=2, heartbeat_timeout=15.0, legal_sizes=[1, 2]
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    hist = {w: tmp_path / f"{w}.jsonl" for w in ("d1", "d2")}
    procs = []
    env = {"EDL_CHECKPOINT_DIR": str(ckpt_dir)}

    def spawn(name, base_port):
        return _spawn_worker(
            procs, hist, name, base_port, caddr,
            checkpoint_interval=3, extra_env=env,
        )

    try:
        d1 = spawn("d1", 11300)
        d2 = spawn("d2", 11360)
        # Step well past a checkpoint interval so a spill landed.
        _wait_for(
            lambda: len(_read_history(hist["d1"])) >= 8
            and any(ckpt_dir.glob("ckpt-*.json")),
            240,
            "2-pod world past a durable checkpoint",
            procs,
        )
        # Full slice preemption: no SIGTERM grace, no survivors.
        for p in (d1, d2):
            p.kill()
            p.wait(timeout=30)
        # The massacre is intentional: drop the corpses from the
        # watchlist so _wait_for doesn't read rc=-9 as a test failure.
        procs.clear()
        last_before = max(r["step"] for r in _read_history(hist["d1"]))
        spilled = sorted(
            int(f.name[len("ckpt-"):-len(".json")])
            for f in ckpt_dir.glob("ckpt-*.json")
        )
        assert spilled and spilled[-1] > 0, f"nothing spilled: {spilled}"

        # Cold start: the replacement pods come up with empty DRAM and
        # FRESH names (a k8s Job's restart-all creates new pods; the
        # SIGKILLed names linger at the coordinator until lease expiry
        # and the newcomers stand by until the reaper evicts them).
        hist["d3"] = tmp_path / "d3.jsonl"
        hist["d4"] = tmp_path / "d4.jsonl"
        spawn("d3", 11420)
        spawn("d4", 11480)
        _wait_for(
            lambda: len(_read_history(hist["d3"])) >= 5,
            240,
            "restarted world stepping",
            procs,
        )
        post = _read_history(hist["d3"])
        # Resumed FROM the durable step: nothing re-ran from step 0.
        assert min(r["step"] for r in post) >= spilled[0], (
            f"cold start replayed from step {min(r['step'] for r in post)}, "
            f"durable had {spilled}"
        )
        assert max(r["step"] for r in post) > last_before
        cold = _read_resizes(hist["d3"])[-1]
        assert cold["restored_step"] >= spilled[0] > 0, cold
        assert cold["restore_source"] in ("local", "broadcast", "fabric"), cold
        assert all(math.isfinite(r["loss"]) for r in post)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


def test_multipod_layout_with_durable_checkpoint_massacre(tmp_path):
    """The round's two headline features COMPOSED: a dp x fsdp layout
    job (EDL_PARALLELISM=fsdp=2, params sharded over each pod's 2
    chips) with a durable checkpoint dir survives a whole-world SIGKILL
    — the replacement pods cold-load the spilled (host-assembled,
    full-value) checkpoint and reshard it onto the rebuilt layout mesh."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    ckpt_dir = tmp_path / "durable"
    coord = LocalCoordinator(
        target_world=2, max_world=2, heartbeat_timeout=15.0, legal_sizes=[1, 2]
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    hist = {w: tmp_path / f"{w}.jsonl" for w in ("l1", "l2", "l3", "l4")}
    procs = []
    env = {"EDL_CHECKPOINT_DIR": str(ckpt_dir)}

    def spawn(name, base_port):
        return _spawn_worker(
            procs, hist, name, base_port, caddr,
            devices=2, gbs=16, entrypoint="mnist", parallelism="fsdp=2",
            checkpoint_interval=3, extra_env=env,
        )

    try:
        spawn("l1", 11600)
        spawn("l2", 11660)
        _wait_for(
            lambda: len(_read_history(hist["l1"])) >= 7
            and any(ckpt_dir.glob("ckpt-*.json")),
            300,
            "sharded world past a durable checkpoint",
            procs,
        )
        for p in list(procs):
            p.kill()
            p.wait(timeout=30)
        procs.clear()
        spilled = sorted(
            int(f.name[len("ckpt-"):-len(".json")])
            for f in ckpt_dir.glob("ckpt-*.json")
        )
        assert spilled and spilled[-1] > 0

        spawn("l3", 11720)
        spawn("l4", 11780)
        _wait_for(
            lambda: len(_read_history(hist["l3"])) >= 5,
            300,
            "restarted sharded world stepping",
            procs,
        )
        post = _read_history(hist["l3"])
        assert min(r["step"] for r in post) >= spilled[0], (
            f"replayed from {min(r['step'] for r in post)}, had {spilled}"
        )
        cold = _read_resizes(hist["l3"])[-1]
        assert cold["restored_step"] >= spilled[0] > 0, cold
        assert all(math.isfinite(r["loss"]) for r in post)
        # The restarted world is STILL the layout mesh (2 pods x 2
        # chips, dp2 x fsdp2): its formations span 4 devices.
        formations = _read_formations(hist["l3"])
        assert formations and formations[-1]["devices"] == 4
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


def test_multipod_cross_pod_tensor_parallel_hold_and_recover(tmp_path):
    """A tp=2 layout SPANNING pods (1 chip each): the model's kernels
    shard across processes and every forward runs cross-pod
    collectives.  With the layout, only even worlds are legal, so when
    one pod is SIGKILLed there is NO formable world until a
    replacement arrives.  The SYSTEM must recover: ideally the
    survivor holds at the resize barrier (world_size 0) and re-forms
    when the replacement registers; jaxlib's coordination service can
    also terminate() pods from its C++ error-poll thread
    (std::bad_cast — no Python-level defense exists), in which case
    the Job controller restarts them and recovery flows through the
    durable checkpoint dir.  This test emulates the Job controller (a
    restart pool, like kubelet + backoffLimit) and requires that SOME
    re-formed 2-pod sharded world trains past the pre-kill step
    without ever replaying from step 0."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(
        target_world=2, max_world=2, heartbeat_timeout=8.0, legal_sizes=[2]
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    hist = {}
    procs = []
    env = {"EDL_CHECKPOINT_DIR": str(tmp_path / "durable")}
    next_id = [0]

    def spawn():
        name = f"t{next_id[0]}"
        next_id[0] += 1
        hist[name] = tmp_path / f"{name}.jsonl"
        return _spawn_worker(
            procs, hist, name, 11900 + 30 * next_id[0], caddr,
            devices=1, gbs=16, entrypoint="mnist", parallelism="tp=2",
            checkpoint_interval=3, extra_env=env,
        )

    try:
        spawn()
        t_victim = spawn()
        first = hist["t0"]
        _wait_for(
            lambda: len(_read_history(first)) >= 7,
            300,
            "the cross-pod tp world to step",
            procs,
        )
        mark = max(r["step"] for r in _read_history(first))

        # Ungraceful peer death: no formable world remains.
        t_victim.kill()
        t_victim.wait(timeout=30)
        procs.remove(t_victim)
        spawn()  # the replacement pod

        # Job-controller emulation: restart any pod the coordination
        # service's error propagation kills, up to a restart budget.
        deadline = time.monotonic() + 300
        restarts = 0
        while time.monotonic() < deadline:
            if any(
                r["step"] > mark + 3
                for h in hist.values()
                for r in _read_history(h)
            ):
                break
            for pr in list(procs):
                if pr.poll() is not None:
                    procs.remove(pr)
                    restarts += 1
                    assert restarts <= 6, "restart budget exhausted"
                    spawn()
            time.sleep(0.5)
        else:
            raise AssertionError(
                "re-formed tp world never passed the pre-kill step; "
                f"restarts={restarts}"
            )

        all_recs = [r for h in hist.values() for r in _read_history(h)]
        assert all(math.isfinite(r["loss"]) for r in all_recs)
        # THE recovery property: every pod spawned AFTER the kill (t2+)
        # resumed from a checkpoint — a from-scratch re-init would
        # record step 0 and, with deterministic init+data, silently
        # reproduce the original losses, so only this step floor
        # catches that regression.
        for name, h in hist.items():
            if name in ("t0", "t1"):
                continue
            steps = [r["step"] for r in _read_history(h)]
            if steps:
                assert min(steps) > 0, (
                    f"{name} replayed from step {min(steps)} — "
                    "recovery did not come from a checkpoint"
                )
        post = [
            r
            for h in hist.values()
            for r in _read_history(h)
            if h != hist["t1"]  # the SIGKILLed victim's partial log
        ]
        by_step = {}
        for r in sorted(post, key=lambda r: r["step"]):
            if r["step"] in by_step:
                # replays are deterministic (same restored state +
                # deterministic data)
                assert abs(r["loss"] - by_step[r["step"]]) < 1e-4
            by_step[r["step"]] = r["loss"]
        # Every formation spans exactly 2 single-chip pods (the
        # sharded layout, never a degenerate world).
        for h in hist.values():
            for f in _read_formations(h):
                assert f["devices"] == 2, f
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


def test_multipod_merged_trace_one_id_decision_to_first_step(tmp_path):
    """Acceptance walk of the causal-tracing tentpole over a REAL
    2-process world: a trace-tagged scale-up (prewarm hint + retarget
    under one minted id) and a trace-tagged scale-down (the consensus
    stop path), then ONE merged clock-aligned timeline in which
    plan rebuild -> consensus vote/stop -> quiesce -> resize
    (flush/restore) -> first post-resize step all share the minted
    trace id, with per-member lanes in causal order."""
    from edl_tpu.runtime.coord_service import (
        CoordinatorServer,
        HTTPCoordinator,
    )
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.telemetry import new_trace_id
    from edl_tpu.telemetry.trace import (
        chrome_trace,
        load_journal,
        merge_events,
        trace_chains,
    )

    coord = LocalCoordinator(
        target_world=1, max_world=2, heartbeat_timeout=60.0, legal_sizes=[1, 2]
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    hist = {w: tmp_path / f"{w}.jsonl" for w in ("m1", "m2")}
    events = {w: tmp_path / f"{w}.events.jsonl" for w in ("m1", "m2")}
    procs = []

    def spawn(name, base_port):
        return _spawn_worker(
            procs, hist, name, base_port, caddr,
            extra_env={
                "EDL_FLIGHT_RECORDER_FILE": str(events[name]),
                # tight cadence so clock offsets + event tails land at
                # the coordinator well inside the waits below
                "EDL_TELEMETRY_INTERVAL": "1.0",
            },
        )

    try:
        m1 = spawn("m1", 12300)
        _wait_for(
            lambda: len(_read_history(hist["m1"])) >= 5,
            180, "m1 stepping at world 1", procs,
        )
        m2 = spawn("m2", 12360)
        _wait_for(
            lambda: "m2" in coord.members(), 60, "m2 registered", procs
        )

        # -- the autoscaler's actuation, in miniature ---------------------
        client = HTTPCoordinator(caddr)
        up = new_trace_id()
        client.set_prewarm(2, trace_id=up)
        client.set_target_world(2, trace_id=up)
        _wait_for(
            lambda: any(
                r["world_size"] == 2 for r in _read_history(hist["m1"])
            )
            and any(r["world_size"] == 2 for r in _read_history(hist["m2"])),
            240, "the 2-pod world to step", procs,
        )

        down_mark = len(_read_history(hist["m1"]))
        down = new_trace_id()
        client.set_prewarm(1, trace_id=down)
        client.set_target_world(1, trace_id=down)
        _wait_for(
            lambda: any(
                r["world_size"] == 1
                for r in _read_history(hist["m1"])[down_mark:]
            ),
            240, "m1 back at world 1", procs,
        )
        # one more telemetry cadence so the tail (resize, step.first)
        # reaches the coordinator too
        time.sleep(2.5)
        offsets = {
            m: o
            for m, o in coord.telemetry()["clock_offsets"].items()
            if o is not None
        }
        for name, proc in (("m2", m2), ("m1", m1)):
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)

        # -- merge the cluster's journals into one timeline ---------------
        streams = {
            "coordinator": [
                e.to_dict() for e in coord.recorder().events()
            ],
            "m1": load_journal(str(events["m1"])),
            "m2": load_journal(str(events["m2"])),
        }
        merged = merge_events(streams, offsets)
        chains = trace_chains(merged)

        def chain_kinds(trace_id, member=None):
            return [
                e["kind"]
                for e in chains.get(trace_id, [])
                if member is None or e["member"] == member
            ]

        # Scale-up: the hint-driven prewarm, both members' resizes and
        # their first post-resize steps share the minted id.
        up_m1 = chain_kinds(up, "m1")
        up_m2 = chain_kinds(up, "m2")
        assert "resize" in up_m1 and "resize" in up_m2, (up_m1, up_m2)
        assert "step.first" in up_m1 and "step.first" in up_m2
        assert "coord.plan" in chain_kinds(up, "coordinator")

        # Scale-down: the full causal chain under ONE id — the plan
        # rebuild, the data-plane stop agreement (vote on at least one
        # member, the learned stop + quiesce on both), the survivor's
        # resize, and its first post-resize step.
        assert "coord.plan" in chain_kinds(down, "coordinator")
        all_down = chain_kinds(down)
        assert "consensus.vote" in all_down, all_down
        for member in ("m1", "m2"):
            kinds = chain_kinds(down, member)
            assert "consensus.stop" in kinds, (member, kinds)
            assert "consensus.quiesce" in kinds, (member, kinds)
        down_m1 = chain_kinds(down, "m1")
        assert "resize" in down_m1 and "step.first" in down_m1, down_m1
        # checkpoint flush inside the traced window (graceful resize)
        assert "checkpoint.save" in down_m1, down_m1

        # Causal order after clock alignment: decision -> vote ->
        # quiesce -> resize -> first step, strictly by aligned wall.
        def first_t(trace_id, kind, member=None):
            for e in chains[trace_id]:
                if e["kind"] == kind and (
                    member is None or e["member"] == member
                ):
                    return e["wall_aligned"]
            raise AssertionError(f"{kind} missing from chain")

        t_plan = first_t(down, "coord.plan", "coordinator")
        t_vote = first_t(down, "consensus.vote")
        t_quiesce = first_t(down, "consensus.quiesce", "m1")
        t_resize = first_t(down, "resize", "m1")
        t_first = first_t(down, "step.first", "m1")
        assert t_plan <= t_vote <= t_quiesce <= t_resize <= t_first, (
            t_plan, t_vote, t_quiesce, t_resize, t_first,
        )

        # The members really reported clock offsets (same host: ~0),
        # and the Chrome-trace doc has one lane per member.
        assert {"m1", "m2"} <= set(offsets)
        assert all(abs(o) < 5.0 for o in offsets.values()), offsets
        doc = chrome_trace(merged)
        lanes = {
            e["args"]["name"]
            for e in doc["traceEvents"]
            if e["name"] == "process_name"
        }
        assert {"coordinator", "m1", "m2"} <= lanes
        # the survivor's resize renders as a duration slice with its
        # serial phases as children
        slice_names = {
            e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"
        }
        assert "resize" in slice_names
        assert any(n.startswith("resize/") for n in slice_names)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()


def test_multipod_shard_only_spills_joiner_and_cold_start(tmp_path):
    """EDL_SHARD_ONLY=1 end to end (ISSUE 19): a 2-pod world runs with
    shard-only host checkpoints — every durable spill is a per-rank
    SHARD file (no full-copy manifest ever exists), a third pod joins
    against peers whose DRAM holds only resident shards, and after a
    whole-world massacre the replacement pods cold-start from the
    shard-spill UNION: each seeds only its wanted ranges and the
    agreement assembles the rest over the fabric — training resumes
    past the spilled step with NO full checkpoint file anywhere."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    ckpt_dir = tmp_path / "durable"
    coord = LocalCoordinator(
        target_world=2, max_world=3, heartbeat_timeout=15.0,
        legal_sizes=[1, 2, 3],
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    caddr = f"127.0.0.1:{server.port}"
    names = ("s1", "s2", "s3", "s4", "s5")
    hist = {w: tmp_path / f"{w}.jsonl" for w in names}
    procs = []
    env = {
        "EDL_CHECKPOINT_DIR": str(ckpt_dir),
        "EDL_SHARD_ONLY": "1",
        # Tiny shards so even fit_a_line's state spreads over many
        # owners (production default is 32MB).
        "EDL_FABRIC_SHARD_BYTES": "512",
    }

    def spawn(name, base_port):
        # gbs=12: divisible by every legal world (1, 2, 3).
        return _spawn_worker(
            procs, hist, name, base_port, caddr,
            checkpoint_interval=3, gbs=12, extra_env=env,
        )

    try:
        s1 = spawn("s1", 13300)
        s2 = spawn("s2", 13360)
        _wait_for(
            lambda: len(_read_history(hist["s1"])) >= 8
            and any(ckpt_dir.glob("ckpt-*.json")),
            240,
            "2-pod shard-only world past a durable spill",
            procs,
        )
        # THE spill-plane claim: shard files only, never a full copy.
        spills = sorted(f.name for f in ckpt_dir.glob("ckpt-*"))
        assert spills, "nothing spilled"
        assert all(".shard-r" in n for n in spills), (
            f"full-copy spill leaked from a shard-only world: {spills}"
        )

        # A joiner restores from peers that hold shard residency.
        s3 = spawn("s3", 13420)
        coord.set_target_world(3)
        _wait_for(
            lambda: any(
                r["world_size"] == 3 for r in _read_history(hist["s3"])
            ),
            300, "the 3-pod shard-only world to step", procs,
        )
        first_3 = next(
            rz
            for rz in _read_resizes(hist["s3"])
            if rz["world_size"] == 3
        )
        assert first_3["restore_source"] in ("fabric", "broadcast"), first_3
        assert first_3["restored_step"] > 0, first_3

        # Whole-world massacre: no survivors, DRAM everywhere is gone.
        for p in (s1, s2, s3):
            p.kill()
            p.wait(timeout=30)
        procs.clear()
        last_before = max(r["step"] for r in _read_history(hist["s1"]))
        covered = sorted(
            set(
                int(f.name[len("ckpt-"):].split(".")[0])
                for f in ckpt_dir.glob("ckpt-*.json")
            )
        )
        assert covered and covered[-1] > 0, f"nothing spilled: {covered}"

        # Cold start from the shard union: fresh pods, empty DRAM; the
        # durable dir holds only per-rank shard files written by a
        # DIFFERENT world size (boundaries are world-independent).
        coord.set_target_world(2)
        spawn("s4", 13480)
        spawn("s5", 13540)
        _wait_for(
            lambda: len(_read_history(hist["s4"])) >= 5,
            240,
            "shard-only cold-started world stepping",
            procs,
        )
        post = _read_history(hist["s4"])
        assert min(r["step"] for r in post) >= covered[0], (
            f"cold start replayed from {min(r['step'] for r in post)}, "
            f"durable shard union had {covered}"
        )
        assert max(r["step"] for r in post) > 0
        cold = _read_resizes(hist["s4"])[-1]
        assert cold["restored_step"] >= covered[0] > 0, cold
        assert all(math.isfinite(r["loss"]) for r in post)
        # Still no full-copy file after the entire exercise.
        assert all(
            ".shard-r" in f.name for f in ckpt_dir.glob("ckpt-*")
        )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
