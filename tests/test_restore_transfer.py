"""Streaming delta-aware restore transfer (checkpoint/transfer.py).

The engine runs here EXACTLY as in production — real TCP on loopback,
real chunk CRCs, real per-leaf digest agreement — with only the tiny
allgather swapped for a barrier fabric (``LoopbackWorld``), so the
wire accounting these tests assert is the production transport's.

The headline regression: a single-joiner resize moves ONLY the bytes
the joiner lacks (the delta path), never the full state — the property
that retired the 25.5s monolithic broadcast (BENCH_r05, ISSUE 2).
"""

import threading
import time

import numpy as np
import pytest

import jax

from edl_tpu.chaos import FaultEvent, FaultSchedule
from edl_tpu.checkpoint.hostdram import HostCheckpoint, HostDRAMStore
from edl_tpu.checkpoint import transfer as tx


# ---- harness ---------------------------------------------------------------


def make_ckpt(leaves, step=10):
    _, treedef = jax.tree_util.tree_flatten(list(leaves))
    return HostCheckpoint(
        step=step, generation=1, leaves=list(leaves), treedef=treedef
    )


def template_of(leaves):
    return [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]


def run_world(member_fns):
    """Run one ``stream_restore`` agreement across N in-process
    members (each on its own thread, as in N real pods).  Returns the
    per-member results; re-raises the first member error."""
    world = tx.LoopbackWorld(len(member_fns))
    results = [None] * len(member_fns)
    errors = [None] * len(member_fns)

    def runner(rank, fn):
        try:
            results[rank] = fn(world.fabric(rank))
        except BaseException as e:  # noqa: BLE001 - re-raised below
            errors[rank] = e

    threads = [
        threading.Thread(target=runner, args=(r, fn), daemon=True)
        for r, fn in enumerate(member_fns)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "member thread hung"
    for e in errors:
        if e is not None:
            raise e
    return results


def source_leaves(seed=0):
    rng = np.random.RandomState(seed)
    return [
        rng.randn(64, 32).astype(np.float32),
        rng.randn(257, 16).astype(np.float32),  # odd row count
        np.asarray(rng.randint(0, 100), np.int32).reshape(()),  # 0-d step
        rng.randn(1000).astype(np.float64),
    ]


# ---- the delta agreement ---------------------------------------------------


def test_single_joiner_moves_only_missing_leaves():
    """THE acceptance property (ISSUE 2): a joiner that already holds
    matching bytes for some leaves receives ONLY the diverged ones —
    zero bytes on the wire for leaves it already holds."""
    leaves = source_leaves()
    src_ckpt = make_ckpt(leaves, step=20)
    # The joiner holds an older checkpoint in which leaves 0 and 3
    # are byte-identical to the source's, leaf 1 diverged, and the
    # 0-d step leaf (2) differs (older step).
    stale = [
        leaves[0],
        leaves[1] + 1.0,
        np.asarray(int(leaves[2]) - 5, np.int32).reshape(()),
        leaves[3],
    ]
    stale_ckpt = make_ckpt(stale, step=15)
    template = template_of(leaves)
    missing_bytes = leaves[1].nbytes + leaves[2].nbytes

    r0, r1 = run_world(
        [
            lambda f: tx.stream_restore(f, template, src_ckpt),
            lambda f: tx.stream_restore(f, template, stale_ckpt),
        ]
    )
    assert r0.stats.mode == r1.stats.mode == "delta"
    assert r0.stats.source_rank == 0 and r1.stats.source_rank == 0
    assert r0.stats.step == r1.stats.step == 20
    # Wire accounting: exactly the joiner's missing leaves, nothing
    # for the leaves it already held.
    assert r1.stats.bytes_received == missing_bytes
    assert r0.stats.bytes_sent == missing_bytes
    assert r0.stats.bytes_scheduled == missing_bytes
    assert r1.stats.leaves_received == 2
    assert r1.stats.leaves_skipped == 2
    # The assembled state is the source's, bit for bit.
    for got, want in zip(r1.leaves, leaves):
        np.testing.assert_array_equal(np.asarray(got).reshape(want.shape), want)
    # Zero-copy: held leaves are adopted by reference, not copied.
    assert r1.leaves[0] is stale[0]
    assert r1.leaves[3] is stale[3]


def test_fresh_joiner_receives_everything_with_overlap_callback():
    leaves = source_leaves(1)
    src_ckpt = make_ckpt(leaves, step=7)
    template = template_of(leaves)
    total = sum(l.nbytes for l in leaves)
    placed = []

    r0, r1 = run_world(
        [
            lambda f: tx.stream_restore(f, template, src_ckpt),
            lambda f: tx.stream_restore(
                f,
                template,
                None,
                on_leaf=lambda i, a: placed.append(i),
            ),
        ]
    )
    assert r1.stats.mode == "delta"
    assert r1.stats.bytes_received == total
    assert r1.stats.leaves_received == len(leaves)
    assert r1.stats.leaves_skipped == 0
    # Every leaf reached the placement callback exactly once.
    assert sorted(placed) == list(range(len(leaves)))
    for got, want in zip(r1.leaves, leaves):
        np.testing.assert_array_equal(np.asarray(got).reshape(want.shape), want)
    # Adoption digests match a fresh hash of the assembled leaves.
    merged = make_ckpt(r1.leaves, step=7)
    merged.adopt_digests(r1.leaf_digests)
    assert merged.verify()


def test_identical_stores_move_nothing():
    leaves = source_leaves(2)
    a = make_ckpt([np.array(l) for l in leaves], step=5)
    b = make_ckpt([np.array(l) for l in leaves], step=5)
    template = template_of(leaves)
    r0, r1 = run_world(
        [
            lambda f: tx.stream_restore(f, template, a),
            lambda f: tx.stream_restore(f, template, b),
        ]
    )
    for r in (r0, r1):
        assert r.stats.mode == "local"
        assert r.stats.bytes_received == r.stats.bytes_sent == 0
        assert r.stats.bytes_scheduled == 0


def test_nobody_has_state_is_init():
    template = template_of(source_leaves())
    r0, r1 = run_world(
        [
            lambda f: tx.stream_restore(f, template, None),
            lambda f: tx.stream_restore(f, template, None),
        ]
    )
    assert r0.stats.mode == r1.stats.mode == "init"
    assert r0.leaves is None


def test_three_members_mixed_roles():
    """Source + identical holder + fresh joiner in one agreement: the
    holder touches no wire, the schedule totals only the joiner's
    bytes."""
    leaves = source_leaves(3)
    total = sum(l.nbytes for l in leaves)
    src = make_ckpt(leaves, step=9)
    twin = make_ckpt([np.array(l) for l in leaves], step=9)
    template = template_of(leaves)
    r0, r1, r2 = run_world(
        [
            lambda f: tx.stream_restore(f, template, src),
            lambda f: tx.stream_restore(f, template, twin),
            lambda f: tx.stream_restore(f, template, None),
        ]
    )
    assert r0.stats.mode == "delta"
    assert r0.stats.bytes_scheduled == total
    assert r0.stats.bytes_sent == total
    assert r1.stats.bytes_received == 0 and r1.stats.bytes_sent == 0
    assert r1.stats.leaves_skipped == len(leaves)
    assert r2.stats.bytes_received == total
    for got, want in zip(r2.leaves, leaves):
        np.testing.assert_array_equal(np.asarray(got).reshape(want.shape), want)


def test_chunked_transfer_splits_large_leaves():
    """A chunk size far below the leaf sizes must yield a multi-chunk
    stream that still reassembles bit-exactly."""
    leaves = source_leaves(4)
    src_ckpt = make_ckpt(leaves, step=3)
    template = template_of(leaves)
    total = sum(l.nbytes for l in leaves)
    min_chunks = sum(
        max(1, -(-l.nbytes // 1024)) for l in leaves
    )

    r0, r1 = run_world(
        [
            lambda f: tx.stream_restore(f, template, src_ckpt, chunk_bytes=1024),
            lambda f: tx.stream_restore(f, template, None, chunk_bytes=1024),
        ]
    )
    assert r1.stats.bytes_received == total
    assert r1.stats.chunks_received == min_chunks
    for got, want in zip(r1.leaves, leaves):
        np.testing.assert_array_equal(np.asarray(got).reshape(want.shape), want)


# ---- chaos: torn and slow chunks (reusing FaultSchedule) -------------------


def test_torn_chunk_fails_resize_on_every_member():
    """chaos[transfer.chunk.torn]: a flipped byte on the wire must
    surface as TornTransferError on EVERY member (the post-transfer
    confirmation all-gather makes the verdict world-consistent — one
    member quietly restoring an older step would diverge the world),
    and the poisoned leaf must NOT reach the placement callback."""
    leaves = source_leaves(5)
    src_ckpt = make_ckpt(leaves, step=4)
    template = template_of(leaves)
    chaos = FaultSchedule(
        seed=7, events=[FaultEvent(step=0, point="transfer.chunk.torn")]
    )
    chaos.advance(0)
    placed = []

    world = tx.LoopbackWorld(2)
    errs = [None, None]

    def run_src():
        try:
            tx.stream_restore(world.fabric(0), template, src_ckpt)
        except BaseException as e:  # noqa: BLE001 - asserted below
            errs[0] = e

    def run_joiner():
        try:
            tx.stream_restore(
                world.fabric(1),
                template,
                None,
                chaos=chaos,
                on_leaf=lambda i, a: placed.append(i),
            )
        except BaseException as e:  # noqa: BLE001 - asserted below
            errs[1] = e

    ts = [
        threading.Thread(target=run_src, daemon=True),
        threading.Thread(target=run_joiner, daemon=True),
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
        assert not t.is_alive()
    # BOTH members see the torn verdict: the resize attempt fails as
    # one unit and the caller retries with a fresh agreement.
    assert isinstance(errs[0], tx.TornTransferError), errs[0]
    assert isinstance(errs[1], tx.TornTransferError), errs[1]
    assert "member(s) [1]" in str(errs[0])
    # The torn event fired once and poisoned exactly one leaf: that
    # leaf never reached placement, the others did.
    assert len(placed) == len(leaves) - 1
    assert not chaos.pending()


def test_source_rot_after_hash_is_caught_by_advertised_digest():
    """Chunk CRCs are computed by the source at SEND time, so bytes
    that rotted between the agreement's hash pass and the send carry
    self-consistent chunk CRCs — the receiver must still catch them
    by checking each reassembled leaf against the source's ADVERTISED
    digest, before adoption (not at the next resize's re-hash)."""
    leaves = source_leaves(7)
    src_ckpt = make_ckpt(leaves, step=6)
    src_ckpt.leaf_digests()  # the agreement will advertise these...
    rotted = np.array(leaves[1], copy=True)
    rotted.reshape(-1).view(np.uint8)[7] ^= 0xFF
    src_ckpt.leaves[1] = rotted  # ...but the wire will carry these
    template = template_of(leaves)
    placed = []

    world = tx.LoopbackWorld(2)
    errs = [None, None]

    def member(rank, ckpt, on_leaf=None):
        def run():
            try:
                tx.stream_restore(
                    world.fabric(rank), template, ckpt, on_leaf=on_leaf
                )
            except BaseException as e:  # noqa: BLE001 - asserted below
                errs[rank] = e

        return run

    ts = [
        threading.Thread(target=member(0, src_ckpt), daemon=True),
        threading.Thread(
            target=member(1, None, lambda i, a: placed.append(i)),
            daemon=True,
        ),
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
        assert not t.is_alive()
    assert isinstance(errs[0], tx.TornTransferError), errs[0]
    assert isinstance(errs[1], tx.TornTransferError), errs[1]
    # The rotted leaf never reached placement.
    assert 1 not in placed and len(placed) == len(leaves) - 1


def test_slow_chunk_stalls_but_completes():
    """chaos[transfer.chunk.slow]: a stalled source link delays the
    stream without corrupting it."""
    leaves = source_leaves(6)
    src_ckpt = make_ckpt(leaves, step=2)
    template = template_of(leaves)
    chaos = FaultSchedule(
        seed=1,
        events=[FaultEvent(step=0, point="transfer.chunk.slow", arg=0.3)],
    )
    chaos.advance(0)

    t0 = time.perf_counter()
    r0, r1 = run_world(
        [
            lambda f: tx.stream_restore(f, template, src_ckpt, chaos=chaos),
            lambda f: tx.stream_restore(f, template, None),
        ]
    )
    elapsed = time.perf_counter() - t0
    assert elapsed >= 0.25, elapsed
    assert r1.stats.bytes_received == sum(l.nbytes for l in leaves)
    for got, want in zip(r1.leaves, leaves):
        np.testing.assert_array_equal(np.asarray(got).reshape(want.shape), want)
    assert not chaos.pending()


# ---- per-leaf digests & adoption ------------------------------------------


def test_leaf_digests_localize_divergence():
    leaves = source_leaves(8)
    a = make_ckpt([np.array(l) for l in leaves])
    b_leaves = [np.array(l) for l in leaves]
    b_leaves[1][3, 4] += 1.0
    b = make_ckpt(b_leaves)
    da, db = a.leaf_digests(), b.leaf_digests()
    assert [i for i in range(len(da)) if da[i] != db[i]] == [1]
    assert a.digest() != b.digest()


def test_digest_derives_from_leaf_digests_and_verify_detects_flips():
    from edl_tpu.chaos.storage import corrupt_checkpoint
    from edl_tpu.checkpoint.hostdram import _pack_leaf_digests

    ck = make_ckpt(source_leaves(9))
    assert ck.digest() == _pack_leaf_digests(ck.leaf_digests())
    assert ck.verify()
    corrupt_checkpoint(ck)
    assert not ck.verify()


def test_legacy_manifest_cold_load_survives_digest_algorithm_change():
    """Durable spills written by the pre-delta revision carry a
    CHAINED-crc digest and no digest_v: the cold-start load must
    verify them with the legacy formula, not classify a healthy
    volume as corrupt (the digest algorithm changed to per-leaf crc
    vectors in this revision)."""
    import json
    import glob
    import tempfile

    from edl_tpu.checkpoint.hostdram import _legacy_chained_crc

    with tempfile.TemporaryDirectory() as spill:
        store = HostDRAMStore(spill_dir=spill)
        state = {"w": np.arange(100, dtype=np.float32), "step": 3}
        store.save_async(state)
        store.wait()
        # Rewrite the manifest as the OLD revision would have.
        (mpath,) = glob.glob(f"{spill}/ckpt-*.json")
        with open(mpath) as f:
            manifest = json.load(f)
        manifest.pop("digest_v")
        manifest.pop("leaf_digests")
        manifest["digest"] = _legacy_chained_crc(
            store.latest().leaves
        )
        with open(mpath, "w") as f:
            json.dump(manifest, f)

        cold = HostDRAMStore(spill_dir=spill)
        ckpt = cold.load_from_disk(state)
        np.testing.assert_array_equal(ckpt.leaves[1], state["w"])
        # Fresh v2 digests were cached on the way in.
        assert ckpt.verify()

        # A legacy manifest whose bytes DON'T match its chained crc is
        # still corruption, not a free pass.
        manifest["digest"] ^= 0x1
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        colder = HostDRAMStore(spill_dir=spill)
        with pytest.raises(RuntimeError, match="failed CRC"):
            colder.load_from_disk(state)


# ---- stale save-error race (ADVICE r5, hostdram.wait) ----------------------


class _LeafThatDies:
    """A pytree leaf whose host materialization blocks, then fails —
    the shape of a save thread stuck in a dead world's collective."""

    def __init__(self, delay=0.3):
        self.delay = delay

    def __array__(self, dtype=None, copy=None):
        time.sleep(self.delay)
        raise RuntimeError("dead world's collective failed")


def test_abandoned_save_error_does_not_poison_next_wait():
    """The broken-world path waits a bounded time and leaks the stuck
    save thread; when that thread later dies, its error must NOT
    surface from the NEXT wait() and spuriously degrade an unrelated
    graceful resize to the replay path (ADVICE r5)."""
    store = HostDRAMStore()
    th = store.save_async({"w": _LeafThatDies(0.3), "step": 1})
    # Broken-world recovery: bounded wait expires -> thread abandoned.
    store.wait(timeout=0.05)
    th.join(timeout=10)
    assert not th.is_alive()
    assert store._save_errors  # the stale error DID land...
    store.wait()  # ...and the next healthy wait() discards it
    assert not store._save_errors


def test_unabandoned_save_error_still_raises():
    """The tagging must not swallow REAL errors: a save that fails
    while still tracked surfaces at the next wait()."""
    store = HostDRAMStore()
    store.save_async({"w": _LeafThatDies(0.0), "step": 2})
    with pytest.raises(RuntimeError, match="async checkpoint save failed"):
        store.wait()
