"""Serving-plane fault tolerance (ISSUE 15), unit layer: graceful
replica drain, the decode dispatch watchdog, coordinator-loss
behavior, and the lease-expiry ghost-telemetry fix.

The seeded multi-replica chaos soak lives in
``tests/test_serving_chaos.py``; the drain-before-patch kubectl golden
in ``tests/test_kubectl_transcript.py``.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu import telemetry
from edl_tpu.chaos.schedule import FaultEvent, FaultSchedule
from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.models.base import get_model
from edl_tpu.runtime.train import TrainState
from edl_tpu.serving import (
    ContinuousBatcher,
    DecodeEngine,
    DrainingError,
    InferenceEngine,
    ServingReplica,
    ServingServer,
    TokenContinuousBatcher,
)

_OPT = optax.adam(1e-3)


def _line_state(g: float) -> TrainState:
    params = {
        "w": jnp.full((13,), g, jnp.float32),
        "b": jnp.asarray(g, jnp.float32),
    }
    return TrainState(
        step=jnp.asarray(int(g), jnp.int32),
        params=params,
        opt_state=_OPT.init(params),
    )

def _line_engine(store, **kw) -> InferenceEngine:
    return InferenceEngine(
        get_model("fit_a_line"),
        store,
        devices=jax.devices()[:1],
        max_batch=4,
        **kw,
    )


def _line_store(g: float = 1.0) -> HostDRAMStore:
    store = HostDRAMStore()
    store.save_async(_line_state(g), generation=0)
    store.wait()
    return store


def _lm_state(model, step: int, seed: int) -> TrainState:
    p = model.init_params(jax.random.key(seed))
    return TrainState(
        step=jnp.asarray(step, jnp.int32),
        params=p,
        opt_state=_OPT.init(p),
    )


def _decode_engine(**kw):
    model = get_model("transformer_lm", tiny=True)
    store = HostDRAMStore()
    store.save_async(_lm_state(model, 1, 1), generation=0)
    store.wait()
    engine = DecodeEngine(
        model,
        store,
        devices=jax.devices()[:1],
        max_batch=1,
        max_seqs=4,
        block_tokens=16,
        **kw,
    )
    assert engine.load()
    engine.warm()
    return model, store, engine


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


# -- admission semantics -----------------------------------------------------


def test_close_admission_raises_typed_draining_error():
    with telemetry.scoped() as (reg, _):
        store = _line_store()
        engine = _line_engine(store)
        engine.load()
        engine.warm()
        batcher = ContinuousBatcher(engine).start()
        try:
            x = {"x": np.ones((1, 13), np.float32)}
            t = batcher.submit(x)  # admitted before the drain
            batcher.close_admission()
            assert batcher.draining
            with pytest.raises(DrainingError) as ei:
                batcher.submit(x)
            assert ei.value.retry_after > 0
            # DrainingError is NOT a QueueFullError: the HTTP front
            # maps them to 503 vs 429 (different client contract).
            from edl_tpu.serving import QueueFullError

            assert not isinstance(ei.value, QueueFullError)
            # the already-admitted request still completes
            out, _ = t.result(timeout=10)
            np.testing.assert_allclose(out["pred"], [14.0], atol=1e-5)
            assert (
                reg.counter("edl_serve_requests_total").value(
                    status="draining"
                )
                == 1
            )
        finally:
            batcher.stop()


def test_http_drain_contract_503_with_retry_after_vs_429():
    """While draining, /predict and /generate reply 503 + Retry-After —
    the "go to another replica" signal — NOT the 429 queue-full "back
    off and retry here" signal."""
    model, store, engine = _decode_engine()
    batcher = ContinuousBatcher(engine).start()
    gen_b = TokenContinuousBatcher(engine, refresh=False).start()
    server = ServingServer(
        batcher, host="127.0.0.1", gen_batcher=gen_b
    ).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        rng = np.random.RandomState(0)
        corpus = model.synth_batch(rng, 2)["tokens"]
        r = _post(f"{base}/drain", {"budget_ms": 5000})
        assert r["draining"] and r["drained"] and r["in_flight"] == 0
        for path, payload in (
            ("/predict", {"inputs": {"tokens": corpus[:1].tolist()}}),
            (
                "/generate",
                {
                    "inputs": {"tokens": corpus[0][:5].tolist()},
                    "max_new_tokens": 2,
                },
            ),
        ):
            try:
                _post(f"{base}{path}", payload)
                raise AssertionError(f"expected HTTP 503 on {path}")
            except urllib.error.HTTPError as e:
                assert e.code == 503, path
                assert float(e.headers["Retry-After"]) > 0, path
                body = json.loads(e.read())
                assert body["draining"] is True, path
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as h:
            health = json.loads(h.read())
        assert health["draining"] is True and health["in_flight"] == 0
    finally:
        server.stop()
        batcher.stop()
        gen_b.stop()


# -- replica drain lifecycle -------------------------------------------------


def test_replica_drain_finishes_in_flight_frees_kv_deregisters():
    """The full contract, in order: admission closed (typed error),
    every in-flight decode sequence runs to its normal finish, its KV
    blocks are freed, and ONLY then the replica deregisters."""
    from edl_tpu.runtime.coordinator import LocalCoordinator

    with telemetry.scoped() as (reg, rec):
        model, store, engine = _decode_engine()
        coord = LocalCoordinator(target_world=2, max_world=4)
        replica = ServingReplica(
            engine,
            coordinator=coord,
            replica_id="serve-0",
            heartbeat_interval=60.0,
            telemetry_interval=60.0,
        )
        replica.start()
        try:
            rng = np.random.RandomState(0)
            corpus = model.synth_batch(rng, 4)["tokens"]
            tickets = [
                replica.gen_batcher.submit_generate(
                    {"tokens": corpus[i][: 5 + i]},
                    max_new_tokens=24,
                    deadline_s=30.0,
                )
                for i in range(3)
            ]
            # wait until the batch is genuinely in flight
            deadline = time.monotonic() + 10
            while (
                replica.gen_batcher.active_count
                + replica.gen_batcher.prefilling_count
                + replica.gen_batcher.depth
                < 3
                and time.monotonic() < deadline
            ):
                time.sleep(0.002)
            assert coord.members() == ["serve-0"]
            r = replica.drain(budget_s=30.0)
            assert r["drained"] and r["in_flight"] == 0
            # every in-flight sequence COMPLETED (0 drops), full length
            for t in tickets:
                tokens, meta = t.result(timeout=1.0)
                assert len(tokens) == 24
            # KV blocks all freed the same iterations the sequences
            # finished — a drained replica holds no cache
            assert engine.pool.used_blocks == 0
            # deregistered only after the in-flight work settled
            assert coord.members() == []
            # second drain call joins the first (idempotent)
            assert replica.drain()["drained"]
            assert (
                reg.counter("edl_serve_drains_total").value() == 1
            )
            assert (
                reg.gauge("edl_serve_draining").value(replica="serve-0")
                == 2
            )
            phases = [
                e.data.get("phase")
                for e in rec.events()
                if e.kind == "serve.drain"
            ]
            assert phases == ["start", "done"]
        finally:
            replica.stop()


def test_budget_missed_drain_stays_registered_and_retries():
    """Review regression: a drain that MISSES its budget is
    ``incomplete``, not terminal — the replica keeps heartbeating and
    stays REGISTERED (it must remain a visible undrained victim so the
    scale-down patch stays blocked), and a RETRIED drain waits the
    remaining work out and acks for real (the result is never cached
    stale)."""
    from edl_tpu.runtime.coordinator import LocalCoordinator

    with telemetry.scoped() as (reg, rec):
        model, store, engine = _decode_engine()
        coord = LocalCoordinator(target_world=2, max_world=4)
        replica = ServingReplica(
            engine,
            coordinator=coord,
            replica_id="serve-slowgen",
            heartbeat_interval=0.05,
            telemetry_interval=1e9,
        )
        replica.start()
        try:
            rng = np.random.RandomState(0)
            prompt = model.synth_batch(rng, 1)["tokens"][0, :8]
            t = batch_t = replica.gen_batcher.submit_generate(
                {"tokens": prompt}, max_new_tokens=48, deadline_s=60.0
            )
            deadline = time.monotonic() + 10
            while not t.tokens and time.monotonic() < deadline:
                time.sleep(0.002)
            # a 48-token generation cannot finish in ~1ms: budget missed
            r1 = replica.drain(budget_s=0.001)
            assert not r1["drained"] and r1["in_flight"] >= 1
            # STILL a member (undrained victims must stay visible) and
            # still heartbeating
            assert "serve-slowgen" in coord.members()
            assert replica._thread.is_alive()
            # admission stayed closed across the incomplete attempt
            with pytest.raises(DrainingError):
                replica.gen_batcher.submit_generate(
                    {"tokens": prompt}, max_new_tokens=2
                )
            # the retry (next tick's post_drain) waits it out and acks
            r2 = replica.drain(budget_s=60.0)
            assert r2["drained"] and r2["in_flight"] == 0
            tokens, _ = batch_t.result(timeout=1.0)
            assert len(tokens) == 48  # the generation was never cut
            assert "serve-slowgen" not in coord.members()
            # one DRAIN (two attempts) in the counters/journal
            assert reg.counter("edl_serve_drains_total").value() == 1
            phases = [
                e.data.get("phase")
                for e in rec.events()
                if e.kind == "serve.drain"
            ]
            assert phases == ["start", "done"]
        finally:
            replica.stop()


def test_drain_victims_refused_is_dead_but_errors_fail_closed():
    """Review regression: only connection-REFUSED counts as a dead
    victim (acked — nothing live to yank); a broken drain handshake
    (plan fetch raising) fails CLOSED and blocks the actuation."""
    import socket

    from edl_tpu.autoscaler.serving import ServingLane

    with telemetry.scoped():
        # a genuinely closed port: connection refused -> acked
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_addr = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()  # nothing listens here now
        coord = _DrainCoord(2, ["r0", "r1"], ["", dead_addr])
        lane = ServingLane(
            coord,
            min_replicas=1,
            max_replicas=4,
            hold_ticks=1,
            victim_drain_timeout=2.0,
        )
        entry = lane.run_once()
        assert entry["actuated"]  # dead victim: scale-down proceeds
        assert entry["drain"]["victims"][0]["acked"]
        assert entry["drain"]["victims"][0]["unreachable"]

        # a handshake that RAISES blocks the tick (fail closed)
        coord2 = _DrainCoord(2, ["r0", "r1"], ["", ""])

        def boom():
            raise RuntimeError("plan fetch broke")

        coord2.plan = boom
        patches = []
        lane2 = ServingLane(
            coord2,
            min_replicas=1,
            max_replicas=4,
            hold_ticks=1,
            on_scale=lambda old, new: patches.append((old, new)),
        )
        e2 = lane2.run_once()
        assert not e2["actuated"] and patches == []
        assert "drain not acked" in e2["reason"]


def test_drain_budget_bounded_with_slow_chaos():
    """chaos[serve.drain.slow] stalls the drain loop; the budget still
    bounds the wait and the ack reports honestly."""
    with telemetry.scoped():
        chaos = FaultSchedule(
            seed=3,
            events=[FaultEvent(step=0, point="serve.drain.slow", arg=0.1)],
        )
        chaos.advance(0)
        store = _line_store()
        engine = _line_engine(store, chaos=chaos)
        engine.load()
        engine.warm()
        replica = ServingReplica(
            engine, replica_id="serve-slow", heartbeat_interval=60.0
        )
        replica.start()
        try:
            t0 = time.monotonic()
            r = replica.drain(budget_s=5.0)
            dt = time.monotonic() - t0
            assert r["drained"]  # nothing was in flight
            assert 0.1 <= dt < 5.0  # slow chaos consumed, budget held
            assert not chaos.pending()
        finally:
            replica.stop()


def test_replica_die_is_abrupt_clients_retry_against_survivor():
    """serve.replica.die (the SIGKILL shape): in-flight requests FAIL
    (no graceful resolution), the replica never deregisters, and the
    client contract is retry-against-survivors."""
    from edl_tpu.runtime.coordinator import LocalCoordinator

    with telemetry.scoped():
        store = _line_store()
        coord = LocalCoordinator(
            target_world=2, max_world=4, heartbeat_timeout=1e9
        )
        victim_engine = _line_engine(store)
        victim = ServingReplica(
            victim_engine,
            coordinator=coord,
            replica_id="victim",
            heartbeat_interval=60.0,
        )
        survivor_engine = _line_engine(store)
        survivor = ServingReplica(
            survivor_engine,
            coordinator=coord,
            replica_id="survivor",
            heartbeat_interval=60.0,
        )
        victim.start()
        survivor.start()
        try:
            x = {"x": np.ones((1, 13), np.float32)}
            # park requests on the victim, then kill it mid-flight
            tickets = [victim.batcher.submit(x) for _ in range(4)]
            victim.die()
            outcomes = []
            for t in tickets:
                try:
                    out, _ = t.result(timeout=10)
                except BaseException:
                    # the retry contract: resubmit against a survivor
                    out, _ = survivor.batcher.submit(x).result(timeout=10)
                outcomes.append(float(out["pred"][0]))
            assert outcomes == [14.0] * 4
            # a dead pod says no goodbyes: still registered until the
            # heartbeat lease expires
            assert set(coord.members()) == {"victim", "survivor"}
        finally:
            survivor.stop()


def test_chaos_driven_die_and_blackout_via_heartbeat_loop():
    """The per-pod schedule wiring: serve.replica.die kills the replica
    from its own heartbeat loop; serve.coord.unreachable mutes the
    control plane while serving continues, then reconverges."""
    from edl_tpu.runtime.coordinator import LocalCoordinator

    with telemetry.scoped():
        store = _line_store()
        chaos = FaultSchedule(
            seed=5,
            events=[
                FaultEvent(step=0, point="serve.coord.unreachable", arg=1.2)
            ],
        )
        engine = _line_engine(store)
        coord = LocalCoordinator(
            target_world=1, max_world=2, heartbeat_timeout=0.3
        )
        replica = ServingReplica(
            engine,
            coordinator=coord,
            replica_id="serve-b",
            heartbeat_interval=0.05,
            telemetry_interval=1e9,
            chaos=chaos,
        )
        replica.start()
        try:
            chaos.advance(0)
            deadline = time.monotonic() + 5
            while not chaos.fired() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert chaos.fired(), "blackout never delivered"
            # the coordinator hears nothing -> lease expires -> evicted
            deadline = time.monotonic() + 5
            while coord.members() and time.monotonic() < deadline:
                coord.evict_dead()
                time.sleep(0.05)
            assert coord.members() == []
            # ...but the replica keeps serving last-verified weights
            out, meta = replica.batcher.submit(
                {"x": np.ones((1, 13), np.float32)}
            ).result(timeout=10)
            np.testing.assert_allclose(out["pred"], [14.0], atol=1e-5)
            # blackout over: the KeyError->re-register rejoin path
            # reconverges membership
            deadline = time.monotonic() + 5
            while not coord.members() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert coord.members() == ["serve-b"]
        finally:
            replica.stop()


# -- decode dispatch watchdog ------------------------------------------------


def test_wedged_decode_dispatch_recovers_via_reprefill_zero_compiles():
    """The tentpole's watchdog half: a wedged decode dispatch (chaos
    trip) surfaces as the typed DispatchWedgedError into pool-rebuild +
    cache-epoch re-prefill — the request SURVIVES (no reject), its
    final tokens are pure under the one reported generation, and the
    whole recovery performs zero steady-state XLA compiles."""
    from tests.test_decode_serving import _reference_decode

    with telemetry.scoped() as (reg, rec):
        chaos = FaultSchedule(
            seed=11,
            events=[FaultEvent(step=0, point="serve.dispatch.wedged")],
        )
        model, store, engine = _decode_engine()
        engine.dispatch_chaos = chaos  # trip source for the watchdog
        batcher = TokenContinuousBatcher(engine).start()
        import jax._src.compiler as _compiler

        real = _compiler.backend_compile
        count = [0]

        def counting(*a, **k):
            count[0] += 1
            return real(*a, **k)

        try:
            rng = np.random.RandomState(0)
            prompt = model.synth_batch(rng, 1)["tokens"][0, :9]
            t = batcher.submit_generate(
                {"tokens": prompt}, max_new_tokens=12, deadline_s=30.0
            )
            # let it join the decode batch, then wedge mid-generation
            deadline = time.monotonic() + 10
            while not t.tokens and time.monotonic() < deadline:
                time.sleep(0.002)
            assert t.tokens, "sequence never started"
            epoch0 = engine.cache_epoch
            _compiler.backend_compile = counting
            chaos.advance(0)  # the next dispatch trips the watchdog
            tokens, meta = t.result(timeout=30)
        finally:
            _compiler.backend_compile = real
            batcher.stop()
        assert engine.cache_epoch == epoch0 + 1  # pools rebuilt once
        assert count[0] == 0, (
            f"{count[0]} XLA compiles during wedge recovery"
        )
        assert meta["restarts"] >= 1  # re-prefilled, not rejected
        assert reg.counter("edl_serve_dispatch_wedged_total").value() == 1
        trips = [e for e in rec.events() if e.kind == "serve.watchdog"]
        assert len(trips) == 1 and trips[0].data["what"] == "decode"
        # purity: the final tokens equal the reference greedy decode
        # under the single generation the sequence reports
        w = engine.current_weights()
        ref = _reference_decode(model, w.params, list(prompt), 12, engine)
        assert tokens == ref
        assert not chaos.pending()


def test_wedged_chunk_prefill_rewinds_half_prefilled_sequence():
    """A wedge mid-CHUNK leaves the sequence at the FIFO head; the
    epoch rewind resets its progress to zero and it still completes
    with exact first-token parity."""
    from tests.test_decode_serving import _reference_decode

    with telemetry.scoped():
        chaos = FaultSchedule(
            seed=13,
            events=[FaultEvent(step=0, point="serve.dispatch.wedged")],
        )
        model = get_model("longcontext_lm", tiny=True)
        store = HostDRAMStore()
        store.save_async(_lm_state(model, 1, 1), generation=0)
        store.wait()
        engine = DecodeEngine(
            model,
            store,
            devices=jax.devices()[:1],
            max_batch=1,
            max_seqs=2,
            block_tokens=16,
            max_chunk_tokens=32,
        )
        assert engine.load()
        engine.warm()
        engine.dispatch_chaos = chaos
        batcher = TokenContinuousBatcher(
            engine, prefill_token_budget=32
        ).start()
        try:
            rng = np.random.RandomState(0)
            plen = engine.max_context * 3 // 4  # needs several chunks
            prompt = model.synth_batch(rng, 1)["tokens"][0, :plen]
            chaos.advance(0)  # first chunk dispatch wedges
            t = batcher.submit_generate(
                {"tokens": prompt}, max_new_tokens=4, deadline_s=60.0
            )
            tokens, meta = t.result(timeout=60)
        finally:
            batcher.stop()
        assert engine.pool.used_blocks == 0
        w = engine.current_weights()
        ref = _reference_decode(model, w.params, list(prompt), 4, engine)
        assert tokens == ref
        assert not chaos.pending()


def test_dispatch_timeout_env_and_param_wire_the_watchdog():
    model = get_model("transformer_lm", tiny=True)
    store = HostDRAMStore()
    store.save_async(_lm_state(model, 1, 1), generation=0)
    store.wait()
    e = DecodeEngine(
        model,
        store,
        devices=jax.devices()[:1],
        max_batch=1,
        dispatch_timeout=7.5,
    )
    assert e.dispatch_timeout == 7.5 and e.watchdog.timeout == 7.5
    # default: disabled (0) — single-process CPU pays no thread hop
    e2 = DecodeEngine(
        model, store, devices=jax.devices()[:1], max_batch=1
    )
    assert e2.dispatch_timeout == 0.0


# -- torn-candidate rejection dedup (soak determinism) -----------------------


def test_swap_rejection_counts_once_per_torn_candidate():
    """A torn candidate sits in the store until a newer clean save; the
    engine must count/journal its rejection ONCE, not once per refresh
    poll (and must not re-hash it every poll either)."""
    with telemetry.scoped() as (reg, rec):
        chaos = FaultSchedule(
            seed=7, events=[FaultEvent(step=0, point="serve.swap.torn")]
        )
        chaos.advance(0)
        store = HostDRAMStore(chaos=chaos)
        store.save_async(_line_state(1.0), generation=0)
        store.wait()
        engine = _line_engine(store, chaos=chaos)
        assert engine.load()
        engine.warm()
        store.save_async(_line_state(5.0), generation=1)
        store.wait()
        for _ in range(4):  # four polls, one torn candidate
            assert not engine.refresh()
        assert reg.counter("edl_serve_swap_rejected_total").value() == 1
        kinds = [e.kind for e in rec.events()]
        assert kinds.count("serve.swap.rejected") == 1
        # a newer clean save still swaps in, and a LATER torn candidate
        # counts again (dedup is per candidate, not forever)
        store.save_async(_line_state(7.0), generation=2)
        store.wait()
        assert engine.refresh() and engine.weights_step == 7


# -- lease expiry: ghost telemetry -------------------------------------------


def test_evicted_replica_telemetry_drops_out_of_lane_observations():
    """Regression (ISSUE 15 satellite): a dead (never-drained) replica
    with a frozen high-latency histogram and a pinned queue-depth gauge
    must stop feeding ServingLane observations after lease eviction —
    a ghost p95 may not pin scaling decisions."""
    from edl_tpu.autoscaler.serving import ServingLane
    from edl_tpu.runtime.coordinator import LocalCoordinator

    with telemetry.scoped():
        clock = [0.0]
        coord = LocalCoordinator(
            target_world=2,
            max_world=4,
            heartbeat_timeout=5.0,
            clock=lambda: clock[0],
        )
        coord.register("ghost")
        coord.register("healthy")
        # the ghost's dying report: terrible p95, deep queue
        bad = telemetry.MetricsRegistry()
        h = bad.histogram("edl_serve_latency_seconds")
        for _ in range(50):
            h.observe(5.0)
        bad.gauge("edl_serve_queue_depth").set(40)
        coord.report_telemetry("ghost", snapshot=bad.snapshot(), seq=1)
        good = telemetry.MetricsRegistry()
        h2 = good.histogram("edl_serve_latency_seconds")
        for _ in range(50):
            h2.observe(0.004)
        good.gauge("edl_serve_queue_depth").set(0)
        coord.report_telemetry("healthy", snapshot=good.snapshot(), seq=1)

        lane = ServingLane(
            coord,
            min_replicas=1,
            max_replicas=4,
            p95_high_s=0.5,
            hold_ticks=1,
        )
        obs = lane.observe()
        assert obs["p95_latency_s"] > 0.5  # ghost still reporting: high
        assert obs["queue_depth"] == 40

        # the ghost dies (no drain); only "healthy" keeps beating
        clock[0] = 10.0
        coord.heartbeat("healthy")
        assert coord.evict_dead() == ["ghost"]
        merged = coord.telemetry()["merged"]
        depth = merged["gauges"].get("edl_serve_queue_depth") or {}
        assert max(depth.values()) == 0  # the pinned gauge is gone
        # fresh healthy traffic: the ghost's frozen histogram must not
        # haunt the p95 window
        for _ in range(50):
            h2.observe(0.004)
        coord.report_telemetry("healthy", snapshot=good.snapshot(), seq=2)
        obs2 = lane.observe()
        assert obs2["queue_depth"] == 0
        assert obs2["p95_latency_s"] is None or obs2["p95_latency_s"] < 0.5
        # and the band proposal no longer chases the ghost
        proposed, _ = lane.desired_replicas(obs2, 2)
        assert proposed <= 2


# -- lane drain-ack-then-patch ordering --------------------------------------


class _Plan:
    def __init__(self, members, addresses):
        self.members = tuple(members)
        self.addresses = tuple(addresses)


class _DrainCoord:
    """Lane double whose plan carries victim addresses."""

    def __init__(self, target, members, addresses):
        self.target = target
        self._members = members
        self._addresses = addresses
        self.calls = []

    def telemetry(self):
        return {
            "merged": {
                "counters": {},
                "gauges": {"edl_serve_queue_depth": {"": 0}},
                "histograms": {},
            }
        }

    def metrics(self):
        return {"target_world": self.target, "world_size": self.target}

    def plan(self):
        return _Plan(self._members, self._addresses)

    def set_prewarm(self, n, trace_id=""):
        self.calls.append(("prewarm", n))

    def set_target_world(self, n, trace_id=""):
        self.calls.append(("target", n))
        self.target = n


class _FakeDrainReplica:
    """One /drain HTTP endpoint recording its hit and replying an ack."""

    def __init__(self, drained=True):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.hits = []
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                outer.hits.append(
                    (self.path, json.loads(self.rfile.read(n) or b"{}"))
                )
                body = json.dumps(
                    {"draining": True, "drained": drained, "in_flight": 0}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(
            target=self._srv.serve_forever, daemon=True
        ).start()
        self.address = f"127.0.0.1:{self._srv.server_address[1]}"

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


def test_lane_drains_rank_tail_victims_before_retarget_and_patch():
    from edl_tpu.autoscaler.serving import ServingLane

    with telemetry.scoped():
        victim = _FakeDrainReplica(drained=True)
        try:
            coord = _DrainCoord(
                3,
                ["r0", "r1", "r2"],
                ["", "", victim.address],  # victim = rank-order tail
            )
            patches = []
            lane = ServingLane(
                coord,
                min_replicas=1,
                max_replicas=4,
                hold_ticks=1,
                on_scale=lambda old, new: patches.append((old, new)),
                victim_drain_timeout=5.0,
            )
            entry = lane.run_once()
            assert entry["actuated"]
            assert entry["dry_run"]["proposed"] == 2
            # the victim was drained (with the lane's budget) BEFORE
            # the retarget and the Deployment patch
            assert [p for p, _ in victim.hits] == ["/drain"]
            assert victim.hits[0][1]["budget_ms"] == 5000
            assert coord.calls == [("prewarm", 2), ("target", 2)]
            assert patches == [(3, 2)]
            assert entry["drain"]["acked"]
            assert [
                v["replica"] for v in entry["drain"]["victims"]
            ] == ["r2"]
        finally:
            victim.stop()


def test_lane_blocks_patch_when_victim_drain_not_acked():
    """A reachable victim that cannot finish inside the budget blocks
    the WHOLE actuation this tick — the Deployment patch can never
    yank an undrained replica; the lane retries next tick."""
    from edl_tpu.autoscaler.serving import ServingLane

    with telemetry.scoped():
        victim = _FakeDrainReplica(drained=False)
        try:
            coord = _DrainCoord(2, ["r0", "r1"], ["", victim.address])
            patches = []
            lane = ServingLane(
                coord,
                min_replicas=1,
                max_replicas=4,
                hold_ticks=1,
                on_scale=lambda old, new: patches.append((old, new)),
                victim_drain_timeout=2.0,
            )
            entry = lane.run_once()
            assert not entry["actuated"]
            assert "drain not acked" in entry["reason"]
            assert coord.calls == [] and patches == []
            assert coord.target == 2  # nothing moved
        finally:
            victim.stop()


def test_serving_bidder_drains_before_market_scale_down():
    from edl_tpu.autoscaler.serving import ServingLane
    from edl_tpu.fleet.bidders import ServingBidder

    with telemetry.scoped():
        victim = _FakeDrainReplica(drained=True)
        try:
            coord = _DrainCoord(2, ["r0", "r1"], ["", victim.address])
            lane = ServingLane(
                coord,
                min_replicas=1,
                max_replicas=4,
                victim_drain_timeout=5.0,
            )
            bidder = ServingBidder("fleet-a", lane)
            assert bidder.actuate(1, trace_id="t-1")
            assert [p for p, _ in victim.hits] == ["/drain"]
            # drain happened BEFORE the retarget (calls appended after)
            assert coord.calls == [("prewarm", 1), ("target", 1)]
            # scale-UP never drains
            victim.hits.clear()
            assert bidder.actuate(3, trace_id="t-2")
            assert victim.hits == []
        finally:
            victim.stop()


# -- manifests ---------------------------------------------------------------


def test_serving_manifests_carry_drain_grace_and_env():
    from edl_tpu.controller.jobparser import (
        SERVE_DRAIN_MS,
        SERVE_TERMINATION_GRACE_S,
        parse_to_serving_manifests,
    )
    from edl_tpu.resource.training_job import TrainingJob
    from tests.test_serving import SERVING_JOB_YAML

    job = TrainingJob.from_yaml(SERVING_JOB_YAML).validate()
    dep = parse_to_serving_manifests(job)[2]
    pod = dep["spec"]["template"]["spec"]
    assert (
        pod["terminationGracePeriodSeconds"] == SERVE_TERMINATION_GRACE_S
    )
    env = {
        e["name"]: e.get("value")
        for e in pod["containers"][0]["env"]
    }
    assert env["EDL_SERVE_DRAIN_MS"] == str(SERVE_DRAIN_MS)
    # the grace period must exceed the drain budget (SIGKILL never
    # beats a drain)
    assert SERVE_TERMINATION_GRACE_S * 1000 > SERVE_DRAIN_MS
