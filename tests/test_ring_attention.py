"""Ring attention correctness vs the single-device oracle, on an
8-device sp mesh (the long-context path, SURVEY.md §5.7 gap filled)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.ring_attention import reference_attention, ring_attention
from edl_tpu.parallel.mesh import MeshSpec, build_mesh


def qkv(rng, B=2, T=64, H=4, D=16, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(rng), 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.fixture(scope="module")
def sp_mesh():
    return build_mesh(MeshSpec.create(sp=8))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(sp_mesh, causal):
    q, k, v = qkv(0)
    want = reference_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, sp_mesh, axis="sp", causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_ring_on_dp_sp_mesh():
    """sp composes with dp: batch over dp, sequence over sp."""
    mesh = build_mesh(MeshSpec.create(dp=2, sp=4))
    q, k, v = qkv(1, B=4, T=32)
    want = reference_attention(q, k, v, causal=True)
    got = ring_attention(q, k, v, mesh, axis="sp", causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_ring_gradients_match(sp_mesh):
    q, k, v = qkv(2, T=32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, sp_mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_ring_trivial_axis():
    """axis of size 1 (or absent) degrades to plain attention."""
    mesh = build_mesh(MeshSpec.create(dp=8))
    q, k, v = qkv(3, T=16)
    want = reference_attention(q, k, v)
    got = ring_attention(q, k, v, mesh, axis="sp")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


# ---- end-to-end: decoder LM with sequence parallelism ----------------------


def test_transformer_lm_ring_matches_fused():
    """Same params, same batch: the sp_mesh (ring) model and the fused
    model must produce the same loss."""
    from edl_tpu.models import get_model

    mesh = build_mesh(MeshSpec.create(dp=2, sp=4))
    m_ring = get_model("transformer_lm", tiny=True, sp_mesh=mesh)
    m_fused = get_model("transformer_lm", tiny=True)
    params = m_fused.init_params(jax.random.key(0))
    batch = m_fused.synth_batch(np.random.RandomState(0), 4)
    l_fused, _ = m_fused.loss_fn(params, batch, jax.random.key(1))
    l_ring, _ = m_ring.loss_fn(params, batch, jax.random.key(1))
    np.testing.assert_allclose(float(l_ring), float(l_fused), rtol=2e-3)


def test_transformer_lm_trains_with_sequence_parallelism():
    import optax

    from edl_tpu.models import get_model
    from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
    from edl_tpu.runtime.train import Trainer

    mesh = build_mesh(MeshSpec.create(dp=2, sp=4))
    m = get_model("transformer_lm", tiny=True, sp_mesh=mesh)
    tr = Trainer(m, optax.adam(3e-3), mesh)
    state = tr.init_state()
    data = ShardedDataIterator(
        synthetic_dataset(m.synth_batch, 128), global_batch_size=16
    )
    first = last = None
    for step in range(12):
        batch = data.device_batch(step, mesh)
        state, metrics = tr.step(state, batch)
        if first is None:
            first = float(metrics["loss"])
        last = float(metrics["loss"])
    assert np.isfinite(last)
    assert last < first - 0.3, f"no learning under sp: {first} -> {last}"


# ---- zigzag layout (balanced causal rings) ----------------------------------


def test_zigzag_and_plain_layouts_both_match(sp_mesh):
    """Causal rings default to the zigzag layout (balanced per-rank
    work); both layouts must be exact vs the oracle."""
    q, k, v = qkv(3)
    want = reference_attention(q, k, v, causal=True)
    for zz in (True, False):
        got = ring_attention(
            q, k, v, sp_mesh, axis="sp", causal=True, zigzag=zz
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5,
            err_msg=f"zigzag={zz}",
        )


def test_plain_layout_gradients_match(sp_mesh):
    """zigzag=False keeps the plain path's gradients covered (the
    default causal tests now route through zigzag)."""
    q, k, v = qkv(4, T=32)

    def loss_plain(q, k, v):
        return jnp.sum(
            ring_attention(
                q, k, v, sp_mesh, causal=True, zigzag=False
            )
            ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_p = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_p, g_r):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5
        )


def test_zigzag_falls_back_on_odd_local_shard(sp_mesh):
    """T/n odd: the shard can't split into two stripes; auto-zigzag
    declines and the plain ring still matches the oracle."""
    q, k, v = qkv(5, T=24)  # t_local = 3 on sp=8
    want = reference_attention(q, k, v, causal=True)
    got = ring_attention(q, k, v, sp_mesh, axis="sp", causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_zigzag_gate_requires_exact_stripe_divisibility():
    """T=20 on sp=4: t_local=5 is odd AND 20 % 8 != 0 — but T=40 on
    sp=4 with t_local=10: 40 % 8 == 0 takes zigzag, while a T whose
    floor-division LOOKS even but doesn't split into 2n stripes (T=20,
    sp=8 -> t_local=2, 20 % 16 != 0) must fall back to the plain ring
    with FULL-LENGTH output, never a truncated one."""
    mesh = build_mesh(MeshSpec.create(sp=4))
    q, k, v = qkv(6, T=20)
    want = reference_attention(q, k, v, causal=True)
    got = ring_attention(q, k, v, mesh, axis="sp", causal=True)
    assert got.shape == q.shape
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )
