"""L0 resource-model tests.

Mirrors + extends the reference suite ``pkg/resource/training_job_test.go``
(NeedGPU flips on device limit ``:27-37``; Elastic iff min<max ``:39-46``)
and the validation semantics of ``pkg/jobparser.go:47-71``.
"""

import pytest

from edl_tpu.resource import (
    JobState,
    ResourceSpec,
    TrainerSpec,
    TrainingJob,
    TrainingJobSpec,
    ValidationError,
    TPU_RESOURCE_KEY,
)
from edl_tpu.resource.training_job import DEFAULT_IMAGE, DEFAULT_PORT, crd_manifest
from edl_tpu.utils.quantity import (
    add_resource_list,
    parse_cpu_milli,
    parse_memory_mega,
    parse_count,
)


def make_job(name="j1", min_instance=1, max_instance=1, fault_tolerant=False, **kw):
    return TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            fault_tolerant=fault_tolerant,
            trainer=TrainerSpec(
                min_instance=min_instance, max_instance=max_instance, **kw
            ),
        ),
    )


# ---- quantities (ref pkg/utils_test.go + autoscaler unit conversion) ------


def test_parse_cpu_milli():
    assert parse_cpu_milli("250m") == 250
    assert parse_cpu_milli("2") == 2000
    assert parse_cpu_milli("1.5") == 1500
    assert parse_cpu_milli(1) == 1000
    assert parse_cpu_milli("") == 0
    assert parse_cpu_milli(None) == 0


def test_parse_memory_mega():
    assert parse_memory_mega("1Gi") == 1024
    assert parse_memory_mega("64Mi") == 64
    assert parse_memory_mega("500M") == 500_000_000 // 2**20
    assert parse_memory_mega("2G") == 2_000_000_000 // 2**20
    assert parse_memory_mega(0) == 0


def test_parse_count():
    assert parse_count("4") == 4
    assert parse_count(8) == 8
    assert parse_count("") == 0
    with pytest.raises(ValueError):
        parse_count("4.5")
    with pytest.raises(ValueError):
        parse_count("1Gi")


def test_add_resource_list():
    # ref pkg/utils_test.go:25-48 — sums, inserts keys absent in a.
    a = {"cpu_milli": 1000, "memory_mega": 512}
    b = {"cpu_milli": 500, "tpu": 4}
    add_resource_list(a, b)
    assert a == {"cpu_milli": 1500, "memory_mega": 512, "tpu": 4}


# ---- helpers (ref training_job_test.go) -----------------------------------


def test_elastic_iff_min_lt_max():
    # ref :39-46
    assert not make_job(min_instance=2, max_instance=2).elastic()
    assert make_job(min_instance=1, max_instance=2, fault_tolerant=True).elastic()
    assert not make_job(min_instance=3, max_instance=1).elastic()


def test_need_tpu_flips_on_limit():
    # ref :27-37 (NeedGPU flips on the nvidia limit)
    j = make_job(slice_topology="cpu")
    assert not j.need_tpu()
    j.spec.trainer.resources = ResourceSpec(limits={TPU_RESOURCE_KEY: "4"})
    assert j.need_tpu()
    assert j.tpu_per_trainer() == 4


def test_tpu_per_trainer_falls_back_to_topology():
    j = make_job(slice_topology="v5e-8")
    assert j.tpu_per_trainer() == 8


# ---- validation (ref pkg/jobparser.go:47-71) ------------------------------


def test_validate_fills_defaults():
    j = make_job().validate()
    assert j.spec.port == DEFAULT_PORT
    assert j.spec.image == DEFAULT_IMAGE
    assert j.spec.passes == 1


def test_validate_rejects_elastic_without_fault_tolerant():
    # ref :66-68
    j = make_job(min_instance=1, max_instance=4, fault_tolerant=False)
    with pytest.raises(ValidationError):
        j.validate()
    make_job(min_instance=1, max_instance=4, fault_tolerant=True).validate()


def test_validate_rejects_bad_bounds():
    with pytest.raises(ValidationError):
        make_job(min_instance=0).validate()
    with pytest.raises(ValidationError):
        make_job(min_instance=3, max_instance=1).validate()
    with pytest.raises(ValidationError):
        TrainingJob(name="").validate()


def test_validate_rejects_unknown_topology():
    with pytest.raises(ValueError):
        make_job(slice_topology="v9-banana").validate()


def test_validate_global_batch_divisibility():
    # Single-chip slices: quantization is by world size alone.
    j = make_job(
        min_instance=1,
        max_instance=4,
        fault_tolerant=True,
        slice_topology="v5e-1",
    )
    j.spec.global_batch_size = 6  # not divisible by max_instance=4
    with pytest.raises(ValidationError):
        j.validate()
    j.spec.global_batch_size = 8
    j.validate()
    # world size 3 has a non-integral per-replica batch -> excluded from
    # the legal resize targets, not a crash at an intermediate generation.
    assert j.legal_world_sizes() == [1, 2, 4]
    j.spec.global_batch_size = 0
    assert j.legal_world_sizes() == [1, 2, 3, 4]


def test_validate_global_batch_quantizes_on_chips():
    # Multi-chip slices: the batch dim shards over EVERY chip of every
    # replica (world x chips devices), so divisibility is on w * chips,
    # not w (VERDICT r3 missing-1 follow-up).  v5e-4 -> 4 chips/replica.
    j = make_job(
        min_instance=1,
        max_instance=4,
        fault_tolerant=True,
        slice_topology="v5e-4",
    )
    j.spec.global_batch_size = 8  # 8 % (4 pods x 4 chips) != 0
    with pytest.raises(ValidationError):
        j.validate()
    j.spec.global_batch_size = 32
    j.validate()
    # 32 rows / (w * 4 chips): w=1 -> 8, w=2 -> 4, w=4 -> 2; w=3 -> 8/3.
    assert j.legal_world_sizes() == [1, 2, 4]


def test_validate_rejects_negative_resources():
    j = make_job()
    j.spec.trainer.resources = ResourceSpec(limits={TPU_RESOURCE_KEY: "-4"})
    with pytest.raises(ValidationError):
        j.validate()
    j.spec.trainer.resources = ResourceSpec(requests={"cpu": "-500m"})
    with pytest.raises(ValidationError):
        j.validate()


def test_validate_wraps_all_malformed_input_as_validation_error():
    # Non-scalar quantity (easy YAML typo) must not escape as TypeError.
    j = make_job()
    j.spec.trainer.resources = ResourceSpec(requests={"cpu": {"oops": 1}})
    with pytest.raises(ValidationError):
        j.validate()
    # Malformed manifest fields (null port, bogus status state).
    with pytest.raises(ValidationError):
        TrainingJob.from_manifest(
            {"metadata": {"name": "x"}, "spec": {"port": None}}
        )
    with pytest.raises(ValidationError):
        TrainingJob.from_manifest(
            {"metadata": {"name": "x"}, "status": {"state": "Bogus"}}
        )


def test_validate_rejects_tpu_limit_topology_contradiction():
    j = make_job(slice_topology="v5e-4")
    j.spec.trainer.resources = ResourceSpec(limits={TPU_RESOURCE_KEY: "8"})
    with pytest.raises(ValidationError):
        j.validate()
    j.spec.trainer.resources = ResourceSpec(limits={TPU_RESOURCE_KEY: "4"})
    j.validate()


def test_validate_unknown_topology_is_validation_error():
    # validate() must raise ValidationError (not bare ValueError) for every
    # invalid-spec path so submit paths can catch one exception type.
    with pytest.raises(ValidationError):
        make_job(slice_topology="v5e-12").validate()


# ---- (de)serialization ----------------------------------------------------


def test_manifest_roundtrip():
    j = make_job(
        name="mnist", min_instance=1, max_instance=4, fault_tolerant=True,
        slice_topology="v5e-4",
    )
    j.spec.global_batch_size = 128
    j.validate()
    j.status.state = JobState.RUNNING
    j.status.parallelism = 2
    m = j.to_manifest()
    assert m["apiVersion"] == "edl.tpu.dev/v1"
    assert m["kind"] == "TrainingJob"
    j2 = TrainingJob.from_manifest(m)
    assert j2.name == "mnist"
    assert j2.spec.trainer.max_instance == 4
    assert j2.status.state == JobState.RUNNING
    assert j2.status.parallelism == 2
    assert j2.elastic()


def test_from_yaml():
    text = """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata:
  name: resnet50
  namespace: ml
spec:
  fault_tolerant: true
  global_batch_size: 4096
  trainer:
    entrypoint: "python -m edl_tpu.models.resnet"
    min_instance: 1
    max_instance: 16
    slice_topology: v5e-4
    resources:
      requests: {cpu: "4", memory: 8Gi}
      limits: {"google.com/tpu": "4"}
"""
    j = TrainingJob.from_yaml(text).validate()
    assert j.fullname() == "ml/resnet50"
    assert j.trainer_job_name() == "resnet50-trainer"
    assert j.tpu_per_trainer() == 4
    assert j.spec.trainer.resources.cpu_request_milli() == 4000
    assert j.spec.trainer.resources.mem_request_mega() == 8192


def test_deepcopy_is_independent():
    j = make_job()
    j2 = j.deepcopy()
    j2.spec.trainer.min_instance = 99
    assert j.spec.trainer.min_instance == 1


def test_crd_manifest_shape():
    m = crd_manifest()
    assert m["metadata"]["name"] == "trainingjobs.edl.tpu.dev"
    assert m["spec"]["versions"][0]["subresources"] == {"status": {}}


def test_topologies():
    from edl_tpu.cluster.tpu_topology import (
        topology_chips,
        legal_topologies,
        largest_topology_fitting,
    )

    assert topology_chips("v5e-4") == 4
    assert topology_chips("v5e-64") == 64
    assert topology_chips("cpu") == 0
    assert "v5e-8" in legal_topologies()
    assert largest_topology_fitting(40).chips == 32
    assert largest_topology_fitting(3).chips == 1
