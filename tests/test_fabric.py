"""Sharded peer-to-peer checkpoint fabric (checkpoint/fabric.py).

Same discipline as tests/test_restore_transfer.py: the tiny agreement
rides the barrier-based ``LoopbackWorld`` while the TCP data plane is
REAL (loopback sockets, per-chunk CRCs) — so the per-peer wire
accounting these tests assert is the production transport's.

The headline property: a joiner's restore is fed by MANY peers in
parallel with NO single peer sending the full state, and a peer that
dies or serves torn bytes mid-pull costs only a per-shard fallback,
never the restore.
"""

import threading
import zlib

import numpy as np

import jax

from edl_tpu.chaos import FaultEvent, FaultSchedule
from edl_tpu.checkpoint import transfer as tx
from edl_tpu.checkpoint import fabric as fab
from edl_tpu.checkpoint.hostdram import HostCheckpoint, HostDRAMStore


def make_ckpt(leaves, step=10):
    _, treedef = jax.tree_util.tree_flatten(list(leaves))
    return HostCheckpoint(
        step=step, generation=1, leaves=list(leaves), treedef=treedef
    )


def template_of(leaves):
    return [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]


def source_leaves(seed=0):
    rng = np.random.RandomState(seed)
    return [
        rng.randn(64, 32).astype(np.float32),   # 8KB
        rng.randn(257, 16).astype(np.float32),  # odd row count
        np.asarray(rng.randint(0, 100), np.int32).reshape(()),  # 0-d step
        rng.randn(4000).astype(np.float64),     # 32KB
    ]


def run_world(member_fns, timeout=60):
    world = tx.LoopbackWorld(len(member_fns))
    results = [None] * len(member_fns)
    errors = [None] * len(member_fns)

    def runner(rank, fn):
        try:
            results[rank] = fn(world.fabric(rank))
        except BaseException as e:  # noqa: BLE001 - re-raised below
            errors[rank] = e

    threads = [
        threading.Thread(target=runner, args=(r, fn), daemon=True)
        for r, fn in enumerate(member_fns)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
        assert not t.is_alive(), "member thread hung"
    for e in errors:
        if e is not None:
            raise e
    return results


# ---- the shard layout ------------------------------------------------------


def test_layout_boundaries_are_world_independent():
    sizes = [100 << 10, 3 << 10, 4]
    rows = [256, 0, 0]
    a = fab.ShardLayout.build(sizes, 2, shard_bytes=16 << 10, rows=rows)
    b = fab.ShardLayout.build(sizes, 7, shard_bytes=16 << 10, rows=rows)
    assert [
        (s.leaf, s.offset, s.length) for s in a.shards
    ] == [(s.leaf, s.offset, s.length) for s in b.shards]
    assert a.key() == b.key()
    # Coverage is exact and non-overlapping per leaf.
    for layout in (a, b):
        for i, nbytes in enumerate(sizes):
            shs = sorted(layout.by_leaf[i], key=lambda s: s.offset)
            assert shs[0].offset == 0
            assert sum(s.length for s in shs) == nbytes
            for prev, nxt in zip(shs, shs[1:]):
                assert prev.offset + prev.length == nxt.offset


def test_layout_row_aligned_ownership_matches_gspmd_chunks():
    """Row-aligned shards are owned by the member whose ceil-chunked
    axis-0 GSPMD slice contains them — 'each member already holds
    exactly its shards'."""
    rows = 256
    row_b = 1 << 10
    layout = fab.ShardLayout.build(
        [rows * row_b], 4, shard_bytes=16 << 10, rows=[rows]
    )
    chunk = -(-rows // 4)  # 64 rows per member
    for s in layout.shards:
        assert layout.owner(s) == min(s.start_row // chunk, 3)
    owners = {layout.owner(s) for s in layout.shards}
    assert owners == {0, 1, 2, 3}  # every member owns a stripe


def test_layout_replica_map_is_ring_deterministic():
    layout = fab.ShardLayout.build(
        [64 << 10], 4, k=2, shard_bytes=8 << 10, rows=[64]
    )
    for s in layout.shards:
        owner = layout.owner(s)
        assert layout.holders(s) == (
            owner,
            (owner + 1) % 4,
            (owner + 2) % 4,
        )
    # Every member computes the identical map from the membership.
    assert layout.replica_map() == fab.ShardLayout.build(
        [64 << 10], 4, k=2, shard_bytes=8 << 10, rows=[64]
    ).replica_map()


def test_shard_digests_refine_leaf_digests():
    leaves = source_leaves(1)
    ck = make_ckpt(leaves)
    layout = fab.ShardLayout.build(
        [l.nbytes for l in leaves], 3, shard_bytes=1024,
        rows=[l.shape[0] if l.ndim else 0 for l in leaves],
    )
    shard_crcs, leaf_crcs = fab.compute_shard_digests(leaves, layout)
    # The chained-shard leaf crc IS PR 2's leaf digest, bit for bit.
    assert leaf_crcs == ck.leaf_digests()
    # One flipped byte dirties exactly one shard (and its leaf).
    dirty = [np.array(l, copy=True) for l in leaves]
    dirty[3].reshape(-1).view(np.uint8)[7] ^= 0xFF
    shard2, leaf2 = fab.compute_shard_digests(dirty, layout)
    diff = [i for i in range(len(shard_crcs)) if shard_crcs[i] != shard2[i]]
    assert len(diff) == 1 and layout.shards[diff[0]].leaf == 3
    assert [i for i in range(4) if leaf_crcs[i] != leaf2[i]] == [3]


def test_hostcheckpoint_shard_digest_cache_and_spill_manifest(tmp_path):
    leaves = source_leaves(2)
    layout = fab.ShardLayout.build(
        [l.nbytes for l in leaves], 2, shard_bytes=1024,
        rows=[l.shape[0] if l.ndim else 0 for l in leaves],
    )
    ck = make_ckpt(leaves)
    digs = ck.shard_digests(layout)
    assert ck.shard_digests(layout) is digs  # cached by boundary key
    # The single pass filled the per-leaf vector too.
    assert ck._leaf_digests is not None

    # Spill manifests carry the per-shard vector; a cold load re-seeds
    # the cache without a hash pass.
    import glob
    import json

    store = HostDRAMStore(spill_dir=str(tmp_path))
    state = {"w": np.arange(4096, dtype=np.float32), "step": 3}
    store.save_async(state)
    store.wait()
    (mpath,) = glob.glob(f"{tmp_path}/ckpt-*.json")
    with open(mpath) as f:
        manifest = json.load(f)
    assert "shard_digests" in manifest and "shard_bytes" in manifest
    cold = HostDRAMStore(spill_dir=str(tmp_path))
    loaded = cold.load_from_disk(state)
    assert loaded._shard_digests is not None
    assert loaded._shard_digests[1] == manifest["shard_digests"]


# ---- the parallel pull -----------------------------------------------------


def test_joiner_pulls_from_many_peers_no_single_full_sender():
    """THE acceptance property (ROADMAP item 3): a fresh joiner's
    restore is fed by >= 2 peers in parallel and NO single peer sends
    the full state — wire bytes accounted per peer."""
    leaves = source_leaves(3)
    total = sum(l.nbytes for l in leaves)
    src = make_ckpt(leaves, step=9)
    twin = make_ckpt([np.array(l) for l in leaves], step=9)
    template = template_of(leaves)
    placed = []

    r0, r1, r2 = run_world(
        [
            lambda f: fab.fabric_restore(
                f, template, src, shard_bytes=1024
            ),
            lambda f: fab.fabric_restore(
                f, template, twin, shard_bytes=1024
            ),
            lambda f: fab.fabric_restore(
                f,
                template,
                None,
                shard_bytes=1024,
                on_leaf=lambda i, a: placed.append(i),
            ),
        ]
    )
    assert r2.stats.mode == "fabric"
    assert r2.stats.bytes_received == total
    per_peer = r2.stats.per_peer
    assert len(per_peer) >= 2, per_peer
    assert sum(per_peer.values()) == total
    assert max(per_peer.values()) < total, (
        f"one peer sent the full state: {per_peer}"
    )
    # Every leaf reached placement exactly once; bytes are bit-exact.
    assert sorted(placed) == list(range(len(leaves)))
    for got, want in zip(r2.leaves, leaves):
        np.testing.assert_array_equal(
            np.asarray(got).reshape(want.shape), want
        )
    # Zero-copy adoption discipline: the authority's leaf digests
    # verify against the assembled bytes.
    merged = make_ckpt(r2.leaves, step=9)
    merged.adopt_digests(r2.leaf_digests)
    assert merged.verify()
    # The sources each served only part of the state.
    for r in (r0, r1):
        assert 0 < r.stats.bytes_sent < total


def test_two_member_world_falls_back_to_single_source_stream():
    """One holder = no multi-peer coverage: every member hands the
    restore to PR 2's stream (mode 'delta'), so 2-member worlds keep
    the exact leaf-level delta behavior."""
    leaves = source_leaves(4)
    src = make_ckpt(leaves, step=5)
    template = template_of(leaves)
    r0, r1 = run_world(
        [
            lambda f: fab.fabric_restore(f, template, src, shard_bytes=1024),
            lambda f: fab.fabric_restore(f, template, None, shard_bytes=1024),
        ]
    )
    assert r0.stats.mode == "delta" and r1.stats.mode == "delta"
    # The fabric agreement's endpoint addresses ride the hand-off
    # result, so small worlds still replicate/inherit afterwards.
    assert r0.peer_addrs is not None and 0 in r0.peer_addrs
    assert r1.stats.bytes_received == sum(l.nbytes for l in leaves)
    for got, want in zip(r1.leaves, leaves):
        np.testing.assert_array_equal(
            np.asarray(got).reshape(want.shape), want
        )


def test_identical_stores_move_nothing_and_nobody_is_init():
    leaves = source_leaves(5)
    template = template_of(leaves)
    a = make_ckpt([np.array(l) for l in leaves], step=4)
    b = make_ckpt([np.array(l) for l in leaves], step=4)
    c = make_ckpt([np.array(l) for l in leaves], step=4)
    rs = run_world(
        [
            lambda f, ck=ck: fab.fabric_restore(
                f, template, ck, shard_bytes=1024
            )
            for ck in (a, b, c)
        ]
    )
    for r in rs:
        assert r.stats.mode == "local"
        assert r.stats.bytes_received == r.stats.bytes_sent == 0

    rs = run_world(
        [
            lambda f: fab.fabric_restore(f, template, None, shard_bytes=1024)
            for _ in range(3)
        ]
    )
    assert all(r.stats.mode == "init" for r in rs)


def test_partial_divergence_moves_only_diverged_shards():
    """A member diverged in ONE shard of one leaf receives exactly
    that shard's bytes — the delta discipline at shard granularity."""
    leaves = source_leaves(6)
    template = template_of(leaves)
    src = make_ckpt(leaves, step=7)
    twin = make_ckpt([np.array(l) for l in leaves], step=7)
    stale_leaves = [np.array(l) for l in leaves]
    # Flip one byte inside the big last leaf (32KB / 1KB shards).
    stale_leaves[3].reshape(-1).view(np.uint8)[5] ^= 0xFF
    stale = make_ckpt(stale_leaves, step=7)

    r0, r1, r2 = run_world(
        [
            lambda f: fab.fabric_restore(f, template, src, shard_bytes=1024),
            lambda f: fab.fabric_restore(f, template, twin, shard_bytes=1024),
            lambda f: fab.fabric_restore(f, template, stale, shard_bytes=1024),
        ]
    )
    assert r2.stats.mode == "fabric"
    assert r2.stats.bytes_received == 1024  # exactly one shard
    for got, want in zip(r2.leaves, leaves):
        np.testing.assert_array_equal(
            np.asarray(got).reshape(want.shape), want
        )


def test_replica_holder_serves_without_a_checkpoint():
    """A member holding only buddy-replica shards (no checkpoint)
    advertises and serves them — the coverage that makes inheritance
    visible to the next agreement."""
    leaves = source_leaves(7)
    template = template_of(leaves)
    src = make_ckpt(leaves, step=6)
    sizes = [l.nbytes for l in leaves]
    rows = [l.shape[0] if l.ndim else 0 for l in leaves]
    layout = fab.ShardLayout.build(sizes, 3, shard_bytes=1024, rows=rows)
    # The replica holder carries the big leaf's shards at step 6.
    rep = fab.ShardReplicaStore()
    for s in layout.by_leaf[3]:
        view = memoryview(leaves[3]).cast("B")[
            s.offset : s.offset + s.length
        ]
        data = np.frombuffer(bytes(view), np.uint8)
        assert rep.put(
            6, s.leaf, s.offset, s.length, data, zlib.crc32(view)
        )

    r0, r1, r2 = run_world(
        [
            lambda f: fab.fabric_restore(f, template, src, shard_bytes=1024),
            lambda f: fab.fabric_restore(
                f, template, None, shard_bytes=1024, replica_store=rep
            ),
            lambda f: fab.fabric_restore(f, template, None, shard_bytes=1024),
        ]
    )
    assert r2.stats.mode == "fabric"
    # The joiner pulled from BOTH the source and the replica holder.
    assert len(r2.stats.per_peer) == 2, r2.stats.per_peer
    assert r2.stats.per_peer.get("1", 0) > 0
    for got, want in zip(r2.leaves, leaves):
        np.testing.assert_array_equal(
            np.asarray(got).reshape(want.shape), want
        )
    # The replica holder itself assembled a full verified state too.
    for got, want in zip(r1.leaves, leaves):
        np.testing.assert_array_equal(
            np.asarray(got).reshape(want.shape), want
        )


# ---- chaos: torn replicas, lost peers, slow peers --------------------------


def test_torn_replica_falls_back_to_another_holder():
    """chaos[fabric.replica.torn]: a serving peer's bytes rotted after
    its crc was advertised — the receiver's reference-digest check
    must reject the shard and re-pull it from another holder, and the
    restore must still succeed."""
    leaves = source_leaves(8)
    total = sum(l.nbytes for l in leaves)
    template = template_of(leaves)
    src = make_ckpt(leaves, step=3)
    twin = make_ckpt([np.array(l) for l in leaves], step=3)
    chaos = FaultSchedule(
        seed=5, events=[FaultEvent(step=0, point="fabric.replica.torn")]
    )
    chaos.advance(0)

    def src_member(f):
        # The chaos schedule rides ONE member's server: exactly one
        # served shard is torn.
        return fab.fabric_restore(
            f, template, src, shard_bytes=1024, chaos=chaos
        )

    r0, r1, r2 = run_world(
        [
            src_member,
            lambda f: fab.fabric_restore(f, template, twin, shard_bytes=1024),
            lambda f: fab.fabric_restore(f, template, None, shard_bytes=1024),
        ]
    )
    assert r2.stats.mode == "fabric"
    assert r2.stats.shard_fallbacks >= 1
    # The torn shard was re-received: one extra shard of wire bytes.
    assert r2.stats.bytes_received > total
    for got, want in zip(r2.leaves, leaves):
        np.testing.assert_array_equal(
            np.asarray(got).reshape(want.shape), want
        )
    assert not chaos.pending()


def test_peer_lost_mid_pull_falls_back_per_shard():
    """chaos[fabric.peer.lost]: a source dies mid-pull — its
    unfinished shards fall back to another replica holder instead of
    failing the restore."""
    leaves = source_leaves(9)
    template = template_of(leaves)
    src = make_ckpt(leaves, step=8)
    twin = make_ckpt([np.array(l) for l in leaves], step=8)
    chaos = FaultSchedule(
        seed=6, events=[FaultEvent(step=0, point="fabric.peer.lost")]
    )
    chaos.advance(0)
    placed = []

    r0, r1, r2 = run_world(
        [
            lambda f: fab.fabric_restore(f, template, src, shard_bytes=1024),
            lambda f: fab.fabric_restore(f, template, twin, shard_bytes=1024),
            lambda f: fab.fabric_restore(
                f,
                template,
                None,
                shard_bytes=1024,
                chaos=chaos,
                on_leaf=lambda i, a: placed.append(i),
            ),
        ]
    )
    assert r2.stats.mode == "fabric"
    assert r2.stats.shard_fallbacks >= 1
    assert sorted(placed) == list(range(len(leaves)))
    for got, want in zip(r2.leaves, leaves):
        np.testing.assert_array_equal(
            np.asarray(got).reshape(want.shape), want
        )
    assert not chaos.pending()


def test_all_holders_torn_fails_resize_on_every_member():
    """When EVERY holder of a shard serves torn bytes the pull is
    unrecoverable: the confirmation all-gather must fail the resize on
    every member together (nobody adopts), exactly PR 2's
    world-consistent verdict."""
    leaves = source_leaves(10)
    template = template_of(leaves)
    src = make_ckpt(leaves, step=2)
    twin = make_ckpt([np.array(l) for l in leaves], step=2)
    # Both holders serve one torn shard each (their own schedules).
    chaos_a = FaultSchedule(
        seed=7,
        events=[FaultEvent(step=0, point="fabric.replica.torn", arg=None)]
        * 60,
    )
    chaos_b = FaultSchedule(
        seed=8,
        events=[FaultEvent(step=0, point="fabric.replica.torn", arg=None)]
        * 60,
    )
    chaos_a.advance(0)
    chaos_b.advance(0)

    world = tx.LoopbackWorld(3)
    errs = [None, None, None]

    def member(rank, ck, chaos=None):
        def run():
            try:
                fab.fabric_restore(
                    world.fabric(rank),
                    template,
                    ck,
                    shard_bytes=1024,
                    chaos=chaos,
                )
            except BaseException as e:  # noqa: BLE001 - asserted below
                errs[rank] = e

        return run

    ts = [
        threading.Thread(target=member(0, src, chaos_a), daemon=True),
        threading.Thread(target=member(1, twin, chaos_b), daemon=True),
        threading.Thread(target=member(2, None), daemon=True),
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
        assert not t.is_alive()
    assert all(
        isinstance(e, tx.TornTransferError) for e in errs
    ), errs


def test_slow_peer_stalls_but_completes():
    """chaos[fabric.pull.slow]: a stalled serving peer delays its
    stream without corrupting the restore."""
    import time

    leaves = source_leaves(11)
    template = template_of(leaves)
    src = make_ckpt(leaves, step=1)
    twin = make_ckpt([np.array(l) for l in leaves], step=1)
    chaos = FaultSchedule(
        seed=9,
        events=[FaultEvent(step=0, point="fabric.pull.slow", arg=0.3)],
    )
    chaos.advance(0)

    t0 = time.perf_counter()
    r0, r1, r2 = run_world(
        [
            lambda f: fab.fabric_restore(
                f, template, src, shard_bytes=1024, chaos=chaos
            ),
            lambda f: fab.fabric_restore(f, template, twin, shard_bytes=1024),
            lambda f: fab.fabric_restore(f, template, None, shard_bytes=1024),
        ]
    )
    assert time.perf_counter() - t0 >= 0.25
    for got, want in zip(r2.leaves, leaves):
        np.testing.assert_array_equal(
            np.asarray(got).reshape(want.shape), want
        )
    assert not chaos.pending()


# ---- replication: offer/accept to the deterministic buddies ----------------


def _serve_member(ckpt, replicas, step):
    """A started FabricServer over (ckpt, replica store)."""

    def lookup(st, leaf, off, length):
        if (
            ckpt is not None
            and st == step
            and leaf < len(ckpt.leaves)
            and ckpt.leaves[leaf].nbytes >= off + length
        ):
            return memoryview(
                np.ascontiguousarray(ckpt.leaves[leaf])
            ).cast("B")[off : off + length]
        return replicas.get(st, leaf, off, length)

    def has_bytes(st, leaf, off, length):
        return (
            ckpt is not None
            and st == step
            and leaf < len(ckpt.leaves)
            and ckpt.leaves[leaf].nbytes >= off + length
        )

    return fab.FabricServer(
        lookup, ingest=fab.ReplicaIngest(replicas, has_bytes)
    ).start()


def test_replication_offer_accept_dedup():
    """Buddies missing the step accept the payload; buddies already
    holding the flushed checkpoint decline BEFORE any payload moves —
    the byte-free common case of collective flushes."""
    from edl_tpu import telemetry

    leaves = source_leaves(12)
    sizes = [l.nbytes for l in leaves]
    rows = [l.shape[0] if l.ndim else 0 for l in leaves]
    layout = fab.ShardLayout.build(sizes, 3, k=1, shard_bytes=1024, rows=rows)
    ck = make_ckpt(leaves, step=20)
    digs = ck.shard_digests(layout)

    with telemetry.scoped():
        # Buddy 1 already holds the flushed checkpoint; buddy 2 is
        # cold (a fresh joiner / degraded-flush survivor).
        warm_rep = fab.ShardReplicaStore()
        cold_rep = fab.ShardReplicaStore()
        warm = _serve_member(make_ckpt(leaves, step=20), warm_rep, 20)
        cold = _serve_member(None, cold_rep, 20)
        try:
            peer_addrs = {
                1: ("127.0.0.1", warm.port),
                2: ("127.0.0.1", cold.port),
            }

            def shard_source(s):
                view = memoryview(
                    np.ascontiguousarray(ck.leaves[s.leaf])
                ).cast("B")
                return (
                    view[s.offset : s.offset + s.length],
                    digs[s.index],
                )

            # Rank 0 offers its owned shards; its k=1 buddies are the
            # ring successors of each shard's owner.
            summary = fab.replicate_to_buddies(
                layout, 0, 20, 1, peer_addrs, shard_source
            )
        finally:
            warm.stop()
            cold.stop()
    owned = layout.owned_by(0)
    to_warm = [s for s in owned if layout.holders(s)[1:] == (1,)]
    to_cold = [s for s in owned if layout.holders(s)[1:] == (2,)]
    assert summary["offered"] == len(to_warm) + len(to_cold)
    # The warm buddy declined everything (zero payload), the cold one
    # accepted its offers.
    assert summary["accepted"] == len(to_cold)
    assert summary["bytes"] == sum(s.length for s in to_cold)
    assert warm_rep.nbytes() == 0
    for s in to_cold:
        assert cold_rep.get(20, s.leaf, s.offset, s.length) is not None


def test_replication_lost_push_is_dropped_not_fatal():
    """chaos[fabric.replica.lost]: a dropped push is journaled as
    dropped and the flush is unaffected (best-effort replication)."""
    leaves = source_leaves(13)
    sizes = [l.nbytes for l in leaves]
    layout = fab.ShardLayout.build(
        sizes, 2, k=1, shard_bytes=1024,
        rows=[l.shape[0] if l.ndim else 0 for l in leaves],
    )
    ck = make_ckpt(leaves, step=30)
    digs = ck.shard_digests(layout)
    chaos = FaultSchedule(
        seed=10, events=[FaultEvent(step=0, point="fabric.replica.lost")]
    )
    chaos.advance(0)
    rep = fab.ShardReplicaStore()
    srv = _serve_member(None, rep, 30)
    try:

        def shard_source(s):
            view = memoryview(
                np.ascontiguousarray(ck.leaves[s.leaf])
            ).cast("B")
            return view[s.offset : s.offset + s.length], digs[s.index]

        summary = fab.replicate_to_buddies(
            layout,
            0,
            30,
            1,
            {1: ("127.0.0.1", srv.port)},
            shard_source,
            chaos=chaos,
        )
    finally:
        srv.stop()
    assert summary["dropped"] > 0
    assert rep.nbytes() == 0
    assert not chaos.pending()


def test_replica_store_bounds_and_staleness():
    rep = fab.ShardReplicaStore(keep_steps=1)
    data = np.arange(16, dtype=np.uint8)
    crc = zlib.crc32(data)
    assert rep.wants(5, 0, 0, 16)
    assert rep.put(5, 0, 0, 16, data, crc)
    assert not rep.wants(5, 0, 0, 16)  # already held
    assert not rep.wants(4, 0, 0, 16)  # stale step declined
    assert rep.put(6, 1, 0, 16, data, crc)  # newer step...
    assert rep.get(5, 0, 0, 16) is None  # ...prunes the old one
    assert rep.newest_step() == 6
    # A crc-mismatched put is rejected outright.
    assert not rep.put(7, 0, 0, 16, data, crc ^ 1)


def test_inheritance_round_trip_via_next_agreement():
    """The stretch end-to-end, unit-scale: a 'victim' replicates its
    newest shards to a buddy, then a LATER agreement (victim gone)
    finds the buddy advertising them — the joiner restores a state
    that only survived through the replica store."""
    leaves = source_leaves(14)
    sizes = [l.nbytes for l in leaves]
    rows = [l.shape[0] if l.ndim else 0 for l in leaves]
    layout = fab.ShardLayout.build(sizes, 2, k=1, shard_bytes=1024, rows=rows)
    victim_ck = make_ckpt(leaves, step=40)
    digs = victim_ck.shard_digests(layout)

    survivor_rep = fab.ShardReplicaStore()
    srv = _serve_member(None, survivor_rep, 40)
    try:

        def shard_source(s):
            view = memoryview(
                np.ascontiguousarray(victim_ck.leaves[s.leaf])
            ).cast("B")
            return view[s.offset : s.offset + s.length], digs[s.index]

        # The victim owns EVERY shard at world=1 (it is the only
        # member of its ownership ring that still has the bytes).
        solo = fab.ShardLayout.build(sizes, 1, k=1, shard_bytes=1024,
                                     rows=rows)
        items = [
            (
                s.leaf,
                s.offset,
                s.length,
                digs[s.index],
                shard_source(s)[0],
            )
            for s in solo.shards
        ]
        accepted, _ = fab.push_shards(
            ("127.0.0.1", srv.port), 0, 40, 2, items
        )
        assert accepted == len(solo.shards)
    finally:
        srv.stop()

    # Next world: the survivor (replica-only) + a fresh joiner.  The
    # victim is gone; its state restores from the replica store.  One
    # holder only -> the fabric routes to the single-source stream,
    # which needs a full checkpoint — so pair the survivor with a twin
    # replica holder to keep multi-peer coverage.
    template = template_of(leaves)
    twin_rep = fab.ShardReplicaStore()
    for leaf, off, length, crc in survivor_rep.shards_at(40):
        data = survivor_rep.get(40, leaf, off, length)
        assert twin_rep.put(40, leaf, off, length, np.array(data), crc)

    r0, r1, r2 = run_world(
        [
            lambda f: fab.fabric_restore(
                f, template, None, shard_bytes=1024,
                replica_store=survivor_rep,
            ),
            lambda f: fab.fabric_restore(
                f, template, None, shard_bytes=1024,
                replica_store=twin_rep,
            ),
            lambda f: fab.fabric_restore(f, template, None, shard_bytes=1024),
        ]
    )
    assert r2.stats.mode == "fabric"
    assert r2.stats.step == 40
    for got, want in zip(r2.leaves, leaves):
        np.testing.assert_array_equal(
            np.asarray(got).reshape(want.shape), want
        )
    # No full-state authority existed: adoption digests are absent and
    # the caller fingerprints fresh (store.put path).
    assert r2.leaf_digests is None
    # The replica-only holders assembled REAL full leaves from their
    # stores too (not the absent checkpoint's Nones).
    for r in (r0, r1):
        for got, want in zip(r.leaves, leaves):
            assert got is not None
            np.testing.assert_array_equal(
                np.asarray(got).reshape(want.shape), want
            )


def test_fabric_metrics_and_events_registered():
    """Every fabric metric/event/chaos name is catalog-registered (the
    lint gate's runtime mirror)."""
    from edl_tpu.chaos.schedule import KNOWN_POINTS
    from edl_tpu.telemetry.catalog import CATALOG, KNOWN_EVENT_KINDS

    for name in (
        "edl_fabric_bytes_sent_total",
        "edl_fabric_bytes_received_total",
        "edl_fabric_shard_fallbacks_total",
        "edl_fabric_pull_peers",
        "edl_fabric_pull_seconds",
        "edl_fabric_replicas_total",
        "edl_fabric_replica_bytes_total",
    ):
        assert name in CATALOG, name
    for kind in ("fabric.pull", "fabric.replicate", "fabric.inherit"):
        assert kind in KNOWN_EVENT_KINDS, kind
    for point in (
        "fabric.replica.torn",
        "fabric.peer.lost",
        "fabric.replica.lost",
        "fabric.pull.slow",
    ):
        assert point in KNOWN_POINTS, point


def test_flush_sync_stage_b_hook_runs_on_background_thread():
    """``flush_sync(on_background=...)`` fires after fingerprint/spill
    on the BACKGROUND thread, and a hook failure is printed — never
    recorded as a flush error (a failed replication must not read as a
    failed flush and degrade a later resize to replay)."""
    import collections

    St = collections.namedtuple("St", ("w", "step"))
    store = HostDRAMStore()
    seen = []
    ckpt, bg = store.flush_sync(
        St(np.arange(1024, dtype=np.float32), 5),
        generation=2,
        on_background=lambda ck: seen.append(ck.step),
    )
    assert bg is not None
    bg.join()
    assert seen == [5]
    assert bg.edl_error is None

    def boom(ck):
        raise RuntimeError("replication transport down")

    ckpt2, bg2 = store.flush_sync(
        St(np.arange(1024, dtype=np.float32) + 1, 6),
        generation=2,
        on_background=boom,
    )
    bg2.join()
    assert bg2.edl_error is None  # hook errors never poison the flush
    store.wait()


def test_zero_length_shard_offer_keeps_session_in_sync():
    """A 0-byte leaf's shard carries no payload chunks in an OFFER
    session: an accepted empty shard must not desync the wire for the
    ranges after it (the ack must arrive, later shards must land)."""
    leaves = [
        np.zeros((0, 4), np.float32),  # 0-byte leaf
        np.arange(512, dtype=np.float32),
    ]
    sizes = [l.nbytes for l in leaves]
    layout = fab.ShardLayout.build(sizes, 2, shard_bytes=1024, rows=[0, 512])
    ck = make_ckpt(leaves, step=50)
    digs = ck.shard_digests(layout)
    rep = fab.ShardReplicaStore()
    srv = _serve_member(None, rep, 50)
    try:
        items = [
            (
                s.leaf,
                s.offset,
                s.length,
                digs[s.index],
                fab.byte_view(ck.leaves[s.leaf])[
                    s.offset : s.offset + s.length
                ],
            )
            for s in layout.shards  # empty shard FIRST, then payload
        ]
        accepted, sent = fab.push_shards(
            ("127.0.0.1", srv.port), 0, 50, 1, items
        )
    finally:
        srv.stop()
    assert accepted == len(layout.shards)
    assert sent == leaves[1].nbytes  # only the non-empty shards moved
    parts = [
        rep.get(50, 1, s.offset, s.length) for s in layout.by_leaf[1]
    ]
    assert all(p is not None for p in parts)
    np.testing.assert_array_equal(
        np.frombuffer(b"".join(bytes(p) for p in parts), np.float32),
        leaves[1],
    )
    assert rep.get(50, 0, 0, 0) is not None  # empty shard recorded


def test_replica_only_identical_coverage_assembles_locally():
    """Every member is a replica-only holder with the IDENTICAL full
    coverage: nothing moves (mode local) but each member must rebuild
    real leaves from its store — not return the absent checkpoint's
    Nones after a clean agreement."""
    leaves = source_leaves(15)
    sizes = [l.nbytes for l in leaves]
    rows = [l.shape[0] if l.ndim else 0 for l in leaves]
    layout = fab.ShardLayout.build(sizes, 2, shard_bytes=1024, rows=rows)
    ck = make_ckpt(leaves, step=60)
    digs = ck.shard_digests(layout)

    def replica_store_of():
        rep = fab.ShardReplicaStore()
        for s in layout.shards:
            view = fab.byte_view(ck.leaves[s.leaf])[
                s.offset : s.offset + s.length
            ]
            assert rep.put(
                60,
                s.leaf,
                s.offset,
                s.length,
                np.frombuffer(bytes(view), np.uint8),
                digs[s.index],
            )
        return rep

    template = template_of(leaves)
    r0, r1 = run_world(
        [
            lambda f, rep=replica_store_of(): fab.fabric_restore(
                f, template, None, shard_bytes=1024, replica_store=rep
            )
            for _ in range(2)
        ]
    )
    for r in (r0, r1):
        assert r.stats.mode == "local"
        assert r.stats.bytes_received == r.stats.bytes_sent == 0
        assert r.leaf_digests is None  # no full-state authority
        for got, want in zip(r.leaves, leaves):
            assert got is not None
            np.testing.assert_array_equal(
                np.asarray(got).reshape(want.shape), want
            )


def test_unrestorable_newer_step_degrades_to_full_checkpoint():
    """A replica-only PARTIAL newer step with no full holder anywhere
    must not livelock the hold-and-retry loop: the failed agreement
    drops the poisoned step's replica bytes on EVERY member (all
    decode the same gather matrix), so the retried agreement
    advertises the newest FULL checkpoint step — PR 2's
    degrade-to-next-oldest discipline at fabric granularity."""
    leaves = source_leaves(16)
    sizes = [l.nbytes for l in leaves]
    rows = [l.shape[0] if l.ndim else 0 for l in leaves]
    layout = fab.ShardLayout.build(sizes, 2, shard_bytes=1024, rows=rows)
    cks = [make_ckpt(leaves, step=60), make_ckpt(leaves, step=60)]
    nk = make_ckpt(source_leaves(17), step=70)
    digs70 = nk.shard_digests(layout)

    def partial_store():
        rep = fab.ShardReplicaStore()
        for s in layout.shards[: len(layout.shards) // 2]:
            view = fab.byte_view(nk.leaves[s.leaf])[
                s.offset : s.offset + s.length
            ]
            assert rep.put(
                70,
                s.leaf,
                s.offset,
                s.length,
                np.frombuffer(bytes(view), np.uint8),
                digs70[s.index],
            )
        return rep

    reps = [partial_store(), partial_store()]
    template = template_of(leaves)

    def held(rank):
        def fn(f):
            try:
                fab.fabric_restore(
                    f,
                    template,
                    cks[rank],
                    shard_bytes=1024,
                    replica_store=reps[rank],
                )
            except tx.TransferError as e:
                return str(e)
            return None

        return fn

    held0, held1 = run_world([held(0), held(1)])
    assert held0 is not None and held1 is not None
    assert "partial coverage" in held0
    # The poisoned step's bytes are gone on BOTH members: the retry
    # cannot re-advertise step 70.
    assert reps[0].newest_step() == reps[1].newest_step() == -1

    r0, r1 = run_world(
        [
            lambda f: fab.fabric_restore(
                f,
                template,
                cks[0],
                shard_bytes=1024,
                replica_store=reps[0],
            ),
            lambda f: fab.fabric_restore(
                f,
                template,
                cks[1],
                shard_bytes=1024,
                replica_store=reps[1],
            ),
        ]
    )
    for r in (r0, r1):
        assert r.stats.mode == "local"
        assert r.stats.step == 60
        assert r.stats.bytes_received == 0


def test_unrestorable_asymmetric_coverage_also_degrades():
    """No full holder + ASYMMETRIC partial coverage: needs is
    non-empty and ≥2 peers serve the needed shards, but some shards
    were advertised by NOBODY.  The gap check must catch those before
    the pull (they appear in no needs row) and degrade — previously
    this fell through to the exhausted-holder pull failure, which
    retries without degrading and livelocks."""
    leaves = source_leaves(18)
    sizes = [l.nbytes for l in leaves]
    rows = [l.shape[0] if l.ndim else 0 for l in leaves]
    layout = fab.ShardLayout.build(sizes, 3, shard_bytes=1024, rows=rows)
    cks = [make_ckpt(leaves, step=60) for _ in range(3)]
    nk = make_ckpt(source_leaves(19), step=70)
    digs70 = nk.shard_digests(layout)
    half = len(layout.shards) // 2

    def partial_store(count):
        rep = fab.ShardReplicaStore()
        for s in layout.shards[:count]:
            view = fab.byte_view(nk.leaves[s.leaf])[
                s.offset : s.offset + s.length
            ]
            assert rep.put(
                70,
                s.leaf,
                s.offset,
                s.length,
                np.frombuffer(bytes(view), np.uint8),
                digs70[s.index],
            )
        return rep

    # Two members cover the first half, the third only a quarter:
    # its missing shards have TWO serving peers, while the second
    # half of the table has none.
    reps = [partial_store(half), partial_store(half), partial_store(half // 2)]
    template = template_of(leaves)

    def held(rank):
        def fn(f):
            try:
                fab.fabric_restore(
                    f,
                    template,
                    cks[rank],
                    shard_bytes=1024,
                    replica_store=reps[rank],
                )
            except tx.TransferError as e:
                return str(e)
            return None

        return fn

    msgs = run_world([held(0), held(1), held(2)])
    for msg in msgs:
        assert msg is not None and "no holder" in msg
    for rep in reps:
        assert rep.newest_step() == -1  # poisoned step dropped

    results = run_world(
        [
            lambda f, r=rank: fab.fabric_restore(
                f,
                template,
                cks[r],
                shard_bytes=1024,
                replica_store=reps[r],
            )
            for rank in range(3)
        ]
    )
    for r in results:
        assert r.stats.mode == "local"
        assert r.stats.step == 60


def test_stale_step_member_keeps_crc_matched_shards():
    """PR 2's step-agnostic delta keep at shard granularity: a member
    whose checkpoint is one step BEHIND must re-pull only the shards
    whose crcs differ from the agreed reference — the agreement just
    proved the rest byte-identical, so sourcing them locally is free."""
    leaves = source_leaves(20)
    newer = [l.copy() for l in leaves]
    newer[0] = leaves[0] + 1.0  # one leaf really changed
    newer[2] = np.asarray(77, np.int32).reshape(())  # the 0-d step leaf
    template = template_of(leaves)
    cks = [
        make_ckpt([l.copy() for l in newer], step=80),
        make_ckpt([l.copy() for l in newer], step=80),
        make_ckpt(leaves, step=79),  # stale member
    ]
    rs = run_world(
        [
            lambda f, r=rank: fab.fabric_restore(
                f, template, cks[r], shard_bytes=1024
            )
            for rank in range(3)
        ]
    )
    stale = rs[2]
    assert stale.stats.step == 80
    changed = newer[0].nbytes + newer[2].nbytes
    total = sum(l.nbytes for l in leaves)
    assert 0 < stale.stats.bytes_received <= changed + 2048
    assert stale.stats.bytes_received < total // 2
    for got, want in zip(stale.leaves, newer):
        np.testing.assert_array_equal(
            np.asarray(got).reshape(want.shape), want
        )
