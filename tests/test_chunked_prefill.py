"""Chunked, decode-piggybacked prefill (ISSUE 14): stall-free token
scheduling for long prompts.

Key guarantees under test:

- **exactness**: chunked prefill's first sampled token EXACTLY matches
  monolithic prefill for every decode-capable family (transformer_lm,
  moe_lm, longcontext_lm) — including prompts exactly at, one below
  and one above a chunk boundary (the K/V a chunk scatters and the
  causal window it attends over are the same math, split differently);
- **stall-freedom**: a long admission's prompt rides chunk dispatches
  under a per-iteration token budget BESIDE the running batch's decode
  steps — already-active sequences keep emitting tokens while the
  long prompt prefills (the Sarathi-Serve property the PR exists for);
- **TTFT accounting**: TTFT spans ENQUEUE -> first token across every
  chunk, never last-chunk-dispatch -> first token (regression);
- **typed admission**: a prompt over the context cap raises
  ``PromptTooLongError`` at submit, before any chunk runs;
- **swap/expiry hygiene**: a hot swap mid-chunking restarts the
  prompt's chunking from zero on the new weights; a deadline expiry
  frees a half-prefilled sequence's KV blocks the same iteration;
- **zero compiles**: the chunk executables are AOT-held per
  (chunk-bucket x past-length-bucket) and the steady chunked path
  performs zero XLA compiles.
"""

import time

import jax
import numpy as np
import optax
import pytest

from edl_tpu import telemetry
from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.models.base import get_model
from edl_tpu.serving import DecodeEngine, TokenContinuousBatcher
from edl_tpu.serving.engine import PromptTooLongError

from tests.test_decode_serving import _lm_state, _reference_decode

_OPT = optax.adam(1e-3)


def _engine_for(model, step=1, seed=1, **kw):
    store = HostDRAMStore()
    store.save_async(_lm_state(model, step, seed), generation=0)
    store.wait()
    engine = DecodeEngine(
        model,
        store,
        devices=jax.devices()[:1],
        max_batch=1,
        max_seqs=4,
        block_tokens=16,
        **kw,
    )
    assert engine.load()
    engine.warm()
    return store, engine


@pytest.fixture(scope="module")
def chunked_lm():
    """One warmed transformer_lm DecodeEngine with a SMALL chunk cap
    (16 = one block) so modest prompts split into several chunks."""
    model = get_model("transformer_lm", tiny=True)
    store, engine = _engine_for(model, max_chunk_tokens=16)
    return model, store, engine


def _chunked_first_token(engine, weights, prompt, chunk=None):
    """Drive engine.prefill_chunk over the whole prompt (the batcher's
    split discipline: non-final chunks block-aligned, final chunk any
    length) and return the last chunk's sampled id."""
    bt = engine.block_tokens
    chunk = chunk or engine.max_chunk_tokens
    table = np.zeros(engine.blocks_per_seq, np.int32)
    blocks = []
    off, first = 0, None
    plen = len(prompt)
    while off < plen:
        clen = min(chunk, plen - off)
        if plen - off > clen:
            clen = (clen // bt) * bt
        bucket = engine.chunk_bucket_for(clen)
        need = (off + bucket) // bt - len(blocks)
        if need > 0:
            got = engine.pool.alloc(need)
            assert got is not None
            for b in got:
                table[len(blocks)] = b
                blocks.append(b)
        first = engine.prefill_chunk(
            weights, np.asarray(prompt[off : off + clen]), off, table
        )
        off += clen
    engine.pool.free(blocks)
    return first


# -- exactness: the acceptance criterion -------------------------------------


@pytest.mark.parametrize(
    "name", ["transformer_lm", "moe_lm", "longcontext_lm"]
)
def test_chunked_first_token_exact_vs_monolithic_per_family(name):
    """ISSUE 14 acceptance: chunked prefill's first sampled token ==
    monolithic prefill's, per family, seeded.  Prompt lengths cover
    exactly at / one below / one above a chunk boundary (32 with
    chunk 16) plus a multi-chunk tail case.  MoE routing is per-token
    on BOTH serving prefill paths, so the chunk split cannot move a
    token between routing groups."""
    model = get_model(name, tiny=True)
    store, engine = _engine_for(model, max_chunk_tokens=16)
    w = engine.current_weights()
    rng = np.random.RandomState(3)
    for plen in (31, 32, 33, 17, 50):
        prompt = model.synth_batch(rng, 1)["tokens"][0, :plen]
        blocks = engine.pool.alloc(engine.prompt_bucket_for(plen) // 16)
        table = np.zeros(engine.blocks_per_seq, np.int32)
        table[: len(blocks)] = blocks
        mono = engine.prefill(w, prompt, table)
        engine.pool.free(blocks)
        chunked = _chunked_first_token(engine, w, list(prompt))
        assert chunked == mono, (name, plen)
    assert engine.pool.used_blocks == 0


def test_chunk_boundary_prompts_end_to_end(chunked_lm):
    """Prompts at/below/above the chunk boundary serve correctly
    through the batcher: full token purity vs the reference decode,
    and the chunk count is exactly ceil-by-bucket of the prompt."""
    model, _, engine = chunked_lm
    batcher = TokenContinuousBatcher(engine).start()
    rng = np.random.RandomState(5)
    try:
        for plen, want_chunks in ((15, 1), (16, 1), (17, 2), (33, 3)):
            prompt = model.synth_batch(rng, 1)["tokens"][0, :plen]
            toks, meta = batcher.submit_generate(
                {"tokens": prompt}, max_new_tokens=4
            ).result(timeout=60)
            assert meta["prefill_chunks"] == want_chunks, plen
            w = engine.current_weights()
            ref = _reference_decode(model, w.params, list(prompt), 4, engine)
            assert toks == ref, plen
    finally:
        batcher.stop()
    assert engine.pool.used_blocks == 0


def test_prompt_over_context_cap_typed_error_at_admission(chunked_lm):
    """A prompt longer than the context cap is rejected AT ADMISSION
    with the typed error — before any chunk dispatches or any KV block
    is taken — and the batcher keeps serving."""
    model, _, engine = chunked_lm
    batcher = TokenContinuousBatcher(engine)
    too_long = list(range(engine.max_context))  # max_prompt + 1
    chunks0 = batcher.stats["chunks"]
    with pytest.raises(PromptTooLongError, match="max_prompt"):
        batcher.submit_generate({"tokens": too_long})
    assert isinstance(PromptTooLongError("x"), ValueError)  # HTTP 400
    assert batcher.stats["chunks"] == chunks0
    assert engine.pool.used_blocks == 0
    rng = np.random.RandomState(0)
    ok = model.synth_batch(rng, 1)["tokens"][0, :8]
    batcher.start()
    try:
        toks, _ = batcher.submit_generate(
            {"tokens": ok}, max_new_tokens=2
        ).result(timeout=60)
        assert len(toks) == 2
    finally:
        batcher.stop()


# -- stall-freedom ------------------------------------------------------------


def test_long_admission_never_stalls_running_decode(chunked_lm):
    """The tentpole property: while a long prompt prefills chunk by
    chunk, an already-running sequence keeps emitting tokens — at
    least one decode token lands BETWEEN the long request's admission
    and its first token (monolithic admission serializes instead: the
    whole prompt runs before the next decode step)."""
    model, _, engine = chunked_lm
    batcher = TokenContinuousBatcher(
        engine, prefill_token_budget=16
    ).start()
    rng = np.random.RandomState(7)
    short = model.synth_batch(rng, 1)["tokens"][0, :5]
    long = model.synth_batch(rng, 1)["tokens"][0, :48]
    events = []
    try:
        ta = batcher.submit_generate(
            {"tokens": short},
            max_new_tokens=40,
            on_event=lambda e: events.append(("a", time.monotonic(), e)),
        )
        deadline = time.monotonic() + 30
        while not any("token" in e for _, _, e in events):
            assert time.monotonic() < deadline
            time.sleep(0.002)
        tl = batcher.submit_generate(
            {"tokens": long},
            max_new_tokens=3,
            on_event=lambda e: events.append(("l", time.monotonic(), e)),
        )
        toks_l, meta_l = tl.result(timeout=60)
        toks_a, _ = ta.result(timeout=60)
    finally:
        batcher.stop()
    assert meta_l["prefill_chunks"] == 3  # 48 tokens / 16-token chunks
    t_l_first = next(
        t for who, t, e in events if who == "l" and "token" in e
    )
    interleaved = sum(
        1
        for who, t, e in events
        if who == "a" and "token" in e and t < t_l_first
    )
    assert interleaved >= 2, "running batch stalled behind the admission"
    # and neither sequence's output was perturbed by the interleave
    w = engine.current_weights()
    assert toks_l == _reference_decode(
        model, w.params, list(long), len(toks_l), engine
    )
    assert toks_a == _reference_decode(
        model, w.params, list(short), len(toks_a), engine
    )
    assert engine.pool.used_blocks == 0


def test_ttft_spans_enqueue_to_first_token_across_chunks(chunked_lm):
    """Regression (ISSUE 14 satellite): with each chunk dispatch slowed
    30ms, a 3-chunk prompt's reported TTFT must cover ALL chunks
    (>= ~90ms) — an accounting that starts at the last chunk's
    dispatch would report ~30ms."""
    model, _, engine = chunked_lm
    real = engine.prefill_chunk

    def slow_chunk(weights, chunk, offset, table_row):
        time.sleep(0.03)
        return real(weights, chunk, offset, table_row)

    engine.prefill_chunk = slow_chunk
    batcher = TokenContinuousBatcher(
        engine, prefill_token_budget=16
    ).start()
    rng = np.random.RandomState(9)
    prompt = model.synth_batch(rng, 1)["tokens"][0, :48]
    try:
        toks, meta = batcher.submit_generate(
            {"tokens": prompt}, max_new_tokens=2
        ).result(timeout=60)
    finally:
        batcher.stop()
        engine.prefill_chunk = real
    assert meta["prefill_chunks"] == 3
    assert meta["ttft_s"] >= 0.085, meta
    assert engine.pool.used_blocks == 0


# -- swap / expiry hygiene ----------------------------------------------------


def test_hot_swap_mid_chunking_restarts_from_zero():
    """A hot swap landing while a prompt is half-prefilled rewinds its
    chunking to zero: the old-generation K/V is never mixed with new
    weights, and the finished tokens equal the NEW generation's pure
    reference decode."""
    model = get_model("transformer_lm", tiny=True)
    store, engine = _engine_for(model, max_chunk_tokens=16)
    batcher = TokenContinuousBatcher(
        engine, prefill_token_budget=16, default_deadline_s=120.0
    )
    rng = np.random.RandomState(11)
    prompt = model.synth_batch(rng, 1)["tokens"][0, :48]
    real = engine.prefill_chunk
    swapped = []

    def swapping_chunk(weights, chunk, offset, table_row):
        if offset == 16 and not swapped:
            # The long prompt is demonstrably mid-chunking: land a new
            # verified checkpoint NOW.  The worker observes it at the
            # next token boundary and must rewind this prompt.
            swapped.append(True)
            store.save_async(_lm_state(model, 2, 2), generation=2)
            store.wait()
        return real(weights, chunk, offset, table_row)

    engine.prefill_chunk = swapping_chunk
    batcher.start()
    try:
        toks, meta = batcher.submit_generate(
            {"tokens": prompt}, max_new_tokens=4
        ).result(timeout=120)
    finally:
        batcher.stop()
        engine.prefill_chunk = real
    assert swapped, "the swap never fired"
    assert meta["weights_step"] == 2
    # chunking restarted from zero: 2 chunks pre-swap + 3 post-swap
    assert meta["prefill_chunks"] == 5, meta
    ref = _reference_decode(
        model,
        jax.device_get(_lm_state(model, 2, 2).params),
        list(prompt),
        len(toks),
        engine,
    )
    assert toks == ref
    assert engine.pool.used_blocks == 0


def test_expiry_frees_blocks_of_half_prefilled_sequence():
    """A sequence whose deadline passes mid-chunking is expired and its
    KV blocks freed the same iteration (half-prefilled sequences must
    not leak pool blocks)."""
    from edl_tpu.serving.batcher import DeadlineExceededError

    model = get_model("transformer_lm", tiny=True)
    _, engine = _engine_for(model, max_chunk_tokens=16)
    real = engine.prefill_chunk

    def slow_chunk(weights, chunk, offset, table_row):
        time.sleep(0.05)
        return real(weights, chunk, offset, table_row)

    engine.prefill_chunk = slow_chunk
    batcher = TokenContinuousBatcher(
        engine, prefill_token_budget=16
    ).start()
    rng = np.random.RandomState(13)
    prompt = model.synth_batch(rng, 1)["tokens"][0, :48]
    try:
        t = batcher.submit_generate(
            {"tokens": prompt}, deadline_s=0.08
        )
        with pytest.raises(DeadlineExceededError):
            t.result(timeout=30)
        # give the worker one iteration to settle the gauge
        deadline = time.monotonic() + 10
        while engine.pool.used_blocks and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        batcher.stop()
        engine.prefill_chunk = real
    assert engine.pool.used_blocks == 0


def test_pool_rebuild_mid_iteration_never_decodes_zeroed_cache():
    """Review regression: a failed chunk dispatch rebuilds the DONATED
    pools (cache_epoch bump) — the same worker iteration must NOT run
    the decode step over the zeroed cache, or an active sequence
    finishing on that garbage token resolves WRONG before the next
    iteration's epoch check can rewind it.  Timed deterministically:
    the failing admission is submitted from the active sequence's
    4th-token event, so the corrupted decode would have been its 5th
    and FINAL token."""
    model = get_model("transformer_lm", tiny=True)
    _, engine = _engine_for(model, max_chunk_tokens=16)
    batcher = TokenContinuousBatcher(
        engine, prefill_token_budget=16, default_deadline_s=60.0
    )
    rng = np.random.RandomState(29)
    pa = model.synth_batch(rng, 1)["tokens"][0, :10]
    pb = model.synth_batch(rng, 1)["tokens"][0, :20]
    real = engine.prefill_chunk
    boom = []

    def failing_chunk(weights, chunk, offset, table_row):
        # fail ONLY B's first chunk (16 tokens of its 20-token prompt;
        # A's single chunk is 10) — A must already be decoding
        if not boom and len(chunk) == 16:
            boom.append(True)
            # what engine._run does when a donated dispatch fails
            engine.pool.rebuild()
            engine.cache_epoch += 1
            raise RuntimeError("device fell over mid-chunk")
        return real(weights, chunk, offset, table_row)

    engine.prefill_chunk = failing_chunk
    errors = []

    def on_a_event(ev):
        if "token" in ev and ev["i"] == 3 and not boom:
            try:
                batcher.submit_generate({"tokens": pb}, max_new_tokens=2)
            except BaseException as e:  # resolved later via its ticket
                errors.append(e)

    batcher.start()
    try:
        toks_a, meta_a = batcher.submit_generate(
            {"tokens": pa}, max_new_tokens=5, on_event=on_a_event
        ).result(timeout=60)
    finally:
        batcher.stop()
        engine.prefill_chunk = real
    assert boom and not errors
    assert meta_a["restarts"] >= 1  # A was rewound, not served garbage
    ref = _reference_decode(
        model,
        jax.device_get(engine.current_weights().params),
        list(pa),
        5,
        engine,
    )
    assert toks_a == ref
    assert engine.pool.used_blocks == 0
    assert batcher.queued_prefill_tokens == 0


def test_ttft_histogram_observes_once_despite_restart():
    """Review regression: a hot-swap restart re-joins through
    _join_decode but must not inject a second, inflated TTFT sample —
    the histogram's contract is enqueue -> first EVER token, once."""
    model = get_model("transformer_lm", tiny=True)
    store, engine = _engine_for(model, max_chunk_tokens=16)
    with telemetry.scoped() as (reg, _):
        batcher = TokenContinuousBatcher(
            engine, default_deadline_s=60.0
        ).start()
        rng = np.random.RandomState(31)
        prompt = model.synth_batch(rng, 1)["tokens"][0, :10]
        fired = []

        def on_event(ev):
            if "token" in ev and ev["i"] == 2 and not fired:
                fired.append(True)
                store.save_async(_lm_state(model, 2, 2), generation=2)
                store.wait()

        try:
            toks, meta = batcher.submit_generate(
                {"tokens": prompt}, max_new_tokens=8, on_event=on_event
            ).result(timeout=60)
        finally:
            batcher.stop()
        assert meta["restarts"] >= 1
        h = reg.histogram("edl_serve_ttft_seconds").series()
        assert h["count"] == 1, h  # one sample despite the re-join
        assert meta["ttft_s"] is not None


def test_final_chunk_near_context_edge_cannot_overflow_table():
    """Review regression: with a large chunk cap, the FINAL chunk's
    padded bucket must not overshoot the context window — a 113-token
    prompt in a 128-token window whose tail chunk would bucket to 64
    at offset 80 previously overflowed the block table (IndexError on
    the worker thread -> every request hung).  The scheduler must cap
    the chunk to the room left and the engine must refuse an
    overshooting bucket loudly."""
    model = get_model("longcontext_lm", tiny=True)  # ctx 128
    _, engine = _engine_for(model, max_chunk_tokens=64)
    with pytest.raises(ValueError, match="overruns"):
        engine.prefill_chunk(
            engine.current_weights(),
            np.zeros(33, np.int32),
            80,
            np.zeros(engine.blocks_per_seq, np.int32),
        )
    batcher = TokenContinuousBatcher(
        engine, prefill_token_budget=80
    ).start()
    rng = np.random.RandomState(23)
    prompt = model.synth_batch(rng, 1)["tokens"][0, :113]
    try:
        toks, meta = batcher.submit_generate(
            {"tokens": prompt}, max_new_tokens=3
        ).result(timeout=60)
    finally:
        batcher.stop()
    # 64 @ 0, 16 @ 64 (budget tail), then room caps: 32 @ 80, 1 @ 112
    assert meta["prefill_chunks"] == 4, meta
    w = engine.current_weights()
    assert toks == _reference_decode(model, w.params, list(prompt), 3, engine)
    assert engine.pool.used_blocks == 0
    assert batcher.queued_prefill_tokens == 0


# -- zero compiles ------------------------------------------------------------


def test_chunked_steady_state_zero_xla_compiles(chunked_lm):
    """Warm engine: the whole chunked path — multi-chunk admissions at
    varied prompt lengths riding beside decode — dispatches held
    (chunk-bucket x past-length-bucket) executables only."""
    model, _, engine = chunked_lm
    import jax._src.compiler as _compiler

    batcher = TokenContinuousBatcher(
        engine, prefill_token_budget=16, default_max_new=4
    ).start()
    rng = np.random.RandomState(17)
    corpus = model.synth_batch(rng, 8)["tokens"]
    real = _compiler.backend_compile
    count = [0]

    def counting(*a, **k):
        count[0] += 1
        return real(*a, **k)

    _compiler.backend_compile = counting
    try:
        tickets = [
            batcher.submit_generate(
                {"tokens": corpus[i][: 7 + 8 * i]}, max_new_tokens=3 + i
            )
            for i in range(6)
        ]
        for t in tickets:
            t.result(timeout=60)
    finally:
        _compiler.backend_compile = real
        batcher.stop()
    assert count[0] == 0, f"{count[0]} XLA compiles on the chunked path"
    assert engine.pool.used_blocks == 0


def test_stall_and_queued_token_metrics_published(chunked_lm):
    """The new catalog metrics move: chunk dispatches counted, prompt
    tokens counted unpadded, and the stall histogram observes only
    when admission work held up a live batch."""
    model, _, engine = chunked_lm
    with telemetry.scoped() as (reg, _):
        batcher = TokenContinuousBatcher(
            engine, prefill_token_budget=16
        ).start()
        rng = np.random.RandomState(19)
        short = model.synth_batch(rng, 1)["tokens"][0, :5]
        long = model.synth_batch(rng, 1)["tokens"][0, :40]
        try:
            ta = batcher.submit_generate(
                {"tokens": short}, max_new_tokens=30
            )
            time.sleep(0.02)  # let it start decoding
            tl = batcher.submit_generate(
                {"tokens": long}, max_new_tokens=2
            )
            tl.result(timeout=60)
            ta.result(timeout=60)
        finally:
            batcher.stop()
        assert reg.counter("edl_serve_prefill_chunks_total").value() >= 3
        # true prompt tokens, not bucket padding: 40-token prompt =
        # 16 + 16 + 8
        assert (
            reg.counter("edl_serve_prefill_tokens_total").value() >= 40
        )
        stall = reg.histogram("edl_serve_prefill_stall_seconds").series()
        assert stall is not None and stall["count"] >= 1
