"""Golden-transcript test for the ``KubectlAPI`` shell-out surface
(VERDICT r5 #10).

``KubectlAPI`` is the one process boundary the framework cannot test
against a real cluster in CI, so its contract is pinned HERE: every
kubectl invocation's argv (and stdin payload) for the submit / scale /
delete flows is recorded through a transcript shim in front of
``fake_kubectl`` and compared against a golden sequence.  A change to
how the adapter shells out — reordered flags, a renamed verb, a
different patch shape — fails this test instead of surfacing on a live
cluster.
"""

import json
import stat
import sys
import textwrap

import pytest

from edl_tpu.cluster.kube import KubectlAPI, WorkloadInfo

RECORDER = """\
#!/usr/bin/env python
import io, json, os, sys
argv = sys.argv[1:]
# fake_kubectl reads stdin only for `apply -f -`; mirror that so a
# transcript run never blocks on an unpiped stdin.
payload = sys.stdin.read() if ("apply" in argv and "-" in argv) else ""
with open(os.environ["EDL_KUBECTL_TRANSCRIPT"], "a") as f:
    f.write(json.dumps({"argv": argv, "stdin": payload}) + chr(10))
sys.stdin = io.StringIO(payload)
from edl_tpu.cluster import fake_kubectl
sys.exit(fake_kubectl.main(argv))
"""

JOB_MANIFEST = {
    "apiVersion": "batch/v1",
    "kind": "Job",
    "metadata": {"name": "gj-trainer", "labels": {"edl-job": "gj"}},
    "spec": {
        "parallelism": 2,
        "template": {
            "spec": {
                "containers": [
                    {
                        "resources": {
                            "requests": {"cpu": "500m", "memory": "1Gi"},
                            "limits": {"google.com/tpu": "4"},
                        }
                    }
                ]
            }
        },
    },
}


@pytest.fixture
def transcript_api(tmp_path, monkeypatch):
    state = tmp_path / "kube-state.json"
    state.write_text(
        json.dumps(
            {
                "nodes": [
                    {
                        "name": "pool-0",
                        "cpu_milli": 16000,
                        "memory_mega": 65536,
                        "tpu_chips": 8,
                    }
                ]
            }
        )
    )
    recorder = tmp_path / "recorder.py"
    recorder.write_text(RECORDER)
    shim = tmp_path / "kubectl"
    shim.write_text(
        "#!/bin/sh\n" f'exec {sys.executable} {recorder} "$@"\n'
    )
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    transcript = tmp_path / "transcript.jsonl"
    monkeypatch.setenv("EDL_FAKE_KUBE_STATE", str(state))
    monkeypatch.setenv("EDL_KUBECTL_TRANSCRIPT", str(transcript))
    import os

    monkeypatch.setenv(
        "PYTHONPATH",
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    return KubectlAPI(kubectl=str(shim)), transcript


def _read(transcript):
    return [
        json.loads(line)
        for line in transcript.read_text().splitlines()
        if line.strip()
    ]


def test_golden_transcript_submit_scale_delete(transcript_api):
    api, transcript = transcript_api

    # -- submit: one `apply -f -` with the manifest List on stdin ------------
    api.apply_manifests([JOB_MANIFEST])
    # -- scale: optimistic-concurrency read-modify-patch-reread --------------
    w = api.get_workload("gj-trainer")
    assert w is not None and w.parallelism == 2
    w.parallelism = 3
    api.update_workload(w)
    # -- scale-down victim + teardown ----------------------------------------
    api.delete_pod("gj-pod-000001")
    api.delete_workload("gj-trainer")

    records = _read(transcript)
    golden_argv = [
        # submit
        ["-n", "default", "apply", "-f", "-"],
        # scale: GET (fresh resourceVersion) ...
        ["-n", "default", "get", "job", "gj-trainer", "-o", "json"],
        # ... PATCH with the rv precondition in the merge body ...
        [
            "-n",
            "default",
            "patch",
            "job",
            "gj-trainer",
            "--type=merge",
            "-p",
            json.dumps(
                {
                    "metadata": {"resourceVersion": "1"},
                    "spec": {"parallelism": 3},
                }
            ),
        ],
        # ... and the post-patch re-read update_workload returns
        ["-n", "default", "get", "job", "gj-trainer", "-o", "json"],
        # named-victim pod delete: non-blocking, idempotent
        [
            "-n",
            "default",
            "delete",
            "pod",
            "gj-pod-000001",
            "--wait=false",
            "--ignore-not-found",
        ],
        # delete_workload sweeps every kind a job may own, by one name
        ["-n", "default", "delete", "job", "gj-trainer", "--ignore-not-found"],
        [
            "-n",
            "default",
            "delete",
            "deployment",
            "gj-trainer",
            "--ignore-not-found",
        ],
        [
            "-n",
            "default",
            "delete",
            "service",
            "gj-trainer",
            "--ignore-not-found",
        ],
    ]
    assert [r["argv"] for r in records] == golden_argv

    # the submit payload: a v1 List wrapping the manifests verbatim
    payload = json.loads(records[0]["stdin"])
    assert payload == {
        "apiVersion": "v1",
        "kind": "List",
        "items": [JOB_MANIFEST],
    }
    # only apply ships stdin
    assert all(r["stdin"] == "" for r in records[1:])


SERVE_DEPLOYMENT = {
    "apiVersion": "apps/v1",
    "kind": "Deployment",
    "metadata": {"name": "gj-serve", "labels": {"edl-owner": "gj"}},
    "spec": {
        "replicas": 2,
        "template": {
            "spec": {
                "containers": [
                    {
                        "resources": {
                            "requests": {"cpu": "500m", "memory": "1Gi"},
                        }
                    }
                ]
            }
        },
    },
}


def test_golden_transcript_serving_replica_scale(transcript_api):
    """The ServingLane's kube half (ISSUE 13 satellite): scaling the
    serving replica Deployment pins the SAME optimistic-concurrency
    read-modify-patch-reread shape as trainer parallelism, against the
    deployment resource with the spec.replicas knob."""
    api, transcript = transcript_api
    api.apply_manifests([SERVE_DEPLOYMENT])
    w = api.get_workload("gj-serve", kind="Deployment")
    assert w is not None and w.kind == "Deployment" and w.parallelism == 2
    w.parallelism = 4
    after = api.update_workload(w)
    assert after.parallelism == 4 and after.kind == "Deployment"

    records = _read(transcript)
    golden_argv = [
        ["-n", "default", "apply", "-f", "-"],
        ["-n", "default", "get", "deployment", "gj-serve", "-o", "json"],
        [
            "-n",
            "default",
            "patch",
            "deployment",
            "gj-serve",
            "--type=merge",
            "-p",
            json.dumps(
                {
                    "metadata": {"resourceVersion": "1"},
                    "spec": {"replicas": 4},
                }
            ),
        ],
        ["-n", "default", "get", "deployment", "gj-serve", "-o", "json"],
    ]
    assert [r["argv"] for r in records] == golden_argv
    # a Job-kind lookup must NOT find the Deployment (kind-scoped API)
    assert api.get_workload("gj-serve", kind="Job") is None


def test_cluster_update_serving_replicas_conflict_retry(transcript_api):
    """Cluster.update_serving_replicas drives the transcript-pinned
    patch through the bounded conflict_retry idiom and reports a
    missing fleet as False, not an exception."""
    from edl_tpu.cluster.cluster import Cluster
    from edl_tpu.resource.training_job import TrainingJob

    api, transcript = transcript_api
    api.apply_manifests([SERVE_DEPLOYMENT])
    job = TrainingJob.from_yaml(
        """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: gj}
spec:
  fault_tolerant: true
  global_batch_size: 64
  checkpoint_dir: /ckpts
  trainer:
    entrypoint: mnist
    min_instance: 1
    max_instance: 4
    slice_topology: cpu
  serving:
    min_replicas: 1
    max_replicas: 5
"""
    ).validate()
    cluster = Cluster(api)
    assert cluster.update_serving_replicas(job, 3)
    w = api.get_workload("gj-serve", kind="Deployment")
    assert w.parallelism == 3
    # spec.serving unset -> False without touching kubectl
    before = len(_read(transcript))
    job.spec.serving = None
    assert not cluster.update_serving_replicas(job, 2)
    assert len(_read(transcript)) == before


def test_golden_transcript_drain_before_serving_scale_down(transcript_api):
    """ISSUE 15: the drain-victim-ack-then-patch scale-down sequence
    through ``update_serving_replicas``, pinned end to end.  The
    victim's /drain ack is recorded into the SAME transcript as the
    kubectl calls (the fake replica's handler appends a DRAIN line),
    so the golden proves ordering: the Deployment patch happens only
    AFTER the drain acked — a scale-down can never yank a replica with
    live generations."""
    import os
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from edl_tpu import telemetry
    from edl_tpu.autoscaler.serving import ServingLane, kube_replica_glue
    from edl_tpu.cluster.cluster import Cluster
    from edl_tpu.resource.training_job import TrainingJob

    api, transcript = transcript_api
    api.apply_manifests([SERVE_DEPLOYMENT])
    job = TrainingJob.from_yaml(
        """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: gj}
spec:
  fault_tolerant: true
  global_batch_size: 64
  checkpoint_dir: /ckpts
  trainer:
    entrypoint: mnist
    min_instance: 1
    max_instance: 4
    slice_topology: cpu
  serving:
    min_replicas: 1
    max_replicas: 5
"""
    ).validate()
    cluster = Cluster(api)
    tpath = os.environ["EDL_KUBECTL_TRANSCRIPT"]

    class DrainHandler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            with open(tpath, "a") as f:
                f.write(
                    json.dumps(
                        {"argv": ["DRAIN", "gj-serve-1"], "stdin": ""}
                    )
                    + "\n"
                )
            body = json.dumps(
                {"draining": True, "drained": True, "in_flight": 0}
            ).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), DrainHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    victim_addr = f"127.0.0.1:{srv.server_address[1]}"

    class Coord:
        target = 2

        def telemetry(self):
            return {
                "merged": {
                    "counters": {},
                    "gauges": {"edl_serve_queue_depth": {"": 0}},
                    "histograms": {},
                }
            }

        def metrics(self):
            return {"target_world": self.target}

        def plan(self):
            class P:
                members = ("gj-serve-0", "gj-serve-1")
                addresses = ("", victim_addr)

            return P()

        def set_prewarm(self, n, trace_id=""):
            pass

        def set_target_world(self, n, trace_id=""):
            self.target = n

    try:
        with telemetry.scoped():
            lane = ServingLane(
                Coord(),
                min_replicas=1,
                max_replicas=5,
                hold_ticks=1,
                on_scale=kube_replica_glue(cluster, job),
                victim_drain_timeout=5.0,
            )
            entry = lane.run_once()
        assert entry["actuated"] and entry["drain"]["acked"]
    finally:
        srv.shutdown()
        srv.server_close()

    records = _read(transcript)
    golden_argv = [
        # fleet submit
        ["-n", "default", "apply", "-f", "-"],
        # the victim drain ACK — strictly before any kubectl mutation
        ["DRAIN", "gj-serve-1"],
        # then the pinned read-modify-patch-reread Deployment scale
        ["-n", "default", "get", "deployment", "gj-serve", "-o", "json"],
        [
            "-n",
            "default",
            "patch",
            "deployment",
            "gj-serve",
            "--type=merge",
            "-p",
            json.dumps(
                {
                    "metadata": {"resourceVersion": "1"},
                    "spec": {"replicas": 1},
                }
            ),
        ],
        ["-n", "default", "get", "deployment", "gj-serve", "-o", "json"],
    ]
    assert [r["argv"] for r in records] == golden_argv


def test_golden_transcript_conflict_surfaces(transcript_api):
    """A stale resourceVersion must round-trip to ConflictError through
    the recorded patch invocation (the retry loop's trigger)."""
    from edl_tpu.cluster.kube import ConflictError

    api, transcript = transcript_api
    api.apply_manifests([JOB_MANIFEST])
    stale = WorkloadInfo(
        name="gj-trainer", job_name="gj", parallelism=5, resource_version=99
    )
    with pytest.raises(ConflictError):
        api.update_workload(stale)
    records = _read(transcript)
    assert records[-1]["argv"][2:6] == ["patch", "job", "gj-trainer", "--type=merge"]
    assert json.loads(records[-1]["argv"][-1]) == {
        "metadata": {"resourceVersion": "99"},
        "spec": {"parallelism": 5},
    }


def test_recorder_is_literal_shim():
    """The transcript recorder must stay a pass-through: it may not
    reorder or rewrite argv (the golden pins would be meaningless)."""
    assert "fake_kubectl.main(argv)" in textwrap.dedent(RECORDER)
