"""Data-plane step agreement (edl_tpu.consensus): the step bus, the
stop-step protocol, the collective watchdog, and their journal trail.

The multipod half — two real processes, one with a chaos-delayed plan
poll, leaving the old world at the same step boundary — lives in
``tests/test_multipod.py`` (it needs real subprocess pods); this file
covers the protocol and its pieces on the in-process 8-device world.
"""

import threading
import time

import numpy as np
import optax
import pytest

from edl_tpu import telemetry
from edl_tpu.chaos.schedule import FaultEvent, FaultSchedule
from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.consensus import (
    CollectiveTimeout,
    CollectiveWatchdog,
    StepBus,
    timing_bucket,
)
from edl_tpu.models import get_model
from edl_tpu.runtime import ShardedDataIterator
from edl_tpu.runtime.coordinator import LocalCoordinator
from edl_tpu.runtime.data import synthetic_dataset
from edl_tpu.runtime.elastic import ElasticTrainer


def _world(devices, n=4, gbs=8, ckpt_interval=0, chaos=None, **kw):
    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 256, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=gbs, seed=0)
    coord = LocalCoordinator(
        target_world=n, max_world=n, legal_sizes=[1, 2, 4][: n.bit_length()]
    )
    for i in range(n):
        coord.register(f"t{i}")
    et = ElasticTrainer(
        model,
        optax.sgd(0.05),
        it,
        coord,
        devices=devices[:n],
        checkpoint_interval=ckpt_interval,
        store=HostDRAMStore(chaos=chaos) if chaos is not None else None,
        **kw,
    )
    return et, coord


# ---- the bus itself -------------------------------------------------------
def test_bus_word_roundtrip(devices8):
    from edl_tpu.parallel.mesh import dp_mesh

    mesh = dp_mesh(4)
    with telemetry.scoped() as (reg, rec):
        bus = StepBus(registry=reg, recorder=rec)
        out = bus.dispatch(
            mesh, step=7, generation=3, stop=10, poison=False, bucket=2
        )
        word = bus.decode(mesh, 7, np.asarray(out))
    assert word.step == 7
    assert word.max_generation == 3
    assert word.stop_step == 10
    assert not word.poisoned
    # single process: every row belongs to rank 0, identical bucket
    assert word.member_buckets == {0: 2}
    assert word.skew == 0


def test_bus_decode_detects_straggler_and_poison():
    """Unit-level decode over a crafted gathered matrix: per-member
    timing buckets, the straggler call, and the poison bit."""
    from edl_tpu.parallel.mesh import dp_mesh

    mesh = dp_mesh(4)
    with telemetry.scoped() as (reg, rec):
        bus = StepBus(registry=reg, recorder=rec)
        b = bus.bind(mesh)
        # pretend rows 0/1 belong to rank 0, rows 2/3 to rank 1
        object.__setattr__(b, "row_owner", (0, 0, 1, 1))
        mat = np.array(
            [
                [5, 0, 0, 1],
                [5, 0, 0, 1],
                [5, 0, 1, 9],  # rank 1: poisoned, 8 buckets slower
                [5, 0, 0, 9],
            ],
            np.int32,
        )
        word = bus.decode(mesh, 3, mat)
        assert word.poisoned
        assert word.member_buckets == {0: 1, 1: 9}
        assert word.skew == 8
        assert word.straggler == 1
        snap = reg.snapshot()
        assert snap["counters"]["edl_consensus_stragglers_total"]
        kinds = [e.kind for e in rec.events(10)]
        assert "consensus.straggler" in kinds


def test_bus_warm_makes_dispatch_zero_compile(devices8, monkeypatch):
    """The warm-resize zero-compile contract extends to the bus: after
    ``warm(mesh)``, the first dispatch performs no backend compile."""
    import jax._src.compiler as _compiler

    from edl_tpu.parallel.mesh import dp_mesh

    mesh = dp_mesh(4)
    with telemetry.scoped() as (reg, rec):
        bus = StepBus(registry=reg, recorder=rec)
        bus.warm(mesh)
        compiles = []
        real = _compiler.backend_compile

        def counting(*args, **kwargs):
            compiles.append(1)
            return real(*args, **kwargs)

        monkeypatch.setattr(_compiler, "backend_compile", counting)
        out = bus.dispatch(
            mesh, step=0, generation=1, stop=0, poison=False, bucket=0
        )
        np.asarray(out)
    assert compiles == [], f"{len(compiles)} compiles after warm"


def test_timing_bucket_quantization():
    assert timing_bucket(0.0005) == 0
    assert timing_bucket(0.0015) == 1
    assert timing_bucket(0.1) > timing_bucket(0.01) > timing_bucket(0.002)
    assert timing_bucket(1e9) == 31


# ---- stop agreement on a live local world ---------------------------------
def test_stop_agreement_quiesces_at_one_boundary(devices8):
    """A retarget on a live multi-member world must NOT tear down on
    sight of the plan: the vote rides the step bus and the world leaves
    at ``stop_step = vote_step + pipeline_depth + 1`` — the old world's
    step stream runs exactly to the boundary, the new world starts at
    it, and the agreement is journaled end to end (consensus.vote /
    consensus.stop / consensus.quiesce + ResizeEvent.stop_step)."""
    et, coord = _world(devices8, n=4)
    et.consensus_stop = True  # force the multipod-only default on

    fired = []

    def on_step(rec):
        if rec.step == 5 and not fired:
            fired.append(rec.step)
            coord.heartbeat("t0", step=rec.step)
            coord.set_target_world(2)

    with telemetry.scoped() as (reg, rec):
        et.telemetry = reg
        et.recorder = rec
        et._bus = StepBus(registry=reg, recorder=rec)
        hist = et.run(30, on_step=on_step)
        events = {e.kind: e for e in rec.events(200)}

    ev = et.resize_events[-1]
    assert ev.world_size == 2
    stop = ev.stop_step
    assert stop > 5, f"agreed stop {stop} not after the retarget step"
    # THE boundary property: every old-world step is < stop, the new
    # world starts exactly AT stop, nothing is lost or doubled.
    old = [r.step for r in hist if r.world_size == 4]
    new = [r.step for r in hist if r.world_size == 2]
    assert max(old) == stop - 1
    assert min(new) == stop
    assert sorted(old + new) == list(range(30))
    # journal trail
    assert events["consensus.vote"].data["for_generation"] == ev.generation
    assert events["consensus.stop"].data["stop_step"] == stop
    assert events["consensus.quiesce"].data["stop_step"] == stop
    vote_step = events["consensus.stop"].data["vote_step"]
    assert stop == vote_step + et.pipeline_depth + 1


def test_stop_agreement_synchronous_pipeline(devices8):
    """Depth 0 (the synchronous loop): horizon collapses to 1 — the
    world leaves one step after the vote, still as one boundary."""
    et, coord = _world(devices8, n=2, gbs=8)
    et.consensus_stop = True
    et.pipeline_depth = 0

    def on_step(rec):
        if rec.step == 4:
            coord.set_target_world(1)

    hist = et.run(12, on_step=on_step)
    ev = et.resize_events[-1]
    assert ev.world_size == 1
    old = [r.step for r in hist if r.world_size == 2]
    assert max(old) == ev.stop_step - 1
    assert sorted(r.step for r in hist) == list(range(12))


def test_consensus_losses_bit_identical_bus_on_off(devices8):
    """The control word rides beside the model step: the loss stream
    must be BIT-identical with the bus on or off (no resize)."""

    def run(bus_on):
        et, _ = _world(devices8, n=4)
        et.consensus_bus = bus_on
        return [r.loss for r in et.run(12)]

    assert run(True) == run(False)


def test_plan_stamps_stop_step_from_heartbeat():
    coord = LocalCoordinator(target_world=2, max_world=2)
    coord.register("a")
    coord.register("b")
    assert coord.plan().stop_step == -1  # nothing reported yet
    coord.heartbeat("a", step=40)
    coord.set_target_world(1)
    plan = coord.plan()
    assert plan.stop_step == 40 + coord.stop_margin
    # checkpoint reports feed the stamp too (retarget forces a rebuild)
    coord.report_checkpoint(90)
    coord.set_target_world(2)
    assert coord.plan().stop_step == 90 + coord.stop_margin


def test_plan_stop_step_over_http():
    from edl_tpu.runtime.coord_service import (
        CoordinatorServer,
        HTTPCoordinator,
    )

    coord = LocalCoordinator(target_world=2, max_world=2)
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start()
    try:
        client = HTTPCoordinator(f"127.0.0.1:{server.port}")
        client.register("a")
        client.register("b")
        client.heartbeat("a", step=25)
        client.set_target_world(1)
        plan = client.plan()
        assert plan.stop_step == 25 + coord.stop_margin
    finally:
        server.stop()


def test_immediate_resize_journals_no_fabricated_boundary(devices8):
    """An IMMEDIATE resize (no live multi-member world, no agreement)
    must journal stop_step = -1 even when the coordinator stamped an
    advisory stop into the plan: the stamp lives in the coordinator's
    own journal (coord.plan events / decision log), and recording it
    as 'honored' would fabricate a boundary that never existed."""
    et, coord = _world(devices8, n=2, gbs=8)
    coord.heartbeat("t0", step=0)
    coord.set_target_world(1)  # before any world forms
    assert coord.plan().stop_step >= 0  # the stamp IS in the plan
    et.run(4)
    first = et.resize_events[0]
    assert first.stop_step == -1, first
    # ...and the honored boundary is always the agreement alone
    et._stop_agreed = 9
    assert et._effective_stop() == 9


def test_vote_delay_chaos_defers_the_poll(devices8):
    """chaos[consensus.vote.delayed]: the member keeps stepping
    obliviously while its plan poll is suppressed, then quiesces and
    resizes normally once the suppression expires."""
    sched = FaultSchedule(
        0, [FaultEvent(0, "consensus.vote.delayed", 0.3)]
    )
    et, coord = _world(devices8, n=2, gbs=8, chaos=sched)
    et.consensus_stop = True
    marks = {}

    def on_step(rec):
        sched.advance(rec.step)
        time.sleep(0.005)  # keep the run alive past the suppression
        if rec.step == 3 and "t0" not in marks:
            marks["t0"] = time.monotonic()
            coord.set_target_world(1)

    hist = et.run(200, on_step=on_step)
    ev = et.resize_events[-1]
    assert ev.world_size == 1
    assert time.monotonic() - marks["t0"] >= 0.3
    assert not sched.pending(), "the delay event never fired"
    assert sorted(r.step for r in hist) == list(range(200))


# ---- collective watchdog --------------------------------------------------
def test_watchdog_passthrough_and_timeout():
    with telemetry.scoped() as (reg, rec):
        wd = CollectiveWatchdog(timeout=5.0, registry=reg, recorder=rec)
        assert wd.fetch(lambda: 42) == 42
        # exceptions propagate unchanged
        with pytest.raises(ValueError, match="boom"):
            wd.fetch(lambda: (_ for _ in ()).throw(ValueError("boom")))
        wd.timeout = 0.1
        release = threading.Event()
        with pytest.raises(CollectiveTimeout, match="watchdog"):
            wd.fetch(release.wait)  # wedged "collective"
        assert wd.trips == 1
        release.set()  # unwedge the abandoned worker
        # a fresh worker serves the next fetch
        assert wd.fetch(lambda: 7) == 7
        snap = reg.snapshot()
        assert (
            sum(
                snap["counters"][
                    "edl_consensus_watchdog_trips_total"
                ].values()
            )
            == 1
        )
        kinds = [e.kind for e in rec.events(10)]
        assert "consensus.watchdog" in kinds


def test_watchdog_disabled_runs_inline():
    wd = CollectiveWatchdog(timeout=0.0)
    assert wd.fetch(lambda: threading.current_thread().name) == (
        threading.current_thread().name
    )


def test_watchdog_chaos_trip_without_wait():
    sched = FaultSchedule(0, [FaultEvent(0, "consensus.watchdog.trip")])
    sched.advance(0)
    with telemetry.scoped() as (reg, rec):
        wd = CollectiveWatchdog(timeout=0.0, chaos=sched, registry=reg, recorder=rec)
        t0 = time.perf_counter()
        with pytest.raises(CollectiveTimeout, match="chaos"):
            wd.fetch(lambda: 1)
        assert time.perf_counter() - t0 < 1.0  # no actual wait
    # one-shot: the next fetch is clean
    assert wd.fetch(lambda: 1) == 1


def test_watchdog_trip_buries_world_and_recovers(devices8):
    """A tripped watchdog mid-run takes the broken-world recovery path
    (world buried, hold, re-form on the generation bump) — the wedged-
    collective hang becomes a bounded resize + replay."""
    sched = FaultSchedule(0, [FaultEvent(4, "consensus.watchdog.trip")])
    et, coord = _world(
        devices8,
        n=2,
        gbs=8,
        ckpt_interval=2,
        chaos=sched,
        world_builder=lambda plan: devices8[:2],
    )
    et.heartbeat_ids = ["t0", "t1"]
    et.barrier_poll_interval = 0.01

    def on_step(rec):
        sched.advance(rec.step)

    # the reaper analog: re-admit the world after the break
    stop = threading.Event()

    def bumper():
        while not stop.wait(0.25):
            coord.deregister("t1")
            coord.register("t1")

    th = threading.Thread(target=bumper, daemon=True)
    th.start()
    try:
        hist = et.run(10, on_step=on_step)
    finally:
        stop.set()
        th.join(timeout=5)
    assert sorted(set(r.step for r in hist)) == list(range(10))
    assert et._watchdog is not None and et._watchdog.trips == 1
    kinds = [e.kind for e in et.recorder.events(400)]
    assert "world.broken" in kinds


def test_poisoned_word_buries_world(devices8):
    """A peer's poison bit surfaces as BusPoisonError at harvest and
    takes the same recovery path as a mid-collective death."""
    et, coord = _world(
        devices8,
        n=2,
        gbs=8,
        ckpt_interval=2,
        world_builder=lambda plan: devices8[:2],
    )
    et.heartbeat_ids = ["t0", "t1"]
    et.barrier_poll_interval = 0.01
    et._bus_poison = True  # this member self-reports failure

    def unpoison_and_bump():
        et._bus_poison = False
        coord.deregister("t1")
        coord.register("t1")

    timer = threading.Timer(0.3, unpoison_and_bump)
    timer.start()
    try:
        hist = et.run(6)
    finally:
        timer.cancel()
    assert sorted(set(r.step for r in hist)) == list(range(6))
    assert et._m_world_breaks.value() >= 1


# ---- actuation sequencing -------------------------------------------------
def test_autoscaler_victim_deletion_waits_for_world_ack():
    """The scale-down actuation must not SIGTERM victim pods while the
    world is still quiescing toward the agreed stop: deletion waits
    (bounded) until every member of the retargeted plan acked the new
    generation (= the old world fully left the boundary).  Coordinators
    without the signal, and worlds with no live trainers, skip the
    wait."""
    from edl_tpu.autoscaler.scaler import Autoscaler

    asc = Autoscaler.__new__(Autoscaler)
    asc.victim_drain_timeout = 5.0

    # (1) live world mid-quiesce: the wait holds until the ack lands
    coord = LocalCoordinator(target_world=2, max_world=2)
    coord.register("a")
    coord.register("b")
    gen = coord.plan().generation
    coord.ack_generation("a", gen)
    coord.ack_generation("b", gen)
    coord.set_target_world(1)  # retarget: nobody acked the new gen yet
    new_gen = coord.plan().generation

    def ack_later():
        coord.ack_generation("a", new_gen)

    t = threading.Timer(0.4, ack_later)
    t.start()
    t0 = time.monotonic()
    try:
        asc._wait_for_quiesce(coord)
    finally:
        t.cancel()
    waited = time.monotonic() - t0
    assert 0.3 <= waited < 5.0, waited

    # (2) no live trainers (nobody ever acked): no wait at all
    cold = LocalCoordinator(target_world=2, max_world=2)
    cold.register("x")
    cold.set_target_world(1)
    t0 = time.monotonic()
    asc._wait_for_quiesce(cold)
    assert time.monotonic() - t0 < 0.3

    # (3) pre-consensus coordinator shape: no signal, no wait
    class Legacy:
        def metrics(self):
            return {"generation": 1}

    t0 = time.monotonic()
    asc._wait_for_quiesce(Legacy())
    assert time.monotonic() - t0 < 0.3


# ---- lint: chaos injection points are registry-checked --------------------
def test_lint_rejects_unregistered_chaos_point(tmp_path):
    import sys as _sys

    _sys.path.insert(0, "tools")
    try:
        import lint
    finally:
        _sys.path.pop(0)

    bad = tmp_path / "edl_tpu" / "mod.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        'def f(s, p):\n'
        '    s.maybe_raise("consensus.watchdog.tripp")\n'
        '    s.due(p)\n'
        '    s.due("consensus.watchdog.trip")\n'
    )
    msgs = [m for _, m in lint.lint_file(bad)]
    assert any("unregistered chaos injection point" in m for m in msgs)
    assert any("free-form chaos point" in m for m in msgs)
    # the registered literal on the last line is NOT flagged
    assert sum("chaos" in m for m in msgs) == 2
