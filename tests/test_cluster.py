"""L1 cluster layer + L3 control loop, closed-loop against FakeKube —
the integration coverage the reference never had (SURVEY.md §4: Cluster
and the Run loop were untested)."""

import pytest

from edl_tpu.autoscaler.scaler import Autoscaler
from edl_tpu.cluster.cluster import Cluster
from edl_tpu.cluster.kube import ConflictError, FakeKube, NodeInfo, WorkloadInfo
from edl_tpu.resource.training_job import TrainingJob


def tpu_nodes(n=4, chips=4, cpu=8000, mem=32768):
    return [
        NodeInfo(
            name=f"pool-{i}",
            cpu_milli=cpu,
            memory_mega=mem,
            tpu_chips=chips,
            tpu_topology=f"v5e-{chips}",
        )
        for i in range(n)
    ]


def make_job(name="j", mn=1, mx=4, topo="v5e-4", cpu="1", mem="1Gi", gbs=0):
    return TrainingJob.from_manifest(
        {
            "apiVersion": "edl.tpu.dev/v1",
            "kind": "TrainingJob",
            "metadata": {"name": name},
            "spec": {
                "fault_tolerant": mn < mx,
                "global_batch_size": gbs,
                "trainer": {
                    "min_instance": mn,
                    "max_instance": mx,
                    "slice_topology": topo,
                    "resources": {"requests": {"cpu": cpu, "memory": mem}},
                },
            },
        }
    ).validate()


# ---- FakeKube mechanics -----------------------------------------------------


def test_fake_kube_reconciles_parallelism_to_pods():
    kube = FakeKube(tpu_nodes(4))
    cluster = Cluster(kube)
    job = make_job()
    cluster.create_trainer_workload(job)
    assert cluster.job_pods(job) == (1, 1, 0, 0)
    assert cluster.update_parallelism(job, 3)
    assert cluster.job_pods(job) == (3, 3, 0, 0)
    assert cluster.update_parallelism(job, 1)
    assert cluster.job_pods(job) == (1, 1, 0, 0)


def test_fake_kube_leaves_unschedulable_pods_pending():
    kube = FakeKube(tpu_nodes(2))  # 8 chips
    cluster = Cluster(kube)
    job = make_job(mx=4)
    cluster.create_trainer_workload(job)
    cluster.update_parallelism(job, 4)  # wants 16 chips
    total, running, pending, _ = cluster.job_pods(job)
    assert (total, running, pending) == (4, 2, 2)


def test_fake_kube_conflict_on_stale_resource_version():
    kube = FakeKube(tpu_nodes(1))
    w = kube.create_workload(
        WorkloadInfo(name="w-trainer", job_name="w", parallelism=1)
    )
    stale = WorkloadInfo(**vars(w))
    kube.update_workload(w)  # bumps version
    stale.parallelism = 3
    with pytest.raises(ConflictError):
        kube.update_workload(stale)


def test_update_parallelism_retries_through_conflicts():
    kube = FakeKube(tpu_nodes(2))
    cluster = Cluster(kube)
    job = make_job()
    cluster.create_trainer_workload(job)

    real_get = kube.get_workload
    calls = {"n": 0}

    def racy_get(name):
        w = real_get(name)
        calls["n"] += 1
        if calls["n"] == 1 and w is not None:
            w.resource_version -= 1  # simulate a concurrent writer
        return w

    kube.get_workload = racy_get
    assert cluster.update_parallelism(job, 2)
    assert real_get(job.trainer_job_name()).parallelism == 2


# ---- inventory --------------------------------------------------------------


def test_inquiry_resource_charges_scheduled_pods_only():
    kube = FakeKube(tpu_nodes(2, chips=4, cpu=4000))
    cluster = Cluster(kube)
    job = make_job(mx=4)
    cluster.create_trainer_workload(job)
    cluster.update_parallelism(job, 4)  # 2 run, 2 pend (8 chips exist)
    r = cluster.inquiry_resource()
    assert r.tpu_total == 8
    assert r.tpu_limit == 8  # only the two scheduled replicas
    assert r.cpu_request_milli == 2000
    assert sum(r.nodes.tpu_free.values()) == 0


# ---- autoscaler closed loop -------------------------------------------------


def test_autoscaler_grows_job_into_idle_cluster():
    kube = FakeKube(tpu_nodes(4))  # 16 chips
    cluster = Cluster(kube)
    a = Autoscaler(cluster)
    job = make_job(mn=1, mx=4)
    cluster.create_trainer_workload(job)
    a.on_add(job)
    # fixed point reaches max within a few loop iterations
    for _ in range(4):
        a.run_once()
    assert cluster.get_trainer_workload(job).parallelism == 4
    assert cluster.job_pods(job) == (4, 4, 0, 0)


def test_autoscaler_holds_non_elastic_job():
    kube = FakeKube(tpu_nodes(4))
    cluster = Cluster(kube)
    a = Autoscaler(cluster)
    job = make_job(mn=2, mx=2)
    cluster.create_trainer_workload(job)
    cluster.update_parallelism(job, 2)
    a.on_add(job)
    assert a.run_once() is None or cluster.get_trainer_workload(job).parallelism == 2


def test_autoscaler_sheds_elastic_job_for_pending_job():
    kube = FakeKube(tpu_nodes(4))  # 16 chips
    cluster = Cluster(kube)
    a = Autoscaler(cluster)
    greedy = make_job("greedy", mn=1, mx=4)
    cluster.create_trainer_workload(greedy)
    a.on_add(greedy)
    for _ in range(4):
        a.run_once()
    assert cluster.get_trainer_workload(greedy).parallelism == 4  # all chips

    newbie = make_job("newbie", mn=1, mx=2)
    cluster.create_trainer_workload(newbie)  # pod stays Pending: 0 free chips
    assert cluster.job_pods(newbie) == (1, 0, 1, 0)
    a.on_add(newbie)
    for _ in range(4):
        a.run_once()
        kube.retry_scheduling()
    assert cluster.get_trainer_workload(greedy).parallelism == 3
    assert cluster.job_pods(newbie) == (1, 1, 0, 0)  # newbie runs


def test_autoscaler_event_removal_stops_management():
    kube = FakeKube(tpu_nodes(4))
    cluster = Cluster(kube)
    a = Autoscaler(cluster)
    job = make_job()
    cluster.create_trainer_workload(job)
    a.on_add(job)
    a.run_once()
    a.on_del(job)
    assert a.run_once() is None


def test_batch_quantized_growth_closed_loop():
    # global_batch 96, max 8 -> legal sizes 1,2,3,4,6,8: growth jumps
    # only between those.
    kube = FakeKube(tpu_nodes(8))  # 32 chips
    cluster = Cluster(kube)
    a = Autoscaler(cluster)
    job = make_job(mn=1, mx=8, gbs=96)
    cluster.create_trainer_workload(job)
    a.on_add(job)
    seen = []
    for _ in range(8):
        a.run_once()
        seen.append(cluster.get_trainer_workload(job).parallelism)
    assert seen[-1] == 8
    legal = {1, 2, 3, 4, 6, 8}
    assert all(s in legal for s in seen), seen


# ---- scale-down victim coordination (VERDICT r3 missing-3) ------------------


def test_fake_kube_arbitrary_victim_mode():
    """The real kube Job controller promises nothing about which pod it
    kills on a parallelism drop; the 'oldest' mode makes FakeKube
    adversarial so tests can't silently rely on drop-newest luck."""
    kube = FakeKube(tpu_nodes(4), scale_down_victim="oldest")
    cluster = Cluster(kube)
    job = make_job()
    cluster.create_trainer_workload(job)
    cluster.update_parallelism(job, 3)
    names = sorted(p.name for p in kube.list_pods())
    cluster.update_parallelism(job, 2)
    left = sorted(p.name for p in kube.list_pods())
    assert left == names[1:], "oldest pod should have been the victim"


def test_fake_kube_graceful_delete_preempts_victim_choice():
    """A named graceful delete before the parallelism PUT converges the
    count without the controller choosing: the Terminating pod is purged
    first, so no additional victim is needed."""
    kube = FakeKube(tpu_nodes(4), scale_down_victim="oldest")
    cluster = Cluster(kube)
    job = make_job()
    cluster.create_trainer_workload(job)
    cluster.update_parallelism(job, 3)
    names = sorted(p.name for p in kube.list_pods())
    assert kube.delete_pod(names[-1])  # gracefully remove the newest
    cluster.update_parallelism(job, 2)
    left = sorted(p.name for p in kube.list_pods())
    assert left == names[:2], "named victim should have satisfied the drop"


def test_scale_down_victims_follow_coordinator_plan():
    """End-to-end victim coordination: on scale-down the autoscaler
    deletes exactly the pods the coordinator dropped from the plan, so
    even an adversarial Job controller never kills an active-world
    member — the graceful-resize path, no lease timeout (VERDICT r3
    missing-3; ref kube-chooses semantics pkg/autoscaler.go:339-376)."""
    from edl_tpu.runtime.coordinator import LocalCoordinator

    kube = FakeKube(tpu_nodes(4), scale_down_victim="oldest")
    cluster = Cluster(kube)
    coord = LocalCoordinator(
        target_world=1, max_world=4, heartbeat_timeout=1e9,
        legal_sizes=[1, 2, 4],
    )
    a = Autoscaler(cluster, coord_client_factory=lambda job: coord)
    ja = make_job(name="a", mn=1, mx=4, gbs=64)
    cluster.create_trainer_workload(ja)
    a.on_add(ja)
    a.run_once()  # idle cluster: grows to max
    assert cluster.get_trainer_workload(ja).parallelism == 4
    pods = sorted(p.name for p in kube.list_pods() if p.job_name == "a")
    assert len(pods) == 4
    # the four launchers register under their pod names (EDL_POD_NAME)
    for name in pods:
        coord.register(name)
    assert coord.target_world() == 4  # the scale-up handshake landed
    assert coord.plan().world_size == 4

    # a second job's fully-pending pods force a shed (ref findPendingJob)
    jb = make_job(name="b", mn=2, mx=2)
    cluster.create_trainer_workload(jb)
    a.on_add(jb)
    a.run_once()

    w = cluster.get_trainer_workload(ja)
    plan = coord.plan()
    assert w.parallelism == plan.world_size == 2
    survivors = sorted(
        p.name
        for p in kube.list_pods()
        if p.job_name == "a" and not p.deleting
    )
    # Survivors are exactly the plan's members — the adversarial
    # controller never chose a victim, so no active member died.
    assert survivors == sorted(plan.members)
    assert survivors == pods[:2]  # oldest two == coordinator rank order
    # the freed chips let job b schedule
    total, running, pending, _ = cluster.job_pods(jb)
    assert (total, running, pending) == (2, 2, 0)


def test_job_pod_nodes_map_newest_first():
    """job_pod_nodes_map: scheduled pods' nodes, newest pod first (the
    autoscaler's victim-order proxy for JobView.pod_nodes)."""
    kube = FakeKube(tpu_nodes(3, chips=4))
    cluster = Cluster(kube)
    job = make_job(mx=3)
    cluster.create_trainer_workload(job)
    cluster.update_parallelism(job, 3)
    nodes_by_job = cluster.job_pod_nodes_map()
    assert len(nodes_by_job[job.name]) == 3
    # newest pod (highest creation seq) leads the victim list
    pods = sorted(kube.list_pods(), key=lambda p: p.name)
    assert nodes_by_job[job.name][0] == pods[-1].node
    assert nodes_by_job[job.name][-1] == pods[0].node
