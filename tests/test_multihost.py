"""Multi-host slice topologies (VERDICT r3 missing-2): a trainer
replica on v5e-16 is 2 hosts x 8 chips — one replica = one Indexed Job
of ``hosts`` pods, grouped by the coordinator into a single world rank
block.  The reference's trainer Job was a flat pod pool
(``pkg/jobparser.go:115-158``); pod GROUPS are the piece it never had.
"""

import pytest

from edl_tpu.cluster.cluster import Cluster
from edl_tpu.cluster.kube import FakeKube, NodeInfo
from edl_tpu.resource.training_job import TrainingJob
from edl_tpu.runtime.coordinator import LocalCoordinator


def v5e16_job(name="mh", mn=1, mx=2, gbs=0):
    return TrainingJob.from_manifest(
        {
            "apiVersion": "edl.tpu.dev/v1",
            "kind": "TrainingJob",
            "metadata": {"name": name},
            "spec": {
                "fault_tolerant": True,
                "global_batch_size": gbs,
                "trainer": {
                    "entrypoint": "mnist",
                    "min_instance": mn,
                    "max_instance": mx,
                    "slice_topology": "v5e-16",
                    "resources": {"requests": {"cpu": "1", "memory": "1Gi"}},
                },
            },
        }
    ).validate()


# ---- spec helpers -----------------------------------------------------------


def test_hosts_per_replica_and_chips_per_host():
    job = v5e16_job()
    assert job.hosts_per_replica() == 2  # v5e-16 = 16 chips / 8 per host
    assert job.tpu_per_trainer() == 16
    assert job.tpu_per_host() == 8


def test_legal_sizes_quantize_on_full_replica_chips():
    # 32 rows / (w replicas x 16 chips): only w=1 and w=2 divide.
    job = v5e16_job(mn=1, mx=2, gbs=32)
    assert job.legal_world_sizes() == [1, 2]
    with pytest.raises(Exception):
        v5e16_job(gbs=24)  # 24 % 16 != 0 -> endpoints illegal


# ---- jobparser rendering ----------------------------------------------------


def test_multihost_renders_indexed_jobs_and_headless_service():
    from edl_tpu.controller.jobparser import (
        parse_to_trainer,
        parse_to_trainer_manifests,
    )

    job = v5e16_job(mn=2, mx=4)
    with pytest.raises(ValueError):
        parse_to_trainer(job)  # flat Job cannot express pod groups

    ms = parse_to_trainer_manifests(job)
    kinds = [m["kind"] for m in ms]
    assert kinds == ["Service", "Job", "Job"]  # headless + min_instance jobs
    svc = ms[0]
    assert svc["spec"]["clusterIP"] == "None"
    assert svc["metadata"]["name"] == "mh-trainer"

    j0 = ms[1]
    assert j0["metadata"]["name"] == "mh-trainer-0"
    assert j0["spec"]["completionMode"] == "Indexed"
    assert j0["spec"]["completions"] == 2
    assert j0["spec"]["parallelism"] == 2
    tmpl = j0["spec"]["template"]["spec"]
    assert tmpl["subdomain"] == "mh-trainer"
    c = tmpl["containers"][0]
    # per-POD chips are chips-per-host, not the whole replica
    assert c["resources"]["limits"]["google.com/tpu"] == "8"
    env = {e["name"]: e.get("value") for e in c["env"]}
    assert env["EDL_REPLICA"] == "0"
    assert ms[2]["metadata"]["name"] == "mh-trainer-1"


def test_coordinator_command_carries_hosts():
    from edl_tpu.controller.jobparser import parse_to_coordinator

    dep = parse_to_coordinator(v5e16_job())[0]
    cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
    assert "--hosts" in cmd
    assert cmd[cmd.index("--hosts") + 1] == "2"


# ---- coordinator replica grouping ------------------------------------------


def test_incomplete_replica_cannot_form_world():
    coord = LocalCoordinator(
        target_world=2, max_world=2, hosts_per_replica=2
    )
    coord.register("r0h0", address="a:1", replica=0, host=0)
    plan = coord.plan()
    assert plan.world_size == 0  # half a slice is not a trainer
    coord.register("r0h1", address="a:2", replica=0, host=1)
    plan = coord.plan()
    assert plan.world_size == 1
    assert plan.members == ("r0h0", "r0h1")  # replica-major, host-minor
    assert plan.addresses == ("a:1", "a:2")


def test_replica_grouping_rank_order_and_scale():
    coord = LocalCoordinator(
        target_world=1, max_world=2, hosts_per_replica=2
    )
    # registration order deliberately scrambled: rank order must come
    # from (replica, host), not join order
    coord.register("r1h1", replica=1, host=1)
    coord.register("r0h1", replica=0, host=1)
    coord.register("r1h0", replica=1, host=0)
    coord.register("r0h0", replica=0, host=0)
    plan = coord.plan()
    assert plan.world_size == 1
    assert plan.members == ("r0h0", "r0h1")  # lowest replica first

    coord.set_target_world(2)
    plan = coord.plan()
    assert plan.world_size == 2
    assert plan.members == ("r0h0", "r0h1", "r1h0", "r1h1")

    # scale down: the HIGHEST replica drops (matching the actuation,
    # which deletes the highest-indexed per-replica Jobs)
    coord.set_target_world(1)
    plan = coord.plan()
    assert plan.members == ("r0h0", "r0h1")


def test_losing_one_host_drops_the_whole_replica():
    coord = LocalCoordinator(
        target_world=2, max_world=2, hosts_per_replica=2
    )
    for r in (0, 1):
        for h in (0, 1):
            coord.register(f"r{r}h{h}", replica=r, host=h)
    assert coord.plan().world_size == 2
    coord.deregister("r1h0")  # one pod of replica 1 dies
    plan = coord.plan()
    assert plan.world_size == 1
    assert plan.members == ("r0h0", "r0h1")
    # the surviving half of replica 1 re-joins when its peer returns
    coord.register("r1h0", replica=1, host=0)
    assert coord.plan().world_size == 2


def test_rejoin_without_placement_keeps_previous():
    coord = LocalCoordinator(
        target_world=1, max_world=1, hosts_per_replica=2
    )
    coord.register("p", replica=0, host=1)
    coord.register("p")  # heartbeat-path re-register omits placement
    coord.register("q", replica=0, host=0)
    assert coord.plan().members == ("q", "p")


# ---- cluster actuation in whole replicas ------------------------------------


def _slice_nodes(n):
    # n host NODES, paired into v5e-16 slices: nodes 2k and 2k+1 share
    # nodepool "slice-k" (one physical slice), 8 chips per host
    return [
        NodeInfo(
            name=f"host-{i}",
            cpu_milli=16000,
            memory_mega=65536,
            tpu_chips=8,
            tpu_topology="4x4",
            pool=f"slice-{i // 2}",
        )
        for i in range(n)
    ]


def test_cluster_scales_multihost_job_in_whole_jobs():
    kube = FakeKube(_slice_nodes(4))  # room for 2 replicas (4 hosts)
    cluster = Cluster(kube)
    job = v5e16_job(mn=1, mx=2)

    w = cluster.create_trainer_workload(job)
    assert w is not None and w.parallelism == 1  # 1 replica Job
    names = sorted(x.name for x in kube.list_workloads())
    assert names == ["mh-trainer-0"]
    # the replica Job runs `hosts` pods of 8 chips each
    pods = [p for p in kube.list_pods() if p.job_name == "mh"]
    assert len(pods) == 2
    assert all(p.tpu_limit == 8 for p in pods)

    assert cluster.update_parallelism(job, 2)
    assert cluster.get_trainer_workload(job).parallelism == 2
    assert sorted(x.name for x in kube.list_workloads()) == [
        "mh-trainer-0",
        "mh-trainer-1",
    ]
    assert len([p for p in kube.list_pods() if p.job_name == "mh"]) == 4

    # scale down deletes the HIGHEST replica Job (and only its pods)
    assert cluster.update_parallelism(job, 1)
    assert sorted(x.name for x in kube.list_workloads()) == ["mh-trainer-0"]
    left = [p for p in kube.list_pods() if p.job_name == "mh"]
    assert len(left) == 2
    assert all(p.workload == "mh-trainer-0" for p in left)

    assert cluster.delete_trainer_workload(job)
    assert cluster.get_trainer_workload(job) is None
    assert [p for p in kube.list_pods() if p.job_name == "mh"] == []


def test_autoscaler_grows_multihost_job_in_replicas():
    """Closed loop: the autoscaler's decision plane counts replicas
    (the virtual aggregate workload), and its actuation creates whole
    per-replica Jobs on the idle cluster."""
    from edl_tpu.autoscaler.scaler import Autoscaler

    kube = FakeKube(_slice_nodes(4))  # 32 chips = 2 v5e-16 replicas
    cluster = Cluster(kube)
    coord = LocalCoordinator(
        target_world=1, max_world=2, heartbeat_timeout=1e9,
        hosts_per_replica=2,
    )
    a = Autoscaler(cluster, coord_client_factory=lambda job: coord)
    job = v5e16_job(mn=1, mx=2)
    cluster.create_trainer_workload(job)
    a.on_add(job)
    a.run_once()
    assert cluster.get_trainer_workload(job).parallelism == 2
    assert sorted(w.name for w in kube.list_workloads()) == [
        "mh-trainer-0",
        "mh-trainer-1",
    ]
    # the handshake carries the REPLICA count to the coordinator
    assert coord.target_world() == 2


def test_placement_refuses_hosts_across_slices():
    """Free host-nodes on two DIFFERENT slices are not a slice: the dry
    run must not admit a replica whose pods GKE could never co-locate
    (ICI does not span nodepools)."""
    from edl_tpu.autoscaler.algorithm import (
        JobView,
        search_assignable_nodes,
    )

    nodes = _slice_nodes(4)
    kube = FakeKube(nodes)
    r = Cluster(kube).inquiry_resource()
    j = JobView(
        name="mh", min_instance=1, max_instance=2, parallelism=1,
        cpu_request_milli=1000, mem_request_mega=1024,
        tpu_per_trainer=16, slice_topology="v5e-16", hosts=2,
    )
    # both hosts of slice-0 free -> placeable, and on ONE pool
    got = search_assignable_nodes(r, j)
    assert got is not None
    assert {r.nodes.node_pool[n] for n in got} == {"slice-0"}

    # burn one host on each slice: 2 free hosts remain but on different
    # slices -> NOT placeable
    r2 = Cluster(kube).inquiry_resource()
    r2.nodes.tpu_free["host-0"] = 0  # slice-0 half busy
    r2.nodes.tpu_free["host-2"] = 0  # slice-1 half busy
    assert search_assignable_nodes(r2, j) is None

    # nodes without pool identity cannot prove co-location
    r3 = Cluster(FakeKube([
        NodeInfo(name=f"n{i}", cpu_milli=16000, memory_mega=65536,
                 tpu_chips=8, tpu_topology="4x4")
        for i in range(2)
    ])).inquiry_resource()
    assert search_assignable_nodes(r3, j) is None


def test_update_parallelism_keeps_lowest_existing_replicas():
    """Non-contiguous replica indexes (replica 0 externally deleted):
    scale-down must keep the lowest EXISTING replicas — the ones the
    coordinator keeps — not blindly delete every r >= parallelism."""
    kube = FakeKube(_slice_nodes(6))
    cluster = Cluster(kube)
    job = v5e16_job(mn=1, mx=3)
    cluster.create_trainer_workload(job)
    cluster.update_parallelism(job, 3)
    assert sorted(w.name for w in kube.list_workloads()) == [
        "mh-trainer-0", "mh-trainer-1", "mh-trainer-2",
    ]
    kube.delete_workload("mh-trainer-0")  # external deletion / TTL
    assert cluster.get_trainer_workload(job).parallelism == 2
    # scale down to 1: survivor must be mh-trainer-1 (lowest existing)
    assert cluster.update_parallelism(job, 1)
    assert [w.name for w in kube.list_workloads()] == ["mh-trainer-1"]
    # scale back to 2: fills the smallest unused index
    assert cluster.update_parallelism(job, 2)
    assert sorted(w.name for w in kube.list_workloads()) == [
        "mh-trainer-0", "mh-trainer-1",
    ]
