"""Telemetry subsystem: registry bounds, exposition format, coordinator
aggregation idempotence, flight-recorder determinism, and the goodput
feedback loop into the autoscaler's decision log (ISSUE 6).

The headline test re-runs a seeded chaos soak twice and asserts the
flight recorder's digest is identical AND that the soak is fully
reconstructible from the journal alone: every chaos injection, every
retry, every resize (including the corruption-triggered degrade), and
every checkpoint save appears as a stamped event.
"""

import time

import optax
import pytest

from edl_tpu import telemetry
from edl_tpu.autoscaler.scaler import Autoscaler
from edl_tpu.chaos import (
    ChaosCoordinator,
    ChaosHTTPCoordinator,
    ChaosMonkey,
    FaultEvent,
    FaultSchedule,
)
from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.cluster.cluster import Cluster
from edl_tpu.cluster.kube import FakeKube, NodeInfo
from edl_tpu.models import get_model
from edl_tpu.resource.training_job import TrainingJob
from edl_tpu.runtime.coord_service import CoordinatorServer, HTTPCoordinator
from edl_tpu.runtime.coordinator import LocalCoordinator
from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.telemetry import (
    FlightRecorder,
    MetricsRegistry,
    merge_snapshots,
    render_prometheus,
)


# ---- registry: bucket + cardinality bounds ----------------------------------


def test_histogram_bucket_assignment_and_bounds():
    reg = MetricsRegistry()
    h = reg.histogram("edl_step_seconds")
    h.observe(0.001)   # == first bound: inclusive (v <= le)
    h.observe(0.0011)  # second bucket
    h.observe(500.0)   # beyond every bound: +Inf only
    s = h.series()
    assert s["count"] == 3
    assert s["sum"] == pytest.approx(500.0021)
    assert s["counts"][0] == 1
    assert s["counts"][1] == 1
    assert s["counts"][-1] == 1  # the +Inf bucket
    assert len(s["counts"]) == len(s["buckets"]) + 1
    # constant memory: 10k observations change no structure
    for i in range(10_000):
        h.observe(i * 0.01)
    s2 = h.series()
    assert len(s2["counts"]) == len(s["counts"])
    assert s2["count"] == 10_003


def test_label_cardinality_bounded_with_overflow_series():
    reg = MetricsRegistry(max_label_sets=4)
    h = reg.histogram("edl_resize_phase_seconds")
    for i in range(10):
        h.observe(0.01, phase=f"p{i}")
    series = reg.snapshot()["histograms"]["edl_resize_phase_seconds"]
    # 4 real series + ONE overflow series, never 10
    assert len(series) == 5
    assert "overflow=true" in series
    # nothing was dropped: the overflow series absorbed the tail
    assert sum(s["count"] for s in series.values()) == 10
    assert series["overflow=true"]["count"] == 6

    c = reg.counter("edl_chaos_injections_total")
    for i in range(10):
        c.inc(point=f"pt{i}")
    cseries = reg.snapshot()["counters"]["edl_chaos_injections_total"]
    assert len(cseries) == 5
    assert cseries["overflow=true"] == 6


def test_strict_registry_rejects_uncataloged_and_mistyped():
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="not in the catalog"):
        reg.counter("edl_totally_made_up")
    with pytest.raises(ValueError, match="cataloged as"):
        reg.counter("edl_step_seconds")  # declared histogram
    with pytest.raises(ValueError, match="does not declare label"):
        reg.counter("edl_steps_total").inc(bogus="x")
    # non-strict (test/scratch) registries admit anything
    loose = MetricsRegistry(strict=False)
    loose.counter("edl_totally_made_up").inc()
    assert loose.counter("edl_totally_made_up").value() == 1


# ---- prometheus exposition format -------------------------------------------


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.counter("edl_steps_total").inc(3)
    reg.gauge("edl_world_size").set(4)
    h = reg.histogram("edl_resize_phase_seconds")
    h.observe(0.004, phase="flush")
    h.observe(0.2, phase="flush")
    text = reg.render()
    lines = text.splitlines()
    assert "# TYPE edl_steps_total counter" in lines
    assert "edl_steps_total 3" in lines
    assert "# TYPE edl_world_size gauge" in lines
    assert "edl_world_size 4" in lines
    assert "# TYPE edl_resize_phase_seconds histogram" in lines
    # HELP strings come from the catalog
    assert any(
        ln.startswith("# HELP edl_steps_total ") for ln in lines
    )
    # bucket counts are CUMULATIVE and end at +Inf == _count
    buckets = [
        ln for ln in lines if ln.startswith("edl_resize_phase_seconds_bucket")
    ]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts)
    assert buckets[-1].startswith(
        'edl_resize_phase_seconds_bucket{le="+Inf",phase="flush"}'
    ) or buckets[-1].startswith(
        'edl_resize_phase_seconds_bucket{phase="flush",le="+Inf"}'
    )
    assert counts[-1] == 2
    assert 'edl_resize_phase_seconds_sum{phase="flush"} 0.204' in text
    assert 'edl_resize_phase_seconds_count{phase="flush"} 2' in text


# ---- merge + coordinator-side aggregation -----------------------------------


def _snap(steps: float, resize_s: float = 0.0) -> dict:
    reg = MetricsRegistry()
    reg.counter("edl_steps_total").inc(steps)
    if resize_s:
        reg.histogram("edl_resize_seconds").observe(resize_s)
    return reg.snapshot()


def test_merge_snapshots_sums_counters_and_histograms():
    a, b = _snap(10, 0.5), _snap(5, 1.5)
    m = merge_snapshots([a, b])
    assert m["counters"]["edl_steps_total"][""] == 15
    h = m["histograms"]["edl_resize_seconds"][""]
    assert h["count"] == 2 and h["sum"] == pytest.approx(2.0)
    # render of a merged snapshot is still valid exposition
    assert "edl_steps_total 15" in render_prometheus(m)


def test_telemetry_merge_idempotent_across_coordinator_restart():
    """The delta-merge contract: trainers report CUMULATIVE snapshots
    keyed by (trainer, seq), so (a) re-delivery and stale re-ordering
    change nothing, and (b) a restarted coordinator reconverges to the
    exact pre-restart merge from each trainer's next report."""
    fake = [0.0]

    def clock():
        return fake[0]

    snap_a, snap_b = _snap(100, 0.25), _snap(60)
    coord = LocalCoordinator(target_world=1, clock=clock)
    coord.report_telemetry("a", snapshot=snap_a, seq=3)
    fake[0] = 10.0
    coord.report_telemetry("b", snapshot=snap_b, seq=7)
    merged = coord.telemetry()["merged"]
    assert merged["counters"]["edl_steps_total"][""] == 160

    # idempotence: duplicate and stale deliveries are no-ops
    coord.report_telemetry("a", snapshot=snap_a, seq=3)
    coord.report_telemetry("a", snapshot=_snap(1), seq=2)  # stale seq
    assert coord.telemetry()["merged"] == merged
    assert coord.telemetry()["resize_cost_seconds"] == pytest.approx(0.25)

    # restart: all aggregator state lost...
    coord2 = LocalCoordinator(target_world=1, clock=clock)
    assert coord2.telemetry()["merged"] == {
        "counters": {},
        "gauges": {},
        "histograms": {},
    }
    # ...and the trainers' next CUMULATIVE reports rebuild it exactly
    coord2.report_telemetry("a", snapshot=snap_a, seq=4)
    fake[0] = 20.0
    coord2.report_telemetry("b", snapshot=snap_b, seq=8)
    assert coord2.telemetry()["merged"] == merged


def test_restarted_trainer_fresh_boot_supersedes_old_high_seq():
    """A restarted trainer restarts its seq stream at 1 under a fresh
    boot nonce — the aggregator must accept it immediately instead of
    rejecting reports until the new seq outruns the dead incarnation's
    (hours of frozen telemetry otherwise)."""
    coord = LocalCoordinator(target_world=1)
    coord.report_telemetry("a", snapshot=_snap(5000), seq=720, boot="b1")
    assert coord.telemetry()["merged"]["counters"]["edl_steps_total"][
        ""
    ] == 5000
    # same boot, stale seq: rejected (idempotence)
    coord.report_telemetry("a", snapshot=_snap(1), seq=3, boot="b1")
    assert coord.telemetry()["merged"]["counters"]["edl_steps_total"][
        ""
    ] == 5000
    # NEW boot, low seq: the restarted process wins outright
    coord.report_telemetry("a", snapshot=_snap(7), seq=1, boot="b2")
    assert coord.telemetry()["merged"]["counters"]["edl_steps_total"][
        ""
    ] == 7


def test_step_rate_derived_from_report_points():
    fake = [0.0]
    coord = LocalCoordinator(target_world=1, clock=lambda: fake[0])
    coord.report_telemetry("a", snapshot=_snap(100), seq=1)
    fake[0] = 10.0
    coord.report_telemetry("a", snapshot=_snap(200), seq=2)
    assert coord.telemetry()["step_rate"] == pytest.approx(10.0)


# ---- coord_service: registry-backed /metrics + /telemetry -------------------


def test_http_metrics_prometheus_default_and_json_fallback():
    coord = LocalCoordinator(target_world=2, max_world=4)
    coord.register("a")
    coord.register("b")
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start(
        evict=False
    )
    try:
        client = HTTPCoordinator(f"127.0.0.1:{server.port}")
        # trainer-side telemetry report over the wire
        client.report_telemetry(
            "a",
            snapshot=_snap(42, 0.3),
            seq=1,
            events=[
                {
                    "kind": "resize",
                    "step": 5,
                    "generation": 2,
                    "data": {"world_size": 2},
                }
            ],
        )
        # default GET /metrics: Prometheus text, coordinator gauges +
        # merged trainer counters on one exposition surface
        text = client.metrics_text()
        assert "# TYPE edl_generation gauge" in text
        assert "# TYPE edl_members gauge" in text
        assert "edl_members 2" in text
        assert "edl_steps_total 42" in text
        # ?format=json keeps the pre-telemetry dict shape
        snap = client.metrics()
        assert snap["members"] == 2
        assert "generation" in snap and "world_size" in snap
        assert client.completed() is False
        # GET /telemetry: the merged doc + the ingested event tail
        tel = client.telemetry()
        assert tel["merged"]["counters"]["edl_steps_total"][""] == 42
        assert tel["sources"] == {"a": 1}
        kinds = [e["kind"] for e in tel["events"]]
        assert "resize" in kinds  # the trainer's piggybacked event
        assert "coord.plan" in kinds  # the coordinator's own journal
        resize_ev = next(e for e in tel["events"] if e["kind"] == "resize")
        assert resize_ev["data"]["origin"] == "a"
    finally:
        server.stop()


def test_elastic_trainer_reports_telemetry_on_heartbeat_cadence():
    with telemetry.scoped():
        model = get_model("fit_a_line")
        ds = synthetic_dataset(model.synth_batch, 256, seed=0)
        it = ShardedDataIterator(ds, global_batch_size=32, seed=0)
        coord = LocalCoordinator(
            target_world=1, max_world=1, heartbeat_timeout=1e9
        )
        coord.register("tr0")
        et = ElasticTrainer(
            model, optax.adam(1e-2), it, coord, checkpoint_interval=0, seed=0
        )
        et.heartbeat_ids = ["tr0"]
        et.heartbeat_interval = 0.0  # bg thread beats/reports ~50ms
        et.telemetry_interval = 1e-9
        et.run(5)
        # The report rides the heartbeat BACKGROUND thread (never the
        # step loop's poll->dispatch window): wait for it to land.
        def steps_reported():
            m = coord.telemetry()["merged"]
            return (m["counters"].get("edl_steps_total") or {}).get("", 0)

        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and steps_reported() < 5:
            time.sleep(0.02)
        et.stop_heartbeat()
        et.store.wait()
        assert coord.telemetry()["sources"].get("tr0", 0) >= 1
        assert steps_reported() >= 5


# ---- spans: one name for traces and metrics ---------------------------------


def test_span_observes_histogram_under_trace_name():
    with telemetry.scoped() as (reg, _):
        with telemetry.span("resize/unit_test_phase"):
            time.sleep(0.01)
        s = reg.histogram("edl_span_seconds").series(
            span="resize/unit_test_phase"
        )
        assert s is not None and s["count"] == 1
        assert s["sum"] >= 0.009


# ---- flight recorder: ring, spill, determinism ------------------------------


def test_flight_recorder_ring_spill_and_digest(tmp_path):
    spill = tmp_path / "fr.jsonl"
    rec = FlightRecorder(capacity=3, spill_path=str(spill))
    rec.set_context(7, 2)
    for i in range(5):
        rec.record("chaos", {"i": i}, timing={"seconds": 0.1 * i})
    evs = rec.events()
    assert len(evs) == 3  # ring bound
    assert [e.data["i"] for e in evs] == [2, 3, 4]
    assert all(e.step == 7 and e.generation == 2 for e in evs)
    # the spill kept ALL 5 (it outlives the ring)
    import json as _json

    lines = [
        _json.loads(ln) for ln in spill.read_text().splitlines() if ln
    ]
    assert len(lines) == 5
    assert lines[4]["timing"]["seconds"] == pytest.approx(0.4)

    # digest ignores wall/timing and record ORDER, not content
    a, b = FlightRecorder(), FlightRecorder()
    a.record("x", {"k": 1}, step=1, generation=0, timing={"seconds": 9})
    a.record("y", {"k": 2}, step=2, generation=0)
    b.record("y", {"k": 2}, step=2, generation=0)
    b.record("x", {"k": 1}, step=1, generation=0, timing={"seconds": 1})
    assert a.digest() == b.digest()
    b.record("z", {}, step=3, generation=0)
    assert a.digest() != b.digest()


# ---- the chaos-soak acceptance: reconstruct the run from the journal --------


def _soak_once(seed: int):
    """A ~100-step chaos soak over the real HTTP transport, inside a
    scoped telemetry world.  Returns everything the reconstruction and
    determinism assertions need."""
    events = [
        FaultEvent(15, "member.restart", "tr2"),
        FaultEvent(15, "member.restart", "tr3"),
        FaultEvent(15, "scale.target", 4),
        FaultEvent(30, "transport.refuse", 2),
        FaultEvent(40, "member.kill", "tr3"),
        FaultEvent(45, "checkpoint.corrupt"),
        FaultEvent(47, "member.die_with_state", "tr2"),
        FaultEvent(70, "scale.target", 2),
    ]
    with telemetry.scoped() as (reg, rec):
        schedule = FaultSchedule(seed, events)
        model = get_model("fit_a_line")
        ds = synthetic_dataset(model.synth_batch, 512, seed=0)
        it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
        inner = LocalCoordinator(
            target_world=2,
            max_world=4,
            legal_sizes=[1, 2, 4],
            heartbeat_timeout=1e9,
        )
        coord = ChaosCoordinator(inner, schedule)
        coord.register("tr0")
        coord.register("tr1")
        server = CoordinatorServer(coord, host="127.0.0.1", port=0).start(
            evict=False
        )
        try:
            client = ChaosHTTPCoordinator(
                f"127.0.0.1:{server.port}",
                schedule,
                timeout=10.0,
                retries=5,
                retry_base_delay=0.02,
            )
            store = HostDRAMStore(keep=3, chaos=schedule)
            et = ElasticTrainer(
                model,
                optax.adam(1e-2),
                it,
                client,
                store=store,
                checkpoint_interval=10,
                seed=0,
            )
            monkey = ChaosMonkey(
                schedule, et, coordinator=coord, store=store
            ).track(["tr0", "tr1"])
            et.run(100, on_step=monkey.on_step)
            store.wait()
            return {
                "digest": rec.digest(),
                "journal": [e.to_dict() for e in rec.events()],
                "fired": [(e.step, e.point) for e in schedule.fired()],
                "resizes": [
                    (
                        e.generation,
                        e.world_size,
                        e.restored_step,
                        e.replayed_steps,
                        e.graceful,
                        e.restore_source,
                    )
                    for e in et.resize_events
                ],
                "chaos_counts": reg.snapshot()["counters"].get(
                    "edl_chaos_injections_total", {}
                ),
                "retries": reg.counter("edl_retry_attempts_total").value(
                    op="coordinator request"
                ),
                "pending": schedule.pending(),
            }
        finally:
            server.stop()


@pytest.mark.chaos
def test_chaos_soak_reconstructible_from_flight_recorder_alone():
    """Acceptance: every injection, retry, resize, and degrade of a
    seeded chaos soak appears as a stamped flight-recorder event — and
    the journal digest is bit-identical across same-seed runs."""
    r = _soak_once(seed=4321)
    assert r["pending"] == []
    journal = r["journal"]

    # 1. every delivered chaos injection is journaled with its point
    chaos_evs = [e for e in journal if e["kind"] == "chaos"]
    assert sorted(
        (e["data"]["scheduled_step"], e["data"]["point"]) for e in chaos_evs
    ) == sorted(r["fired"])
    # ...and counted on the shared registry
    assert sum(r["chaos_counts"].values()) == len(r["fired"])

    # 2. every resize barrier is journaled with its full outcome
    resize_evs = [e for e in journal if e["kind"] == "resize"]
    assert [
        (
            e["generation"],
            e["data"]["world_size"],
            e["data"]["restored_step"],
            e["data"]["replayed_steps"],
            e["data"]["graceful"],
            e["data"]["restore_source"],
        )
        for e in resize_evs
    ] == r["resizes"]

    # 3. the corruption-triggered DEGRADE is visible: a non-graceful
    # resize restored an older snapshot and replayed
    assert any(
        not e["data"]["graceful"] and e["data"]["replayed_steps"] > 0
        for e in resize_evs
    )

    # 4. the transport.refuse storm's absorbed retries are journaled
    retry_evs = [e for e in journal if e["kind"] == "retry"]
    assert len(retry_evs) >= 2
    assert r["retries"] >= 2

    # 5. every interval checkpoint save is journaled at its step
    save_steps = {
        e["data"]["step"]
        for e in journal
        if e["kind"] == "checkpoint.save" and e["data"]["kind"] == "async"
    }
    assert {10, 20, 100} <= save_steps

    # determinism: an identical-seed soak produces the identical journal
    r2 = _soak_once(seed=4321)
    assert r2["digest"] == r["digest"]
    assert [
        (e["step"], e["generation"], e["kind"], e["data"])
        for e in r2["journal"]
    ] == [
        (e["step"], e["generation"], e["kind"], e["data"])
        for e in journal
    ]
    # a different seed reorders retry jitter but not the fault plan;
    # the journal identity must still match (same schedule, same run)
    r3 = _soak_once(seed=9)
    assert r3["digest"] == r["digest"]


# ---- goodput feedback into the autoscaler decision log ----------------------


def _tpu_nodes(n=4, chips=4):
    return [
        NodeInfo(
            name=f"pool-{i}",
            cpu_milli=8000,
            memory_mega=32768,
            tpu_chips=chips,
            tpu_topology=f"v5e-{chips}",
        )
        for i in range(n)
    ]


def _elastic_job(name="jg", mn=1, mx=4):
    return TrainingJob.from_manifest(
        {
            "apiVersion": "edl.tpu.dev/v1",
            "kind": "TrainingJob",
            "metadata": {"name": name},
            "spec": {
                "fault_tolerant": True,
                "trainer": {
                    "min_instance": mn,
                    "max_instance": mx,
                    "slice_topology": "v5e-4",
                    "resources": {
                        "requests": {"cpu": "1", "memory": "1Gi"}
                    },
                },
            },
        }
    ).validate()


def test_autoscaler_decision_log_shows_observed_goodput():
    """One tick's decision log carries the dry-run trace AND the
    observed step-rate / resize-cost read from the job coordinator's
    merged trainer telemetry (the acceptance criterion's 'observed
    step-rate feeding the dry-run')."""
    kube = FakeKube(_tpu_nodes(4))
    cluster = Cluster(kube)
    job = _elastic_job()
    cluster.create_trainer_workload(job)

    fake = [0.0]
    coord = LocalCoordinator(
        target_world=1, max_world=4, clock=lambda: fake[0]
    )
    # two trainer reports 10s apart: observed rate = 10 steps/s
    coord.report_telemetry("t0", snapshot=_snap(100, 0.5), seq=1)
    fake[0] = 10.0
    coord.report_telemetry("t0", snapshot=_snap(200, 0.5), seq=2)

    asc = Autoscaler(cluster, coord_client_factory=lambda j: coord)
    asc.jobs[job.name] = job
    plan = asc.run_once()
    assert plan is not None and plan.decisions
    d = next(e for e in plan.decisions if e["job"] == job.name)
    assert d["observed"]["step_rate"] == pytest.approx(10.0)
    assert d["observed"]["resize_cost_seconds"] == pytest.approx(0.5)
    assert d["observed"]["steps_total"] == 200
    assert d["dry_run"]["current"] == 1
    assert d["dry_run"]["proposed"] == d["dry_run"]["current"] + d[
        "dry_run"
    ]["diff"]
    assert d["reason"]
    assert d["actuated"] == (d["dry_run"]["diff"] != 0)
    assert asc.decision_log[-len(plan.decisions):] == plan.decisions


def test_decision_log_reports_put_giveup_as_not_actuated():
    """The decision log must report what actually happened: a PUT that
    gave up under a conflict storm journals actuated=False with the
    give-up in the reason — not the dry run's optimistic plan."""
    from edl_tpu.chaos import ChaosKube
    from edl_tpu.utils.retry import RetryPolicy

    kube = FakeKube(_tpu_nodes(4))
    sched = FaultSchedule(0, [FaultEvent(0, "kube.conflict", 50)])
    sched.advance(0)
    cluster = Cluster(
        ChaosKube(kube, sched),
        conflict_retry=RetryPolicy(max_attempts=2, base_delay=0.0),
    )
    job = _elastic_job(name="jq")
    cluster.create_trainer_workload(job)
    coord = LocalCoordinator(target_world=1, max_world=4)
    asc = Autoscaler(cluster, coord_client_factory=lambda j: coord)
    asc.jobs[job.name] = job
    plan = asc.run_once()
    d = next(e for e in plan.decisions if e["job"] == "jq")
    assert d["dry_run"]["diff"] > 0  # the dry run DID want to scale up
    assert d["actuated"] is False    # ...but the PUT never landed
    assert "gave up" in d["reason"]


def test_autoscaler_decision_log_tolerates_unreachable_coordinator():
    kube = FakeKube(_tpu_nodes(2))
    cluster = Cluster(kube)
    job = _elastic_job(name="ju")
    cluster.create_trainer_workload(job)

    class Dead:
        def telemetry(self):
            raise ConnectionError("nope")

        def set_target_world(self, n):
            pass

        def set_prewarm(self, n):
            pass

        def plan(self):
            return None

        def members(self):
            return []

    asc = Autoscaler(cluster, coord_client_factory=lambda j: Dead())
    asc.jobs[job.name] = job
    plan = asc.run_once()
    assert plan is not None and plan.decisions
    d = plan.decisions[0]
    assert d["observed"] == {}  # best-effort: logged without data
    # the failure memo keeps later ticks cheap (no re-probe this tick)
    assert asc._goodput_failed_tick[job.name] == 1
