"""Asynchronous steady-state step pipeline tests (ISSUE 7).

The pipeline overlaps batch staging with device compute and defers the
per-step host<->device metrics sync behind a bounded lag.  Everything
here asserts ONE invariant from different angles: the pipeline changes
WHEN work happens, never WHAT is computed — the loss/metric stream is
bit-identical with the pipeline on or off, through resizes, replays,
and chaos-injected stager faults.
"""

import numpy as np
import optax
import pytest

from edl_tpu.models import get_model
from edl_tpu.runtime import ShardedDataIterator
from edl_tpu.runtime.coordinator import LocalCoordinator
from edl_tpu.runtime.data import BatchStager, synthetic_dataset
from edl_tpu.runtime.elastic import ElasticTrainer


def make_world(
    target_world=2, n_trainers=2, ckpt_interval=5, seed=0, depth=2
):
    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 512, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
    coord = LocalCoordinator(target_world=target_world, max_world=8)
    for i in range(n_trainers):
        coord.register(f"tr{i}")
    et = ElasticTrainer(
        model,
        optax.adam(1e-2),
        it,
        coord,
        checkpoint_interval=ckpt_interval,
        seed=seed,
    )
    et.pipeline_depth = depth
    return et, coord


def _stream(hist):
    return [(r.step, r.loss) for r in hist]


# ---- bit-identical loss stream ---------------------------------------------


def test_loss_stream_bit_identical_pipeline_on_vs_off():
    """The core determinism claim: the EXACT float stream (not merely
    allclose) is invariant to the pipeline — batches are a pure
    function of (seed, step) and harvesting only defers reads."""
    sync, _ = make_world(depth=0)
    pipe, _ = make_world(depth=2)
    s_hist = sync.run(20)
    p_hist = pipe.run(20)
    assert _stream(s_hist) == _stream(p_hist)
    # the pipelined run actually ran ahead (it was not secretly sync)
    assert pipe.pipeline_stats["max_in_flight"] == 2
    assert sync.pipeline_stats["max_in_flight"] == 0


def test_loss_stream_bit_identical_across_midrun_resize():
    """Same claim across a 2 -> 4 growth resize: the barrier-entry
    drain confirms every in-flight step before the world changes, so
    records, step order, and losses match the synchronous mode."""
    runs = {}
    for depth in (0, 2):
        et, coord = make_world(target_world=2, n_trainers=4, depth=depth)
        et.run(10)
        coord.set_target_world(4)
        runs[depth] = (et, et.run(20))
    assert _stream(runs[0][1]) == _stream(runs[2][1])
    for et, hist in runs.values():
        assert hist[9].world_size == 2 and hist[10].world_size == 4
        grow = et.resize_events[-1]
        # the drain ran BEFORE the flush: no steps lost, none replayed
        assert grow.graceful and grow.replayed_steps == 0


def test_loss_stream_bit_identical_across_replay_after_kill():
    """Replay after a death-with-state-loss: both modes restore the
    step-5 interval checkpoint and replay the same steps with the same
    losses (the history contains the pre-kill and replayed records in
    the same order)."""
    streams = {}
    for depth in (0, 2):
        et, coord = make_world(ckpt_interval=5, depth=depth)
        et.run(8)
        et.store.wait()
        et.inject_failure()  # device state gone; pipeline discarded
        coord.deregister("tr1")  # failure detection evicts the peer
        hist = et.run(14)
        ev = et.resize_events[-1]
        assert not ev.graceful and ev.restored_step == 5
        assert ev.replayed_steps == 3
        streams[depth] = _stream(hist)
    assert streams[0] == streams[2]


def test_chaos_seeded_stager_faults_keep_stream_identical():
    """chaos[stage.batch.slow] / chaos[stage.batch.failed]: a stalled
    or dying background stager degrades to synchronous staging — same
    losses, no lost steps, and the failure is visible in the stager's
    accounting rather than the run's output."""
    from edl_tpu.chaos.schedule import FaultEvent, FaultSchedule

    ref, _ = make_world(depth=2)
    ref_hist = ref.run(16)

    schedule = FaultSchedule(
        seed=7,
        events=[
            FaultEvent(3, "stage.batch.slow", 0.05),
            FaultEvent(6, "stage.batch.failed"),
        ],
    )
    et, _ = make_world(depth=2)
    et.store.chaos = schedule  # the stager reads store.chaos
    # the chaos clock normally advances via ChaosMonkey.on_step; this
    # test only needs stager events, so drive it directly
    hist = et.run(16, on_step=lambda r: schedule.advance(r.step))
    assert _stream(hist) == _stream(ref_hist)
    assert schedule.pending() == []
    assert et._stager.stats["failures"] >= 1


def test_pipeline_drains_at_checkpoint_interval_and_run_exit():
    """Sanctioned sync points: at every interval save and at run exit
    the in-flight queue is empty, so a checkpoint can never capture a
    state whose confirming metrics are still in flight."""
    et, _ = make_world(ckpt_interval=4, depth=2)
    seen = []

    def on_step(rec):
        done = rec.step + 1
        if done % 4 == 0:
            # the interval-save drain harvests THIS step before the
            # save; nothing newer may be pending at that moment
            seen.append(len(et._pending))

    et.run(12, on_step=on_step)
    assert seen and all(n == 0 for n in seen)
    assert len(et._pending) == 0
    assert [r.step for r in et.history] == list(range(12))


def test_host_step_counter_retires_device_fetch(monkeypatch):
    """The hot loop must not fetch state.step from the device: poison
    the device counter's __int__ path and the loop must still step
    correctly from its host-side counter."""
    et, _ = make_world(depth=2)
    et.run(6)  # host counter live after the initial resize

    impl = type(et.state.step)  # the concrete ArrayImpl class

    def boom(self):
        raise AssertionError("hot loop fetched a device scalar via int()")

    monkeypatch.setattr(impl, "__int__", boom)
    try:
        et.run(10)
    finally:
        monkeypatch.undo()
    assert [r.step for r in et.history] == list(range(10))


# ---- BatchStager unit tests -------------------------------------------------


@pytest.fixture
def mesh1():
    from edl_tpu.parallel.mesh import dp_mesh

    return dp_mesh(1)


def test_stager_epoch_boundary_determinism(mesh1):
    """Prefetch across an epoch boundary yields exactly the batches the
    synchronous path builds: the (seed, epoch) reshuffle is a pure
    function, so staging ahead into the next epoch changes nothing."""
    ds = {"x": np.arange(128, dtype=np.float32)[:, None]}
    it = ShardedDataIterator(ds, global_batch_size=32, seed=3)
    assert it.batches_per_epoch == 4
    stager = BatchStager(it, depth=3)
    stager.rebind(mesh1, key=0)
    # steps 2..6 cross the epoch-1 boundary at step 4
    for step in range(2, 7):
        got = stager.get(step)
        want = it.device_batch(step, mesh1)
        np.testing.assert_array_equal(
            np.asarray(got["x"]), np.asarray(want["x"])
        )


def test_stager_rebind_invalidates_staged_batches(mesh1):
    ds = {"x": np.arange(64, dtype=np.float32)[:, None]}
    it = ShardedDataIterator(ds, global_batch_size=16, seed=0)
    stager = BatchStager(it, depth=2)
    stager.rebind(mesh1, key=1)
    stager.get(0)  # schedules 1, 2
    stager.rebind(mesh1, key=2)  # a resize: staged batches must drop
    with stager._cv:
        assert stager._ready == {} and not stager._queue
    # and the stager still serves correctly under the new key
    got = stager.get(1)
    np.testing.assert_array_equal(
        np.asarray(got["x"]), np.asarray(it.device_batch(1, mesh1)["x"])
    )


def test_stager_worker_failure_falls_back_synchronously(mesh1, monkeypatch):
    """A worker that dies on every build must not lose steps or hang
    the consumer: get() falls back to building inline."""
    ds = {"x": np.arange(64, dtype=np.float32)[:, None]}
    it = ShardedDataIterator(ds, global_batch_size=16, seed=0)
    stager = BatchStager(it, depth=2)
    stager.rebind(mesh1, key=1)

    real = it.device_batch
    calls = {"n": 0}

    def flaky(step, mesh, batch_axes=("dp",)):
        import threading

        if threading.current_thread().name == "edl-batch-stager":
            calls["n"] += 1
            raise RuntimeError("worker build failed")
        return real(step, mesh, batch_axes=batch_axes)

    monkeypatch.setattr(it, "device_batch", flaky)
    for step in range(4):
        got = stager.get(step)
        np.testing.assert_array_equal(
            np.asarray(got["x"]), np.asarray(real(step, mesh1)["x"])
        )
    assert stager.stats["failures"] >= 1
    assert stager.stats["hits"] == 0


# ---- lint gate: the per-step sync cannot silently regress ------------------


def test_lint_rejects_blocking_fetch_in_hot_loop(tmp_path):
    """tools/lint.py must reject float()/int()/.item() device syncs
    inside ElasticTrainer.run unless the line carries the
    sanctioned-sync marker."""
    import sys

    sys.path.insert(0, "tools")
    try:
        import lint
    finally:
        sys.path.pop(0)

    bad = tmp_path / "elastic.py"
    bad.write_text(
        "class ElasticTrainer:\n"
        "    def run(self, n):\n"
        "        loss = float(self.metrics['loss'])\n"
        "        step = int(self.state.step)\n"
        "        x = self.arr.item()\n"
        "    def other(self):\n"
        "        return float(1)\n"  # outside the hot loop: allowed
    )
    findings = [msg for _, msg in lint.lint_file(bad)]
    assert sum("blocking device fetch" in m for m in findings) == 3

    ok = tmp_path / "elastic_ok.py"
    ok.write_text(
        "class ElasticTrainer:\n"
        "    def run(self, n):\n"
        "        loss = float(self.m['loss'])  # sanctioned-sync\n"
    )
    assert [m for _, m in lint.lint_file(ok) if "blocking" in m] == []

    # the REAL hot loop passes its own gate (regression canary)
    from pathlib import Path

    real = Path("edl_tpu/runtime/elastic.py")
    assert [m for _, m in lint.lint_file(real) if "blocking" in m] == []
