"""Chaos suite: deterministic fault injection across the four layers.

The tentpole robustness harness (ISSUE 1): a seeded ``FaultSchedule``
drives named injection points through coordinator membership, the
coord_service HTTP transport, the checkpoint store, and kube
actuation.  The headline test is the ~200-step soak — kills, scale
events, dropped RPCs, and one corrupted checkpoint, run TWICE with the
same seed and asserted bit-identical (final-state CRC digest, full
loss history, resize sequence).  The longer multi-cycle soak is gated
behind ``-m slow`` so the tier-1 budget holds.
"""

import os
import socket
import time

import numpy as np
import optax
import pytest

from edl_tpu.autoscaler.scaler import Autoscaler
from edl_tpu.chaos import (
    ChaosCoordinator,
    ChaosHTTPCoordinator,
    ChaosKube,
    ChaosMonkey,
    FaultEvent,
    FaultSchedule,
    corrupt_newest,
)
from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.cluster.cluster import Cluster, ParallelismUpdateError
from edl_tpu.cluster.kube import FakeKube, NodeInfo
from edl_tpu.controller.coordclient import make_coord_client
from edl_tpu.models import get_model
from edl_tpu.parallel import dp_mesh
from edl_tpu.resource.training_job import TrainingJob
from edl_tpu.runtime import ShardedDataIterator, Trainer
from edl_tpu.runtime.coord_service import CoordinatorServer, HTTPCoordinator
from edl_tpu.runtime.coordinator import LocalCoordinator
from edl_tpu.runtime.data import synthetic_dataset
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.utils.retry import GiveUpError, RetryPolicy

pytestmark = pytest.mark.chaos


# ---- helpers ----------------------------------------------------------------


def tpu_nodes(n=4, chips=4, cpu=8000, mem=32768):
    return [
        NodeInfo(
            name=f"pool-{i}",
            cpu_milli=cpu,
            memory_mega=mem,
            tpu_chips=chips,
            tpu_topology=f"v5e-{chips}",
        )
        for i in range(n)
    ]


def make_job(name="j", mn=1, mx=4):
    return TrainingJob.from_manifest(
        {
            "apiVersion": "edl.tpu.dev/v1",
            "kind": "TrainingJob",
            "metadata": {"name": name},
            "spec": {
                "fault_tolerant": mn < mx,
                "trainer": {
                    "min_instance": mn,
                    "max_instance": mx,
                    "slice_topology": "v5e-4",
                    "resources": {
                        "requests": {"cpu": "1", "memory": "1Gi"}
                    },
                },
            },
        }
    ).validate()


def _closed_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _trained_state(steps=3, mesh_size=2):
    """A real TrainState a few steps in (checkpoint-layer fixture)."""
    model = get_model("fit_a_line")
    mesh = dp_mesh(mesh_size)
    tr = Trainer(model, optax.adam(1e-2), mesh, seed=0)
    state = tr.init_state()
    ds = synthetic_dataset(model.synth_batch, 128, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=32, seed=0)
    for s in range(steps):
        state, _ = tr.step(state, it.device_batch(s, mesh))
    return model, mesh, tr, it, state


# ---- FaultSchedule core -----------------------------------------------------


def test_fault_schedule_one_shot_ordering_and_strictness():
    ev = [
        FaultEvent(5, "member.kill", "b"),
        FaultEvent(3, "member.kill", "a"),
        FaultEvent(3, "scale.target", 4),
    ]
    s = FaultSchedule(0, ev)
    assert s.due("member.kill") == []  # clock at -1: nothing due
    s.advance(3)
    hits = s.due("member.kill")
    assert [e.arg for e in hits] == ["a"]
    assert s.due("member.kill") == []  # one-shot
    assert [e.arg for e in s.due("scale.target")] == [4]
    s.advance(9)
    assert [e.arg for e in s.due("member.kill")] == ["b"]
    assert s.pending() == []
    assert len(s.fired()) == 3
    with pytest.raises(ValueError, match="unknown injection point"):
        FaultSchedule(0, [FaultEvent(0, "not.a.point")])


def test_fault_schedule_rolls_are_seed_deterministic():
    a = FaultSchedule(7)
    b = FaultSchedule(7)
    c = FaultSchedule(8)
    seq_a = [a.roll("transport.refuse", 0.3) for _ in range(64)]
    seq_b = [b.roll("transport.refuse", 0.3) for _ in range(64)]
    seq_c = [c.roll("transport.refuse", 0.3) for _ in range(64)]
    assert seq_a == seq_b
    assert seq_a != seq_c  # different seed, different stream
    # distinct points draw from distinct streams
    assert seq_a != [a.roll("transport.torn", 0.3) for _ in range(64)]
    assert a.rng("transport.slow").random() == b.rng("transport.slow").random()


# ---- RetryPolicy ------------------------------------------------------------


def test_retry_policy_deterministic_jitter_caps_and_giveup():
    p = RetryPolicy(max_attempts=4, base_delay=0.1, max_delay=1.0)
    # jitter is a pure function of (seed, attempt): replayable
    assert [p.delay(i, seed=3) for i in range(8)] == [
        p.delay(i, seed=3) for i in range(8)
    ]
    assert p.delay(0, seed=1) != p.delay(0, seed=2)
    for i in range(8):
        raw = min(0.1 * 2**i, 1.0)
        assert raw * 0.75 <= p.delay(i, seed=0) <= raw * 1.25

    calls, sleeps = [], []

    def fail():
        calls.append(1)
        raise OSError("transient")

    with pytest.raises(GiveUpError) as ei:
        p.run(fail, sleep=sleeps.append)
    assert len(calls) == 4 and len(sleeps) == 3
    assert ei.value.attempts == 4
    assert isinstance(ei.value.last_error, OSError)

    # give-up classification: non-retryable errors surface immediately
    def fatal():
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        p.run(fatal, retryable=lambda e: not isinstance(e, ValueError))


def test_retry_policy_deadline_bounds_total_attempts():
    t = [0.0]

    def clock():
        return t[0]

    def sleep(d):
        t[0] += d

    p = RetryPolicy(
        max_attempts=100, base_delay=1.0, multiplier=1.0, jitter=0.0,
        deadline=3.5,
    )
    calls = []

    def fail():
        calls.append(1)
        raise OSError("x")

    with pytest.raises(GiveUpError):
        p.run(fail, sleep=sleep, clock=clock)
    # attempts at t=0,1,2,3; the next sleep would overshoot 3.5s
    assert len(calls) == 4


# ---- layer 2: HTTP transport chaos ------------------------------------------


def test_transport_faults_absorbed_by_retry_policy():
    inner = LocalCoordinator(target_world=1)
    inner.register("t0")
    server = CoordinatorServer(inner, host="127.0.0.1", port=0).start(
        evict=False
    )
    try:
        sched = FaultSchedule(
            0,
            [
                FaultEvent(0, "transport.refuse", 2),
                FaultEvent(1, "transport.torn", 1),
                FaultEvent(2, "transport.timeout", 1),
                FaultEvent(3, "transport.slow", 0.02),
            ],
        )
        client = ChaosHTTPCoordinator(
            f"127.0.0.1:{server.port}",
            sched,
            retries=4,
            retry_base_delay=0.01,
        )
        sched.advance(0)
        assert client.members() == ["t0"]  # 2 refusals absorbed
        assert client.injected["refuse"] == 2
        sched.advance(1)
        assert client.plan().world_size == 1  # torn JSON retried
        assert client.injected["torn"] == 1
        sched.advance(2)
        assert client.metrics()["members"] == 1  # timeout retried
        assert client.injected["timeout"] == 1
        sched.advance(3)
        assert client.members() == ["t0"]  # slow response tolerated
        assert client.injected["slow"] == 1

        # A storm outlasting the retry budget surfaces as the same
        # typed ConnectionError the pre-chaos contract promised.
        storm = FaultSchedule(0, [FaultEvent(0, "transport.refuse", 50)])
        storm.advance(0)
        dead = ChaosHTTPCoordinator(
            f"127.0.0.1:{server.port}",
            storm,
            retries=2,
            retry_base_delay=0.0,
        )
        with pytest.raises(ConnectionError, match="unreachable"):
            dead.members()
    finally:
        server.stop()


def test_http_coordinator_backoff_is_configurable_and_jittered():
    """Satellite: the transient-failure backoff (once hardcoded
    ``0.2 * 2**attempt``) is policy-driven — deadline + base delay
    configurable, deterministic jitter on."""
    c = HTTPCoordinator(
        "127.0.0.1:1", retries=7, retry_base_delay=0.5, retry_deadline=3.0
    )
    assert c.retry_policy.max_attempts == 7
    assert c.retry_policy.base_delay == 0.5
    assert c.retry_policy.deadline == 3.0
    assert c.retry_policy.jitter > 0
    client = make_coord_client(
        make_job(name="cfg"), retries=3, retry_base_delay=0.05,
        retry_deadline=1.0,
    )
    assert client.retry_policy.max_attempts == 3
    assert client.retry_policy.base_delay == 0.05
    assert client.retry_policy.deadline == 1.0


def test_coordclient_connection_error_handshake_tolerated(
    monkeypatch, capfd
):
    """Satellite: the ``coordclient.py`` comment-only claim ("callers
    catch ConnectionError and retry on the next tick") made real: the
    client raises typed ConnectionError, and the autoscaler's actuation
    tick logs the failed retarget and still applies the PUT."""
    port = _closed_port()
    monkeypatch.setenv("EDL_COORD_ADDR_TEMPLATE", f"127.0.0.1:{port}")
    job = make_job(name="jx")
    client = make_coord_client(job, timeout=0.2)
    with pytest.raises(ConnectionError):
        client.set_target_world(2)

    kube = FakeKube(tpu_nodes(2))
    cluster = Cluster(kube)
    cluster.create_trainer_workload(job)
    asc = Autoscaler(cluster)  # default factory -> unreachable address
    asc.jobs[job.name] = job
    asc._actuate({job.name: 2}, {job.name: -1})  # scale-down probes first
    err = capfd.readouterr().err
    assert "retarget" in err and "failed" in err
    assert kube.get_workload(job.trainer_job_name()).parallelism == 2


# ---- layer 4: kube actuation chaos ------------------------------------------


def test_conflict_storm_below_retry_budget_is_absorbed():
    kube = FakeKube(tpu_nodes(2))
    sched = FaultSchedule(0, [FaultEvent(0, "kube.conflict", 2)])
    sched.advance(0)
    ck = ChaosKube(kube, sched)
    cluster = Cluster(
        ck, conflict_retry=RetryPolicy(max_attempts=5, base_delay=0.0)
    )
    job = make_job(name="jk")
    cluster.create_trainer_workload(job)
    assert cluster.update_parallelism(job, 3)
    assert ck.injected_conflicts == 2
    assert kube.get_workload(job.trainer_job_name()).parallelism == 3


def test_conflict_storm_exhaustion_raises_typed_error():
    """Satellite: the once-unbounded ConflictError loop is bounded by
    RetryPolicy and gives up with a TYPED error."""
    kube = FakeKube(tpu_nodes(2))
    sched = FaultSchedule(0, [FaultEvent(0, "kube.conflict", 50)])
    sched.advance(0)
    ck = ChaosKube(kube, sched)
    cluster = Cluster(
        ck, conflict_retry=RetryPolicy(max_attempts=3, base_delay=0.0)
    )
    job = make_job(name="jg")
    cluster.create_trainer_workload(job)
    with pytest.raises(ParallelismUpdateError) as ei:
        cluster.update_parallelism(job, 3)
    assert ei.value.attempts == 3
    assert ck.injected_conflicts == 3
    # unchanged: the PUT never landed
    assert kube.get_workload(job.trainer_job_name()).parallelism == 1


def test_autoscaler_tick_logs_and_skips_conflict_giveup(capfd):
    """Satellite: the autoscaler tick survives the typed give-up —
    logs, skips the job, converges on a later tick."""
    kube = FakeKube(tpu_nodes(2))
    sched = FaultSchedule(0, [FaultEvent(0, "kube.conflict", 50)])
    sched.advance(0)
    ck = ChaosKube(kube, sched)
    cluster = Cluster(
        ck, conflict_retry=RetryPolicy(max_attempts=3, base_delay=0.0)
    )
    job = make_job(name="jc")
    cluster.create_trainer_workload(job)

    class NullClient:
        def set_target_world(self, n):
            pass

        def plan(self):
            return None

        def members(self):
            return []

    asc = Autoscaler(cluster, coord_client_factory=lambda job: NullClient())
    asc.jobs[job.name] = job
    asc._actuate({job.name: 3}, {job.name: 2})  # must not raise
    assert "gave up" in capfd.readouterr().err
    assert kube.get_workload(job.trainer_job_name()).parallelism == 1


def test_scheduling_hold_keeps_pods_pending_until_release():
    kube = FakeKube(tpu_nodes(2))
    sched = FaultSchedule(
        0,
        [
            FaultEvent(0, "kube.hold", "jh"),
            FaultEvent(1, "kube.release", "jh"),
        ],
    )
    sched.advance(0)
    ck = ChaosKube(kube, sched)
    cluster = Cluster(ck)
    ck.list_pods()  # pull the hold before the job exists
    job = make_job(name="jh")
    cluster.create_trainer_workload(job)
    assert cluster.job_pods(job) == (1, 0, 1, 0)  # stuck Pending
    sched.advance(1)
    ck.list_pods()  # release + retry scheduling
    assert cluster.job_pods(job) == (1, 1, 0, 0)


# ---- layer 1: membership chaos ----------------------------------------------


def test_dropped_and_delayed_heartbeats_age_the_lease():
    fake_now = [0.0]
    inner = LocalCoordinator(
        target_world=2, heartbeat_timeout=5.0, clock=lambda: fake_now[0]
    )
    sched = FaultSchedule(0, [FaultEvent(0, "coord.heartbeat.drop", 2)])
    sched.advance(0)
    coord = ChaosCoordinator(inner, sched)
    coord.register("a")
    coord.register("b")
    fake_now[0] = 4.0
    coord.heartbeat("a")  # dropped in flight (caller saw success)
    coord.heartbeat("b")  # dropped
    assert coord.dropped_heartbeats == 2
    fake_now[0] = 6.0  # both last heard at 0 -> past the 5s lease
    assert sorted(coord.evict_dead()) == ["a", "b"]

    # delayed heartbeat: the beat lands but back-dated
    inner2 = LocalCoordinator(
        target_world=1, heartbeat_timeout=5.0, clock=lambda: fake_now[0]
    )
    sched2 = FaultSchedule(0, [FaultEvent(0, "coord.heartbeat.delay", 3.0)])
    sched2.advance(0)
    coord2 = ChaosCoordinator(inner2, sched2)
    fake_now[0] = 0.0
    coord2.register("x")
    fake_now[0] = 4.0
    coord2.heartbeat("x")  # lands as if heard at t=1
    fake_now[0] = 6.1  # 5.1s since the back-dated beat -> evicted
    assert coord2.evict_dead() == ["x"]


def test_coordinator_restart_loses_state_and_recovers():
    sched = FaultSchedule(0)
    coord = ChaosCoordinator(
        LocalCoordinator(target_world=2, max_world=2), sched
    )
    coord.register("a")
    coord.register("b")
    assert coord.plan().world_size == 2
    coord.restart(lambda: LocalCoordinator(target_world=2, max_world=2))
    assert coord.members() == []  # all membership state gone
    assert coord.restarts == 1
    coord.register("a")
    coord.register("b")
    assert coord.plan().members == ("a", "b")


# ---- layer 3: checkpoint store chaos ----------------------------------------


def test_corrupted_checkpoint_detected_and_next_oldest_restores():
    """Satellite: restore verifies the CRC digest recorded at save time
    and falls back to the next-oldest snapshot on mismatch."""
    model, mesh, tr, it, state = _trained_state(3)
    store = HostDRAMStore(keep=3)
    store.save_async(state)
    for s in range(3, 6):
        state, _ = tr.step(state, it.device_batch(s, mesh))
    store.save_async(state)
    store.wait()
    assert store.steps() == [3, 6]
    assert corrupt_newest(store) == 6
    ckpt = store.latest_verified()
    assert ckpt is not None and ckpt.step == 3
    assert store.steps() == [3]  # the corrupt snapshot was discarded
    restored = store.restore(ckpt, mesh)
    assert int(restored.step) == 3


def test_save_thread_death_surfaces_via_wait():
    _, _, _, _, state = _trained_state(2)
    sched = FaultSchedule(0, [FaultEvent(0, "checkpoint.save_thread")])
    sched.advance(0)
    store = HostDRAMStore(chaos=sched)
    store.save_async(state)
    with pytest.raises(RuntimeError, match="async checkpoint save failed"):
        store.wait()
    assert store.latest() is None
    store.save_async(state)  # the fault was one-shot: next save lands
    store.wait()
    assert store.latest() is not None


def test_spill_io_error_surfaces_but_dram_copy_survives(tmp_path):
    _, mesh, _, _, state = _trained_state(2)
    sched = FaultSchedule(0, [FaultEvent(0, "checkpoint.spill")])
    sched.advance(0)
    store = HostDRAMStore(spill_dir=str(tmp_path), chaos=sched)
    store.save_async(state)
    with pytest.raises(RuntimeError, match="async checkpoint save failed"):
        store.wait()
    # The DRAM snapshot landed before the spill failed: still warm,
    # still verified, still restorable.
    ckpt = store.latest_verified()
    assert ckpt is not None
    assert int(store.restore(ckpt, mesh).step) == 2
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".npz")]


def test_flush_failure_degrades_resize_to_replay():
    """A save-thread death during the graceful-resize flush must
    degrade to the last interval checkpoint + deterministic replay, not
    kill the run (elastic._resize's flush guard, now exercised)."""
    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 512, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
    coord = LocalCoordinator(target_world=2, max_world=8)
    for i in range(2):
        coord.register(f"tr{i}")
    sched = FaultSchedule(0, [FaultEvent(0, "checkpoint.save_thread")])
    et = ElasticTrainer(
        model,
        optax.adam(1e-2),
        it,
        coord,
        store=HostDRAMStore(chaos=sched),
        checkpoint_interval=5,
        seed=0,
    )
    et.run(8)  # interval checkpoint at step 5
    et.store.wait()
    sched.advance(0)  # arm the flush fault
    coord.set_target_world(1)
    hist = et.run(12)
    ev = et.resize_events[-1]
    assert not ev.graceful
    assert ev.restored_step == 5
    assert ev.replayed_steps == 3
    assert [r.step for r in hist][-7:] == list(range(5, 12))


def test_spill_scan_race_retries_and_recovers(tmp_path, monkeypatch):
    """Satellite: the hostdram "retry the scan" comment made real — a
    manifest whose .npz vanished (concurrent prune) recovers when the
    rescan finds readable bytes, and raises loudly when it never
    does."""
    _, _, _, _, state = _trained_state(2)
    store = HostDRAMStore(keep=2, spill_dir=str(tmp_path))
    store.save_async(state)
    store.wait()
    step = store.latest().step
    npz = tmp_path / f"ckpt-{step:012d}.npz"
    hidden = tmp_path / "hidden.bin"
    npz.rename(hidden)

    def heal(_seconds):
        # the "concurrent pruner" finishes: bytes are back by rescan
        if hidden.exists():
            hidden.rename(npz)

    monkeypatch.setattr(time, "sleep", heal)
    fresh = HostDRAMStore(keep=2, spill_dir=str(tmp_path))
    ckpt = fresh.load_from_disk(state)
    assert ckpt.step == step

    # permanent loss: the scan retries then refuses to restart at 0
    npz.unlink()
    monkeypatch.setattr(time, "sleep", lambda s: None)
    fresh2 = HostDRAMStore(keep=2, spill_dir=str(tmp_path))
    with pytest.raises(RuntimeError, match="unreadable bytes"):
        fresh2.load_from_disk(state)


def test_corrupted_spill_falls_back_to_older_snapshot(tmp_path, capfd):
    model, mesh, tr, it, state = _trained_state(3)
    store = HostDRAMStore(keep=2, spill_dir=str(tmp_path))
    store.save_async(state)
    for s in range(3, 6):
        state, _ = tr.step(state, it.device_batch(s, mesh))
    store.save_async(state)
    store.wait()
    # corrupt the NEWEST spill's bytes on disk (manifest digest stays)
    npz = tmp_path / "ckpt-000000000006.npz"
    with np.load(npz) as z:
        arrays = {k: np.array(z[k]) for k in z.files}
    first = sorted(arrays)[0]
    arrays[first].reshape(-1).view(np.uint8)[0] ^= 0xFF
    np.savez(str(npz), **arrays)

    fresh = HostDRAMStore(keep=2, spill_dir=str(tmp_path))
    ckpt = fresh.load_from_disk(state)
    assert ckpt.step == 3  # next-oldest spill restored
    assert "failed CRC" in capfd.readouterr().err


# ---- the soak ---------------------------------------------------------------


def _soak_events(base=0):
    """One 200-step chaos cycle: grow, kill, drop RPCs, die-with-state,
    corrupt a checkpoint, restart the coordinator, shrink."""
    return [
        FaultEvent(base + 20, "member.restart", "tr2"),
        FaultEvent(base + 20, "member.restart", "tr3"),
        FaultEvent(base + 20, "scale.target", 4),
        FaultEvent(base + 45, "transport.refuse", 2),
        FaultEvent(base + 50, "member.kill", "tr3"),
        FaultEvent(base + 60, "transport.torn", 2),
        FaultEvent(base + 70, "member.die_with_state", "tr1"),
        FaultEvent(base + 90, "checkpoint.corrupt"),
        FaultEvent(base + 92, "member.die_with_state", "tr2"),
        FaultEvent(base + 110, "scale.target", 4),
        FaultEvent(base + 110, "member.restart", "tr1"),
        FaultEvent(base + 110, "member.restart", "tr2"),
        FaultEvent(base + 110, "member.restart", "tr3"),
        FaultEvent(base + 130, "transport.timeout", 2),
        FaultEvent(base + 140, "coord.restart"),
        FaultEvent(base + 150, "transport.slow", 0.05),
        FaultEvent(base + 160, "scale.target", 2),
        FaultEvent(base + 180, "transport.refuse", 2),
    ]


def _run_soak(seed: int, cycles: int = 1):
    """One full chaos soak over the real HTTP transport.  Returns a
    dict of everything that must be bit-identical across same-seed
    runs."""
    steps = 200 * cycles
    schedule = FaultSchedule(
        seed,
        [ev for c in range(cycles) for ev in _soak_events(c * 200)],
    )
    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 512, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
    inner = LocalCoordinator(
        target_world=2, max_world=4, legal_sizes=[1, 2, 4],
        heartbeat_timeout=1e9,
    )
    coord = ChaosCoordinator(inner, schedule)
    coord.register("tr0")
    coord.register("tr1")
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start(
        evict=False
    )
    try:
        client = ChaosHTTPCoordinator(
            f"127.0.0.1:{server.port}",
            schedule,
            timeout=10.0,
            retries=5,
            retry_base_delay=0.02,
        )
        store = HostDRAMStore(keep=3, chaos=schedule)
        et = ElasticTrainer(
            model,
            optax.adam(1e-2),
            it,
            client,
            store=store,
            checkpoint_interval=10,
            seed=0,
        )
        monkey = ChaosMonkey(
            schedule,
            et,
            coordinator=coord,
            store=store,
            coordinator_factory=lambda: LocalCoordinator(
                target_world=4, max_world=4, legal_sizes=[1, 2, 4],
                heartbeat_timeout=1e9,
            ),
        ).track(["tr0", "tr1"])
        history = et.run(steps, on_step=monkey.on_step)
        store.wait()
        final = store.get(steps)  # interval save at the final step
        assert final is not None, "final-step checkpoint missing"
        return {
            "digest": final.digest(),
            "history": [
                (r.step, r.generation, r.world_size, float(r.loss))
                for r in history
            ],
            "resizes": [
                (
                    e.generation,
                    e.world_size,
                    e.restored_step,
                    e.replayed_steps,
                    e.graceful,
                    e.restore_source,
                )
                for e in et.resize_events
            ],
            "monkey_log": list(monkey.log),
            "injected": dict(client.injected),
            "pending": schedule.pending(),
        }
    finally:
        server.stop()


def _check_soak_invariants(r, cycles=1):
    # Every scheduled fault actually fired.
    assert r["pending"] == []
    # The wire faults really crossed the wire.
    assert r["injected"] == {
        "refuse": 4 * cycles,
        "timeout": 2 * cycles,
        "slow": cycles,
        "torn": 2 * cycles,
    }
    # No lost steps on any graceful resize.
    for gen, world, restored, replayed, graceful, source in r["resizes"]:
        if graceful:
            assert replayed == 0, (gen, restored, replayed)
    # Per cycle: the corrupted step-90 checkpoint was detected and the
    # run fell back to the NEXT-OLDEST snapshot (step 80) and replayed
    # — without aborting.
    for c in range(cycles):
        base = c * 200
        assert any(
            restored == base + 80 and not graceful and replayed == 13
            for _, _, restored, replayed, graceful, _ in r["resizes"]
        ), (c, r["resizes"])
    # The run completed every step despite the chaos.
    steps_seen = {s for s, _, _, _ in r["history"]}
    assert steps_seen == set(range(200 * cycles))


def test_chaos_soak_bit_reproducible_and_recovers():
    """Acceptance: the seeded ~200-step soak — kills, scale events,
    dropped RPCs, one corrupted checkpoint — completes, detects and
    recovers from the corruption, loses no steps on graceful resizes,
    and two runs with the same FaultSchedule seed produce an IDENTICAL
    final-state CRC digest (bit-reproducible chaos)."""
    r1 = _run_soak(seed=1234)
    _check_soak_invariants(r1)
    r2 = _run_soak(seed=1234)
    assert r1["digest"] == r2["digest"]
    assert r1["history"] == r2["history"]  # losses bitwise identical
    assert r1["resizes"] == r2["resizes"]
    assert r1["monkey_log"] == r2["monkey_log"]

    # Loss continuity against an UNINTERRUPTED reference world: the
    # fixed-global-batch + deterministic-data design makes the chaos
    # run's per-step losses match a run that never saw a fault.
    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 512, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
    coord = LocalCoordinator(target_world=2, max_world=8)
    coord.register("a")
    coord.register("b")
    ref = ElasticTrainer(
        model, optax.adam(1e-2), it, coord, checkpoint_interval=10, seed=0
    )
    ref_hist = ref.run(200)
    ref_loss = {r.step: r.loss for r in ref_hist}
    # last occurrence per step (replays re-run earlier steps)
    chaos_loss = {}
    for step, _, _, loss in r1["history"]:
        chaos_loss[step] = loss
    np.testing.assert_allclose(
        [chaos_loss[s] for s in sorted(chaos_loss)],
        [ref_loss[s] for s in sorted(ref_loss)],
        rtol=1e-5,
    )


@pytest.mark.slow
def test_chaos_soak_long_multi_cycle():
    """The full soak: two back-to-back 200-step chaos cycles (kills,
    restarts, a coordinator restart and a corrupted checkpoint per
    cycle).  Gated behind -m slow; tier-1 runs the single-cycle soak."""
    r = _run_soak(seed=99, cycles=2)
    _check_soak_invariants(r, cycles=2)


# ---- zero-stall resize chaos: background flush + prewarm hint -------------


def _elastic_world(store, target=2, trainers=2, ckpt_interval=5):
    model = get_model("fit_a_line")
    ds = synthetic_dataset(model.synth_batch, 512, seed=0)
    it = ShardedDataIterator(ds, global_batch_size=64, seed=0)
    coord = LocalCoordinator(target_world=target, max_world=8)
    for i in range(trainers):
        coord.register(f"tr{i}")
    et = ElasticTrainer(
        model,
        optax.adam(1e-2),
        it,
        coord,
        store=store,
        checkpoint_interval=ckpt_interval,
        seed=0,
    )
    return et, coord


def test_flush_spill_slow_overlaps_resize_window(tmp_path):
    """chaos[flush.spill.slow]: the flush's background hash/spill
    thread stalls.  The stall must land on the BACKGROUND phase
    (overlapping the window), never on the ordered device->host flush
    phase — and the end-of-window join still guarantees the durable
    spill landed before the resize returned."""
    sched = FaultSchedule(0, [FaultEvent(0, "flush.spill.slow", 0.5)])
    store = HostDRAMStore(spill_dir=str(tmp_path), chaos=sched)
    et, coord = _elastic_world(store)
    et.run(8)  # interval save at 5; resize flush at 8 is fresh
    et.store.wait()
    sched.advance(0)  # arm the stall for the resize flush
    coord.set_target_world(1)
    hist = et.run(12)
    ev = et.resize_events[-1]
    assert ev.graceful, "a slow spill must not degrade the resize to replay"
    ph = ev.phase_seconds
    assert ph["flush_bg"] >= 0.5, ph  # the stall hit the background thread
    assert ph["flush"] < 0.5, ph      # ...not the ordered d2h phase
    # join-before-return: the flushed step's durable spill is on disk
    assert (tmp_path / f"ckpt-{8:012d}.npz").exists()
    assert [r.step for r in hist][-4:] == list(range(8, 12))
    assert not sched.pending()


def test_prewarm_hint_dropped_chaos():
    """chaos[prewarm.hint.dropped]: the autoscaler's hint is lost en
    route — no prewarm happens, and the subsequent retarget still
    resizes correctly (cold compile overlapped with restore, not a
    correctness event)."""
    sched = FaultSchedule(0, [FaultEvent(0, "prewarm.hint.dropped")])
    sched.advance(0)
    store = HostDRAMStore(chaos=sched)
    et, coord = _elastic_world(store, target=2, trainers=4)
    et.run(3)
    coord.set_prewarm(4)
    et.run(6)  # the hint is consumed — and dropped — here
    assert et._dropped_prewarm_hints == 1
    assert 4 not in et._trainers, "dropped hint must not prewarm"
    coord.set_target_world(4)
    et.run(9)
    grow = et.resize_events[-1]
    assert grow.world_size == 4 and grow.graceful
    assert [r.step for r in et.history] == list(range(9))
    assert not sched.pending()
