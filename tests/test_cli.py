"""CLI surface tests (the reference had no CLI — paddlecloud did this;
SURVEY.md §2.2)."""

import json

import pytest
import yaml

from edl_tpu.cli import main

JOB_YAML = """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: cli-demo}
spec:
  fault_tolerant: true
  global_batch_size: 64
  trainer:
    entrypoint: fit_a_line
    min_instance: 1
    max_instance: 4
    slice_topology: cpu
    resources:
      requests: {cpu: "1", memory: 1Gi}
"""


@pytest.fixture
def spec(tmp_path):
    p = tmp_path / "job.yaml"
    p.write_text(JOB_YAML)
    return str(p)


def test_submit_dry_run(spec, capsys):
    assert main(["submit", spec, "--dry-run"]) == 0
    doc = yaml.safe_load(capsys.readouterr().out)
    assert doc["kind"] == "TrainingJob"
    assert doc["metadata"]["name"] == "cli-demo"


def test_manifests(spec, capsys):
    assert main(["manifests", spec]) == 0
    docs = list(yaml.safe_load_all(capsys.readouterr().out))
    kinds = sorted(d["kind"] for d in docs)
    assert kinds == ["Deployment", "Job", "Service"]


def test_crd(capsys):
    assert main(["crd"]) == 0
    doc = yaml.safe_load(capsys.readouterr().out)
    assert doc["kind"] == "CustomResourceDefinition"
    assert doc["metadata"]["name"] == "trainingjobs.edl.tpu.dev"


def test_local_sim(spec, capsys):
    assert (
        main(
            [
                "local-sim",
                spec,
                "--nodes",
                "2",
                "--node-tpu-chips",
                "0",
                "--iterations",
                "4",
            ]
        )
        == 0
    )
    out = json.loads(capsys.readouterr().out)
    jobs = out["jobs"]
    assert jobs[0]["name"] == "cli-demo"
    assert jobs[0]["state"] in ("Running", "Scaling")
    assert jobs[0]["parallelism"] >= 1
    assert "tpu_utilization" in out["cluster"]
    assert "pending_p50_s" in out["cluster"]


def test_local_run_with_resize(spec, capsys):
    assert (
        main(
            [
                "local-run",
                spec,
                "--steps",
                "16",
                "--resize-at",
                "8:4",
            ]
        )
        == 0
    )
    out = capsys.readouterr().out
    summary = json.loads(out[out.index("{") :])
    assert summary["steps"] == 16
    assert 4 in summary["world_sizes_seen"]
    assert summary["final_loss"] < summary["first_loss"]
