"""CLI surface tests (the reference had no CLI — paddlecloud did this;
SURVEY.md §2.2)."""

import json

import numpy as np

import pytest
import yaml

from edl_tpu.cli import main

JOB_YAML = """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: cli-demo}
spec:
  fault_tolerant: true
  global_batch_size: 64
  trainer:
    entrypoint: fit_a_line
    min_instance: 1
    max_instance: 4
    slice_topology: cpu
    resources:
      requests: {cpu: "1", memory: 1Gi}
"""


@pytest.fixture
def spec(tmp_path):
    p = tmp_path / "job.yaml"
    p.write_text(JOB_YAML)
    return str(p)


def test_submit_dry_run(spec, capsys):
    assert main(["submit", spec, "--dry-run"]) == 0
    doc = yaml.safe_load(capsys.readouterr().out)
    assert doc["kind"] == "TrainingJob"
    assert doc["metadata"]["name"] == "cli-demo"


def test_manifests(spec, capsys):
    assert main(["manifests", spec]) == 0
    docs = list(yaml.safe_load_all(capsys.readouterr().out))
    kinds = sorted(d["kind"] for d in docs)
    assert kinds == ["Deployment", "Job", "Service"]


def test_crd(capsys):
    assert main(["crd"]) == 0
    doc = yaml.safe_load(capsys.readouterr().out)
    assert doc["kind"] == "CustomResourceDefinition"
    assert doc["metadata"]["name"] == "trainingjobs.edl.tpu.dev"


def test_local_sim(spec, capsys):
    assert (
        main(
            [
                "local-sim",
                spec,
                "--nodes",
                "2",
                "--node-tpu-chips",
                "0",
                "--iterations",
                "4",
            ]
        )
        == 0
    )
    out = json.loads(capsys.readouterr().out)
    jobs = out["jobs"]
    assert jobs[0]["name"] == "cli-demo"
    assert jobs[0]["state"] in ("Running", "Scaling")
    assert jobs[0]["parallelism"] >= 1
    assert "tpu_utilization" in out["cluster"]
    assert "pending_p50_s" in out["cluster"]


def test_local_run_with_resize(spec, capsys):
    assert (
        main(
            [
                "local-run",
                spec,
                "--steps",
                "16",
                "--resize-at",
                "8:4",
            ]
        )
        == 0
    )
    out = capsys.readouterr().out
    summary = json.loads(out[out.index("{") :])
    assert summary["steps"] == 16
    assert 4 in summary["world_sizes_seen"]
    assert summary["final_loss"] < summary["first_loss"]


MIX_A = """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: mix-resnet}
spec:
  fault_tolerant: true
  global_batch_size: 64
  trainer:
    entrypoint: resnet50
    min_instance: 1
    max_instance: 8
    slice_topology: v5e-4
    resources:
      requests: {cpu: "1", memory: 1Gi}
"""

MIX_B = """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: mix-bert}
spec:
  fault_tolerant: true
  global_batch_size: 64
  trainer:
    entrypoint: transformer_base
    min_instance: 1
    max_instance: 8
    slice_topology: v5e-4
    resources:
      requests: {cpu: "1", memory: 1Gi}
"""


def test_local_sim_multi_job_mix(tmp_path, capsys):
    """BASELINE config 5: two elastic jobs contend one pod's worth of
    chips; the autoscaler splits capacity fairly (ascending-fulfillment
    order) and utilization reaches 100%."""
    a = tmp_path / "a.yaml"
    a.write_text(MIX_A)
    b = tmp_path / "b.yaml"
    b.write_text(MIX_B)
    # 4 pools x 4 chips = 16 chips; both jobs want 8 replicas x 4 chips.
    assert (
        main(
            [
                "local-sim",
                str(a),
                str(b),
                "--nodes",
                "4",
                "--node-tpu-chips",
                "4",
                "--iterations",
                "6",
            ]
        )
        == 0
    )
    out = json.loads(capsys.readouterr().out)
    by_name = {j["name"]: j for j in out["jobs"]}
    pa = by_name["mix-resnet"]["parallelism"]
    pb = by_name["mix-bert"]["parallelism"]
    assert pa + pb == 4  # 16 chips / 4 per replica, fully used
    assert abs(pa - pb) <= 1, f"unfair split: {pa} vs {pb}"
    assert out["cluster"]["tpu_utilization"] == 1.0


def test_deploy_manifests(capsys):
    """`edl deploy` renders a complete control-plane install: namespace,
    CRD, least-privilege RBAC, controller Deployment."""
    assert main(["deploy"]) == 0
    docs = list(yaml.safe_load_all(capsys.readouterr().out))
    kinds = [d["kind"] for d in docs]
    assert kinds == [
        "Namespace",
        "CustomResourceDefinition",
        "ServiceAccount",
        "ClusterRole",
        "ClusterRoleBinding",
        "Deployment",
    ]
    role = next(d for d in docs if d["kind"] == "ClusterRole")
    by_group = {
        g: r["verbs"]
        for r in role["rules"]
        for g in r["apiGroups"]
        if "trainingjobs" in r["resources"] or g in ("batch", "apps")
    }
    assert "watch" in by_group["edl.tpu.dev"]  # the informer analog
    assert {"create", "delete"} <= set(by_group["batch"])  # trainer Jobs
    dep = next(d for d in docs if d["kind"] == "Deployment")
    spec = dep["spec"]["template"]["spec"]
    assert spec["serviceAccountName"] == "edl-controller"
    assert spec["containers"][0]["args"] == ["controller"]


def test_local_run_file_backed_matches_in_memory(spec, tmp_path, capsys):
    """BASELINE-config training on real bytes from disk: a file-backed
    (memory-mapped) store trains end-to-end through a mid-run resize,
    and the loss stream is identical to the in-memory run — the
    (seed, step) determinism core is byte-source invariant (VERDICT r3
    missing-5)."""
    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.datasets import stage_synthetic

    store = tmp_path / "store"
    stage_synthetic(
        str(store), get_model("fit_a_line").synth_batch, 4096, seed=0
    )
    common = ["local-run", spec, "--steps", "16", "--resize-at", "8:2"]
    assert main(common + ["--data-dir", str(store)]) == 0
    out = capsys.readouterr().out
    file_run = json.loads(out[out.index("{") :])
    assert main(common) == 0
    out = capsys.readouterr().out
    mem_run = json.loads(out[out.index("{") :])

    assert file_run["final_loss"] < file_run["first_loss"]
    assert 2 in file_run["world_sizes_seen"]
    assert file_run["final_loss"] == mem_run["final_loss"]
    assert file_run["first_loss"] == mem_run["first_loss"]


def test_local_run_trains_user_workspace_model(tmp_path, capsys):
    """The user-code contract (VERDICT r4 #4; ref ENTRY/TRAINER_PACKAGE,
    pkg/jobparser.go:288-291): an UNREGISTERED entrypoint loads from the
    workspace's model.py (build(**kwargs) -> ModelDef) and trains end to
    end through `edl local-run`, including a mid-run resize."""
    ws = tmp_path / "userspace"
    ws.mkdir()
    (ws / "helper.py").write_text("SCALE = 0.5\n")
    (ws / "model.py").write_text(
        '''
import jax
import jax.numpy as jnp
import numpy as np

import helper  # sibling import: the workspace dir is on sys.path

from edl_tpu.models.base import ModelDef


def build(**kwargs):
    def init_params(rng):
        return {"w": jax.random.normal(rng, (4,)) * helper.SCALE}

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"]
        loss = jnp.mean((pred - batch["y"]) ** 2)
        return loss, {"loss": loss}

    def synth_batch(rng, n):
        x = rng.randn(n, 4).astype(np.float32)
        return {"x": x, "y": (x @ np.arange(4.0, dtype=np.float32))}

    return ModelDef(
        name="user_linear",
        init_params=init_params,
        loss_fn=loss_fn,
        synth_batch=synth_batch,
    )
'''
    )
    spec_path = tmp_path / "job.yaml"
    spec_path.write_text(
        f"""
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata:
  name: user-job
spec:
  image: edl-tpu/trainer:latest
  fault_tolerant: true
  global_batch_size: 32
  trainer:
    entrypoint: user_linear
    workspace: {ws}
    min_instance: 1
    max_instance: 2
"""
    )
    rc = main(["local-run", str(spec_path), "--steps", "12", "--resize-at", "6:2"])
    assert rc == 0
    out = capsys.readouterr().out
    summary = json.loads(out[out.index("{") :])
    assert summary["model"] == "user_linear"
    assert summary["steps"] == 12
    assert summary["final_loss"] < summary["first_loss"]
    assert summary["world_sizes_seen"] == [1, 2]


def test_pod_env_roundtrips_workspace(tmp_path):
    """A submitted job's pod env carries the workspace for the launcher
    (EDL_WORKSPACE -> env_config -> bind_model fallback)."""
    from edl_tpu.controller.jobparser import pod_env
    from edl_tpu.resource.training_job import TrainingJob

    job = TrainingJob.from_manifest(
        {
            "apiVersion": "edl.tpu.dev/v1",
            "kind": "TrainingJob",
            "metadata": {"name": "ws-job"},
            "spec": {
                "trainer": {
                    "entrypoint": "user_linear",
                    "workspace": "/mnt/user/code",
                }
            },
        }
    ).validate()
    env = {e["name"]: e.get("value") for e in pod_env(job)}
    assert env["EDL_WORKSPACE"] == "/mnt/user/code"
    assert env["EDL_ENTRYPOINT"] == "user_linear"


def _write_idx_images(path, imgs):
    """Serialize uint8 [N, 28, 28] into the real IDX image format."""
    import struct

    with open(path, "wb") as f:
        f.write(struct.pack(">BBBB", 0, 0, 0x08, 3))
        f.write(struct.pack(">III", *imgs.shape))
        f.write(imgs.astype(np.uint8).tobytes())


def _write_idx_labels(path, labels):
    import struct

    with open(path, "wb") as f:
        f.write(struct.pack(">BBBB", 0, 0, 0x08, 1))
        f.write(struct.pack(">I", len(labels)))
        f.write(labels.astype(np.uint8).tobytes())


def test_ingest_mnist_idx_trains_real_bytes(tmp_path, capsys):
    """VERDICT r4 #8: a BASELINE config (MNIST) trains on bytes that did
    NOT come from synth_batch — real IDX files ingested into an array
    store with sha256 provenance, trained through `edl local-run` with
    a mid-run resize, deterministically (two runs, identical losses)."""
    # Fabricate a learnable MNIST-shaped corpus in the REAL IDX format
    # (digit-dependent blobs like the synthetic generator, but these
    # bytes flow through the ingester, not synth_batch).
    rng = np.random.RandomState(7)
    n = 256
    labels = rng.randint(0, 10, size=n)
    imgs = np.zeros((n, 28, 28), np.uint8)
    for c in range(10):
        idx = labels == c
        imgs[idx, 2 + 2 * c : 6 + 2 * c, 4:24] = 200
    imgs = np.clip(
        imgs.astype(np.int32) + rng.randint(0, 40, imgs.shape), 0, 255
    ).astype(np.uint8)
    _write_idx_images(tmp_path / "train-images-idx3-ubyte", imgs)
    _write_idx_labels(tmp_path / "train-labels-idx1-ubyte", labels)

    store = tmp_path / "mnist_store"
    rc = main(
        [
            "ingest", "mnist",
            "--images", str(tmp_path / "train-images-idx3-ubyte"),
            "--labels", str(tmp_path / "train-labels-idx1-ubyte"),
            "--out", str(store),
        ]
    )
    assert rc == 0
    manifest = json.loads(capsys.readouterr().out)
    prov = manifest["provenance"]
    assert prov["format"] == "mnist-idx"
    assert len(prov["images_sha256"]) == 64
    assert manifest["n"] == n

    spec_path = tmp_path / "job.yaml"
    spec_path.write_text(
        """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata:
  name: mnist-real
spec:
  image: edl-tpu/trainer:latest
  fault_tolerant: true
  global_batch_size: 32
  trainer:
    entrypoint: mnist
    min_instance: 1
    max_instance: 2
"""
    )

    def run():
        rc = main(
            [
                "local-run", str(spec_path),
                "--steps", "14", "--resize-at", "7:2",
                "--data-dir", str(store),
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        return json.loads(out[out.index("{") :])

    a = run()
    b = run()
    assert a["model"] == "mnist" and a["steps"] == 14
    assert a["world_sizes_seen"] == [1, 2]
    assert a["final_loss"] < a["first_loss"] * 0.5  # learned REAL bytes
    # resume-after-resize determinism on file-backed real data
    assert a["final_loss"] == b["final_loss"]
    assert a["first_loss"] == b["first_loss"]


def test_ingest_tokens_roundtrip(tmp_path, capsys):
    """Tokenized-text ingestion: flat .npy corpus -> fixed seq_len+1
    rows keyed for the LM families, leftover tokens dropped, provenance
    recorded."""
    flat = np.arange(3, 3 + 1000, dtype=np.uint16)
    np.save(tmp_path / "corpus.npy", flat)
    rc = main(
        [
            "ingest", "tokens",
            "--tokens", str(tmp_path / "corpus.npy"),
            "--seq-len", "63",
            "--out", str(tmp_path / "tok_store"),
        ]
    )
    assert rc == 0
    manifest = json.loads(capsys.readouterr().out)
    assert manifest["n"] == 1000 // 64
    assert manifest["provenance"]["dropped_tokens"] == str(1000 - 15 * 64)
    from edl_tpu.runtime.datasets import load_array_store

    store = load_array_store(str(tmp_path / "tok_store"))
    assert store["tokens"].shape == (15, 64)
    assert store["tokens"].dtype == np.int32


def test_metrics_subcommand_pretty_prints_merged_telemetry(capsys):
    """`edl metrics <url>`: merged metrics + flight-recorder tail from
    a running job's coordinator, plus --prom / --json raw modes."""
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.telemetry import MetricsRegistry

    coord = LocalCoordinator(target_world=2, max_world=4)
    coord.register("a")
    coord.register("b")
    reg = MetricsRegistry()
    reg.counter("edl_steps_total").inc(42)
    reg.histogram("edl_resize_seconds").observe(0.25)
    coord.report_telemetry(
        "a",
        snapshot=reg.snapshot(),
        seq=1,
        events=[
            {
                "kind": "resize",
                "step": 9,
                "generation": 2,
                "data": {"world_size": 2, "graceful": True},
            }
        ],
    )
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start(
        evict=False
    )
    url = f"127.0.0.1:{server.port}"
    try:
        assert main(["metrics", url]) == 0
        out = capsys.readouterr().out
        assert "coordinator" in out and "goodput" in out
        assert "edl_steps_total" in out and "42" in out
        assert "flight recorder" in out
        assert "resize" in out and "coord.plan" in out

        assert main(["metrics", url, "--prom"]) == 0
        prom = capsys.readouterr().out
        assert "# TYPE edl_members gauge" in prom
        assert "edl_steps_total 42" in prom

        assert main(["metrics", url, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["coordinator"]["members"] == 2
        assert (
            doc["telemetry"]["merged"]["counters"]["edl_steps_total"][""]
            == 42
        )
    finally:
        server.stop()
