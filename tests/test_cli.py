"""CLI surface tests (the reference had no CLI — paddlecloud did this;
SURVEY.md §2.2)."""

import json

import pytest
import yaml

from edl_tpu.cli import main

JOB_YAML = """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: cli-demo}
spec:
  fault_tolerant: true
  global_batch_size: 64
  trainer:
    entrypoint: fit_a_line
    min_instance: 1
    max_instance: 4
    slice_topology: cpu
    resources:
      requests: {cpu: "1", memory: 1Gi}
"""


@pytest.fixture
def spec(tmp_path):
    p = tmp_path / "job.yaml"
    p.write_text(JOB_YAML)
    return str(p)


def test_submit_dry_run(spec, capsys):
    assert main(["submit", spec, "--dry-run"]) == 0
    doc = yaml.safe_load(capsys.readouterr().out)
    assert doc["kind"] == "TrainingJob"
    assert doc["metadata"]["name"] == "cli-demo"


def test_manifests(spec, capsys):
    assert main(["manifests", spec]) == 0
    docs = list(yaml.safe_load_all(capsys.readouterr().out))
    kinds = sorted(d["kind"] for d in docs)
    assert kinds == ["Deployment", "Job", "Service"]


def test_crd(capsys):
    assert main(["crd"]) == 0
    doc = yaml.safe_load(capsys.readouterr().out)
    assert doc["kind"] == "CustomResourceDefinition"
    assert doc["metadata"]["name"] == "trainingjobs.edl.tpu.dev"


def test_local_sim(spec, capsys):
    assert (
        main(
            [
                "local-sim",
                spec,
                "--nodes",
                "2",
                "--node-tpu-chips",
                "0",
                "--iterations",
                "4",
            ]
        )
        == 0
    )
    out = json.loads(capsys.readouterr().out)
    jobs = out["jobs"]
    assert jobs[0]["name"] == "cli-demo"
    assert jobs[0]["state"] in ("Running", "Scaling")
    assert jobs[0]["parallelism"] >= 1
    assert "tpu_utilization" in out["cluster"]
    assert "pending_p50_s" in out["cluster"]


def test_local_run_with_resize(spec, capsys):
    assert (
        main(
            [
                "local-run",
                spec,
                "--steps",
                "16",
                "--resize-at",
                "8:4",
            ]
        )
        == 0
    )
    out = capsys.readouterr().out
    summary = json.loads(out[out.index("{") :])
    assert summary["steps"] == 16
    assert 4 in summary["world_sizes_seen"]
    assert summary["final_loss"] < summary["first_loss"]


MIX_A = """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: mix-resnet}
spec:
  fault_tolerant: true
  global_batch_size: 64
  trainer:
    entrypoint: resnet50
    min_instance: 1
    max_instance: 8
    slice_topology: v5e-4
    resources:
      requests: {cpu: "1", memory: 1Gi}
"""

MIX_B = """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: mix-bert}
spec:
  fault_tolerant: true
  global_batch_size: 64
  trainer:
    entrypoint: transformer_base
    min_instance: 1
    max_instance: 8
    slice_topology: v5e-4
    resources:
      requests: {cpu: "1", memory: 1Gi}
"""


def test_local_sim_multi_job_mix(tmp_path, capsys):
    """BASELINE config 5: two elastic jobs contend one pod's worth of
    chips; the autoscaler splits capacity fairly (ascending-fulfillment
    order) and utilization reaches 100%."""
    a = tmp_path / "a.yaml"
    a.write_text(MIX_A)
    b = tmp_path / "b.yaml"
    b.write_text(MIX_B)
    # 4 pools x 4 chips = 16 chips; both jobs want 8 replicas x 4 chips.
    assert (
        main(
            [
                "local-sim",
                str(a),
                str(b),
                "--nodes",
                "4",
                "--node-tpu-chips",
                "4",
                "--iterations",
                "6",
            ]
        )
        == 0
    )
    out = json.loads(capsys.readouterr().out)
    by_name = {j["name"]: j for j in out["jobs"]}
    pa = by_name["mix-resnet"]["parallelism"]
    pb = by_name["mix-bert"]["parallelism"]
    assert pa + pb == 4  # 16 chips / 4 per replica, fully used
    assert abs(pa - pb) <= 1, f"unfair split: {pa} vs {pb}"
    assert out["cluster"]["tpu_utilization"] == 1.0


def test_deploy_manifests(capsys):
    """`edl deploy` renders a complete control-plane install: namespace,
    CRD, least-privilege RBAC, controller Deployment."""
    assert main(["deploy"]) == 0
    docs = list(yaml.safe_load_all(capsys.readouterr().out))
    kinds = [d["kind"] for d in docs]
    assert kinds == [
        "Namespace",
        "CustomResourceDefinition",
        "ServiceAccount",
        "ClusterRole",
        "ClusterRoleBinding",
        "Deployment",
    ]
    role = next(d for d in docs if d["kind"] == "ClusterRole")
    by_group = {
        g: r["verbs"]
        for r in role["rules"]
        for g in r["apiGroups"]
        if "trainingjobs" in r["resources"] or g in ("batch", "apps")
    }
    assert "watch" in by_group["edl.tpu.dev"]  # the informer analog
    assert {"create", "delete"} <= set(by_group["batch"])  # trainer Jobs
    dep = next(d for d in docs if d["kind"] == "Deployment")
    spec = dep["spec"]["template"]["spec"]
    assert spec["serviceAccountName"] == "edl-controller"
    assert spec["containers"][0]["args"] == ["controller"]


def test_local_run_file_backed_matches_in_memory(spec, tmp_path, capsys):
    """BASELINE-config training on real bytes from disk: a file-backed
    (memory-mapped) store trains end-to-end through a mid-run resize,
    and the loss stream is identical to the in-memory run — the
    (seed, step) determinism core is byte-source invariant (VERDICT r3
    missing-5)."""
    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.datasets import stage_synthetic

    store = tmp_path / "store"
    stage_synthetic(
        str(store), get_model("fit_a_line").synth_batch, 4096, seed=0
    )
    common = ["local-run", spec, "--steps", "16", "--resize-at", "8:2"]
    assert main(common + ["--data-dir", str(store)]) == 0
    out = capsys.readouterr().out
    file_run = json.loads(out[out.index("{") :])
    assert main(common) == 0
    out = capsys.readouterr().out
    mem_run = json.loads(out[out.index("{") :])

    assert file_run["final_loss"] < file_run["first_loss"]
    assert 2 in file_run["world_sizes_seen"]
    assert file_run["final_loss"] == mem_run["final_loss"]
    assert file_run["first_loss"] == mem_run["first_loss"]
