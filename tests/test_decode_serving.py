"""True-Orca decode serving (ISSUE 13): KV-cached autoregressive
decode with per-token continuous batching.

Key guarantees under test:

- the KV-cached prefill+decode path emits EXACTLY the tokens the
  single-shot full-recompute greedy path emits (the correctness anchor
  for the incremental cache);
- decode results are batch-invariant: a sequence's tokens do not
  depend on which other sequences share its decode iterations (the
  purity precondition for continuous batching);
- steady-state decode performs ZERO XLA compiles (prefill + decode
  executables AOT-held per bucket, pools donated);
- requests JOIN and LEAVE the running batch at token boundaries;
  finished sequences (EOS / budget) release their KV blocks the same
  iteration;
- a checkpoint hot swap mid-generation RE-PREFILLS affected sequences
  against the new weights: every finished sequence's tokens are the
  pure function of the one generation it reports (never mixed), and
  zero sequences drop (the ISSUE 13 soak);
- admission semantics carry over: bounded-queue 429, deadline expiry;
- the ServingLane observes TTFT/decode-queue signals, and its replica
  retargets push into the serving Deployment via the kube glue.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu import telemetry
from edl_tpu.chaos.schedule import FaultEvent, FaultSchedule
from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.models.base import get_model
from edl_tpu.runtime.train import TrainState
from edl_tpu.serving import (
    DecodeEngine,
    KVBlockPool,
    QueueFullError,
    TokenContinuousBatcher,
)

_OPT = optax.adam(1e-3)


def _lm_state(model, step: int, seed: int) -> TrainState:
    """TrainState whose params are the pure function of ``seed`` —
    each hot-swap generation in these tests uses seed == step, so a
    finished sequence's reported ``weights_step`` names exactly one
    parameter set to recompute its reference output with."""
    p = model.init_params(jax.random.key(seed))
    return TrainState(
        step=jnp.asarray(step, jnp.int32),
        params=p,
        opt_state=_OPT.init(p),
    )


def _reference_decode(model, params, prompt, n, engine):
    """Greedy reference through the SAME prefill/decode functions on a
    fresh single-sequence pool (the pure function a finished
    sequence's tokens must equal).  Uses the engine's prompt bucket so
    padding matches the serving path exactly."""
    spec = model.decode
    bt = engine.block_tokens
    mb = engine.blocks_per_seq
    kp = jnp.zeros(
        (spec.layers, mb + 1, bt, spec.heads, spec.head_dim),
        spec.cache_dtype,
    )
    vp = jnp.zeros_like(kp)
    tab = np.arange(1, mb + 1, dtype=np.int32)[None]
    plen = len(prompt)
    P = engine.prompt_bucket_for(plen)
    tok = np.zeros((1, P), np.int32)
    tok[0, :plen] = prompt
    ids, kp, vp = jax.jit(spec.prefill_fn)(
        params, tok, np.asarray([plen], np.int32), kp, vp, tab
    )
    out = [int(ids[0])]
    ln = np.asarray([plen], np.int32)
    dec = jax.jit(spec.decode_fn)
    while len(out) < n:
        ids, kp, vp = dec(
            params, np.asarray([out[-1]], np.int32), ln, kp, vp, tab
        )
        out.append(int(ids[0]))
        ln = ln + 1
    return out


@pytest.fixture(scope="module")
def lm_decode():
    """One warmed transformer_lm DecodeEngine (step 1 / seed 1) — the
    bucket compiles are the expensive part.  Tests that hot-swap build
    their own store+engine instead of mutating this one."""
    model = get_model("transformer_lm", tiny=True)
    store = HostDRAMStore()
    store.save_async(_lm_state(model, 1, 1), generation=0)
    store.wait()
    engine = DecodeEngine(
        model,
        store,
        devices=jax.devices()[:1],
        max_batch=1,
        max_seqs=4,
        block_tokens=16,
    )
    assert engine.load()
    engine.warm()
    return model, store, engine


# -- model-layer contract ----------------------------------------------------


def test_decode_spec_on_the_three_lm_families():
    for name in ("transformer_lm", "moe_lm", "longcontext_lm"):
        m = get_model(name, tiny=True)
        spec = m.decode
        assert spec is not None, name
        assert spec.layers >= 1 and spec.heads >= 1, name
        assert spec.max_len >= 64, name
    # single-shot families stay single-shot
    assert get_model("mnist").decode is None
    assert get_model("fit_a_line").decode is None
    # longcontext_lm is the long-context registry entry
    assert get_model("longcontext_lm", tiny=True).decode.max_len == 128


@pytest.mark.parametrize("name", ["transformer_lm", "longcontext_lm"])
def test_kv_decode_matches_naive_full_recompute(name):
    """The correctness anchor: the incremental path's tokens == the
    single-shot predict path's greedy loop (which recomputes the whole
    prefix every token — the quadratic cost the KV cache retires)."""
    model = get_model(name, tiny=True)
    spec = model.decode
    params = model.init_params(jax.random.key(0))
    L = spec.max_len
    bt = 16
    mb = L // bt
    kp = jnp.zeros(
        (spec.layers, mb + 1, bt, spec.heads, spec.head_dim),
        spec.cache_dtype,
    )
    vp = jnp.zeros_like(kp)
    tab = np.arange(1, mb + 1, dtype=np.int32)[None]
    rng = np.random.RandomState(0)
    prompt = model.synth_batch(rng, 1)["tokens"][0, :20]
    P = 32
    tok = np.zeros((1, P), np.int32)
    tok[0, :20] = prompt
    ids, kp, vp = jax.jit(spec.prefill_fn)(
        params, tok, np.asarray([20], np.int32), kp, vp, tab
    )
    seq = [int(ids[0])]
    ln = np.asarray([20], np.int32)
    dec = jax.jit(spec.decode_fn)
    for _ in range(11):
        ids, kp, vp = dec(
            params, np.asarray([seq[-1]], np.int32), ln, kp, vp, tab
        )
        seq.append(int(ids[0]))
        ln = ln + 1
    naive = list(prompt)
    pf = jax.jit(model.predict_fn)
    for _ in range(12):
        row = np.zeros((1, L + 1), np.int32)
        row[0, : len(naive)] = naive
        out = pf(params, {"tokens": row})["tokens"]
        naive.append(int(out[0, len(naive) - 1]))
    assert seq == naive[20:]


def test_moe_decode_batch_invariant_ragged_lengths():
    """MoE decode routes PER TOKEN (group 1), so a sequence's tokens
    cannot depend on which strangers share its decode batch — the
    capacity-grouping coupling that would break continuous batching is
    compiled out of the decode path."""
    model = get_model("moe_lm", tiny=True)
    spec = model.decode
    params = model.init_params(jax.random.key(1))
    bt = 16
    mb = spec.max_len // bt
    B = 3
    kp = jnp.zeros(
        (spec.layers, B * mb + 1, bt, spec.heads, spec.head_dim),
        spec.cache_dtype,
    )
    vp = jnp.zeros_like(kp)
    rng = np.random.RandomState(2)
    prompts = [
        model.synth_batch(rng, 1)["tokens"][0, :n] for n in (9, 17, 30)
    ]
    pre = jax.jit(spec.prefill_fn)
    tabs = np.zeros((B, mb), np.int32)
    lens = np.zeros(B, np.int32)
    seqs = []
    for i, pr in enumerate(prompts):
        tabs[i] = np.arange(1 + i * mb, 1 + (i + 1) * mb)
        tok = np.zeros((1, 32), np.int32)
        tok[0, : len(pr)] = pr
        ids, kp, vp = pre(
            params,
            tok,
            np.asarray([len(pr)], np.int32),
            kp,
            vp,
            jnp.asarray(tabs[i : i + 1]),
        )
        lens[i] = len(pr)
        seqs.append([int(ids[0])])
    dec = jax.jit(spec.decode_fn)
    kp3, vp3 = kp, vp
    l3 = lens.copy()
    for _ in range(6):
        t = np.asarray([s[-1] for s in seqs], np.int32)
        ids, kp3, vp3 = dec(params, t, l3, kp3, vp3, jnp.asarray(tabs))
        for i in range(B):
            seqs[i].append(int(ids[i]))
        l3 = l3 + 1
    # row 1 decoded ALONE from the same post-prefill cache must emit
    # identical tokens
    kp1, vp1 = kp, vp
    lone = [seqs[1][0]]
    ln = lens[1:2].copy()
    for _ in range(6):
        ids, kp1, vp1 = dec(
            params,
            np.asarray([lone[-1]], np.int32),
            ln,
            kp1,
            vp1,
            jnp.asarray(tabs[1:2]),
        )
        lone.append(int(ids[0]))
        ln = ln + 1
    assert lone == seqs[1]


# -- KV pool -----------------------------------------------------------------


def test_kv_pool_free_list_all_or_nothing_and_trash():
    pool = KVBlockPool(
        2, 4, 16, num_blocks=5, block_tokens=16, dtype=jnp.bfloat16,
        sharding=None,
    )
    assert pool.usable_blocks == 4 and pool.free_blocks == 4
    a = pool.alloc(3)
    assert a is not None and 0 not in a
    assert pool.alloc(2) is None  # only 1 left: no partial grant
    assert pool.free_blocks == 1
    b = pool.alloc(1)
    assert pool.occupancy() == 1.0
    pool.free(a)
    pool.free(b)
    assert pool.free_blocks == 4 and pool.used_blocks == 0
    with pytest.raises(ValueError):
        pool.free([0])  # the trash block is never owned
    with pytest.raises(ValueError):
        KVBlockPool(2, 4, 16, num_blocks=1, block_tokens=16,
                    dtype=jnp.bfloat16, sharding=None)


# -- engine ------------------------------------------------------------------


def test_decode_engine_buckets_and_prompt_validation(lm_decode):
    _, _, engine = lm_decode
    assert engine.decode_buckets == (1, 2, 4)
    assert engine.prompt_buckets == (16, 32, 64)
    assert engine.max_context == 64 and engine.max_prompt == 63
    assert engine.prompt_bucket_for(5) == 16
    assert engine.prompt_bucket_for(17) == 32
    with pytest.raises(ValueError, match="context"):
        engine.prompt_bucket_for(65)
    with pytest.raises(ValueError, match="missing"):
        engine.coerce_prompt({})
    with pytest.raises(ValueError, match="max_prompt"):
        engine.coerce_prompt({"tokens": list(range(64))})
    with pytest.raises(ValueError, match="one token row"):
        engine.coerce_prompt({"tokens": [[1, 2], [3, 4]]})
    # decode warm-held executables cover every bucket: 3 prefill +
    # 3 decode + 6 chunked-prefill (chunk-bucket x window) pairs
    kinds = dict.fromkeys(k[0] for k in engine.warm_decode_buckets)
    assert list(kinds) == ["chunk", "decode", "prefill"]
    assert len(engine.warm_decode_buckets) == 12


def test_decode_steady_state_zero_xla_compiles(lm_decode):
    """Warm engine + varied prompt lengths / join patterns: the whole
    token-iteration path must dispatch held executables only."""
    model, _, engine = lm_decode
    import jax._src.compiler as _compiler

    batcher = TokenContinuousBatcher(engine, default_max_new=5).start()
    rng = np.random.RandomState(7)
    corpus = model.synth_batch(rng, 16)["tokens"]
    real = _compiler.backend_compile
    count = [0]

    def counting(*a, **k):
        count[0] += 1
        return real(*a, **k)

    _compiler.backend_compile = counting
    try:
        tickets = [
            batcher.submit_generate(
                {"tokens": corpus[i][: 3 + 5 * i]}, max_new_tokens=4 + i
            )
            for i in range(6)
        ]
        for t in tickets:
            t.result(timeout=60)
    finally:
        _compiler.backend_compile = real
        batcher.stop()
    assert count[0] == 0, f"{count[0]} XLA compiles on the decode path"
    assert engine.pool.used_blocks == 0


# -- token-iteration scheduling ----------------------------------------------


def test_join_and_leave_at_token_boundaries(lm_decode):
    """A request arriving mid-generation joins the RUNNING batch at
    the next token boundary (it finishes while the earlier longer
    sequence is still decoding), and its joining does not perturb the
    earlier sequence's output."""
    model, _, engine = lm_decode
    batcher = TokenContinuousBatcher(engine).start()
    rng = np.random.RandomState(3)
    pa = model.synth_batch(rng, 1)["tokens"][0, :12]
    pb = model.synth_batch(rng, 1)["tokens"][0, :7]
    a_events = []
    try:
        ta = batcher.submit_generate(
            {"tokens": pa}, max_new_tokens=40, on_event=a_events.append
        )
        # wait until A is demonstrably mid-generation
        deadline = time.monotonic() + 30
        while len(a_events) < 3:
            assert time.monotonic() < deadline
            time.sleep(0.002)
        tb = batcher.submit_generate({"tokens": pb}, max_new_tokens=3)
        b_tokens, b_meta = tb.result(timeout=60)
        # B finished while A was still active: token-boundary join+leave
        assert ta.state == "decoding"
        assert len(b_tokens) == 3
        a_tokens, _ = ta.result(timeout=60)
    finally:
        batcher.stop()
    w = engine.current_weights()
    ref_a = _reference_decode(
        model, w.params, list(pa), len(a_tokens), engine
    )
    ref_b = _reference_decode(model, w.params, list(pb), 3, engine)
    assert a_tokens == ref_a  # the join never perturbed A
    assert b_tokens == ref_b
    assert engine.pool.used_blocks == 0


def test_eos_releases_slots_the_same_iteration(lm_decode):
    """A sequence emitting its EOS leaves the batch and frees its
    blocks the same iteration; non-EOS runs cap at max_new_tokens."""
    model, _, engine = lm_decode
    rng = np.random.RandomState(5)
    prompt = model.synth_batch(rng, 1)["tokens"][0, :10]
    batcher = TokenContinuousBatcher(engine).start()
    try:
        probe, _ = batcher.submit_generate(
            {"tokens": prompt}, max_new_tokens=8
        ).result(timeout=60)
        assert len(probe) == 8
        eos = probe[2]  # a token the run provably emits, now EOS
        toks, meta = batcher.submit_generate(
            {"tokens": prompt}, max_new_tokens=8, eos_id=eos
        ).result(timeout=60)
        # stopped AT the first eos emission (inclusive)
        assert toks == probe[: probe.index(eos) + 1]
        assert engine.pool.used_blocks == 0  # released on finish
    finally:
        batcher.stop()


def test_context_cap_uses_the_full_window(lm_decode):
    """A prompt of max_prompt tokens may still write its first decode
    token at the final cache position: the cap fires only when the
    NEXT write would fall outside the window (regression: an
    off-by-one truncated every near-context generation one token
    early)."""
    model, _, engine = lm_decode
    rng = np.random.RandomState(11)
    prompt = model.synth_batch(rng, 1)["tokens"][0, : engine.max_prompt]
    batcher = TokenContinuousBatcher(engine).start()
    try:
        toks, _ = batcher.submit_generate(
            {"tokens": prompt}, max_new_tokens=5
        ).result(timeout=60)
    finally:
        batcher.stop()
    # prefill emits 1 (no write), the one remaining position takes one
    # decode write: exactly 2 tokens for a max_prompt prompt
    assert len(toks) == 2
    assert engine.pool.used_blocks == 0


def test_failed_dispatch_rebuilds_donated_pools_and_recovers():
    """The pools are DONATED into every dispatch: a call failing at
    execution time may already have consumed them, so the engine must
    rebuild fresh buffers (bumping cache_epoch) instead of keeping
    dangling ones — and the batcher must keep serving afterwards."""
    model = get_model("transformer_lm", tiny=True)
    store = HostDRAMStore()
    store.save_async(_lm_state(model, 1, 1), generation=0)
    store.wait()
    engine = DecodeEngine(
        model, store, devices=jax.devices()[:1], max_batch=1, max_seqs=4
    )
    assert engine.load()
    engine.warm()
    w = engine.current_weights()
    rng = np.random.RandomState(0)
    prompt = model.synth_batch(rng, 1)["tokens"][0, :10]

    def boom(*a, **k):
        raise RuntimeError("device fell over")

    real = engine._decode_compiled[("decode", 1)]
    engine._decode_compiled[("decode", 1)] = boom
    epoch0 = engine.cache_epoch
    with pytest.raises(RuntimeError, match="fell over"):
        engine.decode_step(
            w,
            np.zeros(1, np.int32),
            np.zeros(1, np.int32),
            np.zeros((1, engine.blocks_per_seq), np.int32),
        )
    engine._decode_compiled[("decode", 1)] = real
    assert engine.cache_epoch == epoch0 + 1  # cache declared lost
    # the engine is still serviceable end to end (no dangling buffers)
    batcher = TokenContinuousBatcher(engine).start()
    try:
        toks, meta = batcher.submit_generate(
            {"tokens": prompt}, max_new_tokens=4
        ).result(timeout=60)
    finally:
        batcher.stop()
    ref = _reference_decode(
        model, jax.device_get(w.params), list(prompt), 4, engine
    )
    assert toks == ref
    assert engine.pool.used_blocks == 0


def test_generate_admission_429_and_deadline_expiry(lm_decode):
    model, _, engine = lm_decode
    with telemetry.scoped() as (reg, _):
        chaos = FaultSchedule(
            seed=1, events=[FaultEvent(step=0, point="serve.queue.full")]
        )
        chaos.advance(0)
        batcher = TokenContinuousBatcher(engine, chaos=chaos)
        rng = np.random.RandomState(0)
        prompt = model.synth_batch(rng, 1)["tokens"][0, :8]
        # chaos[serve.queue.full]: forced rejection with a retry hint
        with pytest.raises(QueueFullError) as ei:
            batcher.submit_generate({"tokens": prompt})
        assert ei.value.retry_after > 0
        # queued-dead request: expires, never computes
        from edl_tpu.serving.batcher import DeadlineExceededError

        t = batcher.submit_generate(
            {"tokens": prompt}, deadline_s=0.01
        )
        time.sleep(0.05)
        batcher.start()
        with pytest.raises(DeadlineExceededError):
            t.result(timeout=30)
        batcher.stop()
        req = reg.counter("edl_serve_requests_total")
        assert req.value(status="rejected") == 1
        assert req.value(status="expired") == 1


# -- the ISSUE 13 soak: hot swaps under decode load --------------------------


def test_soak_swaps_under_decode_load_generation_purity():
    """Seeded soak with >= 2 hot swaps landing while sequences are
    mid-generation: every finished sequence's tokens must equal the
    pure function (greedy decode) of the ONE generation it reports —
    a swap re-prefills, never blends — and zero sequences drop."""
    model = get_model("transformer_lm", tiny=True)
    store = HostDRAMStore()
    store.save_async(_lm_state(model, 1, 1), generation=0)
    store.wait()
    engine = DecodeEngine(
        model,
        store,
        devices=jax.devices()[:1],
        max_batch=1,
        max_seqs=4,
        block_tokens=16,
    )
    assert engine.load()
    engine.warm()
    with telemetry.scoped() as (reg, rec):
        batcher = TokenContinuousBatcher(
            engine, default_deadline_s=120.0
        ).start()
        rng = np.random.RandomState(0)
        prompts = [
            model.synth_batch(rng, 1)["tokens"][0, : 6 + (i * 5) % 30]
            for i in range(12)
        ]
        # Two swaps triggered FROM token events of in-flight sequences:
        # each lands deterministically mid-generation (the save runs on
        # the worker thread inside an iteration; the swap is observed
        # at the next token boundary and re-prefills).
        fired = []

        def saver(step):
            def on_event(ev):
                if "token" in ev and ev["i"] == 2 and step not in fired:
                    fired.append(step)
                    store.save_async(
                        _lm_state(model, step, step), generation=step
                    )
                    store.wait()

            return on_event

        results = []
        errors = []

        def client(i, on_event=None):
            try:
                toks, meta = batcher.submit_generate(
                    {"tokens": prompts[i]},
                    max_new_tokens=10,
                    on_event=on_event,
                ).result(timeout=120)
            except BaseException as e:
                errors.append(e)
                return
            results.append((i, toks, meta))

        threads = [
            threading.Thread(
                target=client,
                args=(i,),
                kwargs={
                    "on_event": (
                        saver(2) if i == 2 else saver(3) if i == 7 else None
                    )
                },
            )
            for i in range(12)
        ]
        for t in threads:
            t.start()
            time.sleep(0.004)
        for t in threads:
            t.join(timeout=120)
        batcher.stop()
        assert not errors, f"sequences dropped/failed: {errors[:3]}"
        assert len(results) == 12
        assert len(fired) == 2  # both swaps landed
        restarts = reg.counter("edl_serve_restarts_total").value()
        assert restarts >= 1, "no sequence was mid-generation at a swap"
        kinds = [e.kind for e in rec.events()]
        assert "serve.restart" in kinds
    # purity: each sequence == greedy decode under the generation it
    # reports (seed == step by construction)
    params_by_step = {
        s: jax.device_get(_lm_state(model, s, s).params) for s in (1, 2, 3)
    }
    gens_seen = set()
    for i, toks, meta in results:
        gens_seen.add(meta["weights_step"])
        ref = _reference_decode(
            model,
            params_by_step[meta["weights_step"]],
            list(prompts[i]),
            len(toks),
            engine,
        )
        assert toks == ref, (i, meta)
    assert len(gens_seen) >= 2  # the soak actually crossed generations
    assert engine.pool.used_blocks == 0


# -- HTTP front --------------------------------------------------------------


def test_http_generate_stream_and_nonstream(lm_decode):
    from edl_tpu.serving import ContinuousBatcher, ServingServer

    model, _, engine = lm_decode
    sb = ContinuousBatcher(engine).start()
    gb = TokenContinuousBatcher(engine, refresh=False).start()
    server = ServingServer(sb, host="127.0.0.1", gen_batcher=gb).start()
    base = f"http://127.0.0.1:{server.port}"
    rng = np.random.RandomState(0)
    prompt = model.synth_batch(rng, 1)["tokens"][0, :10].tolist()

    def post(payload):
        req = urllib.request.Request(
            f"{base}/generate",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        return urllib.request.urlopen(req, timeout=30)

    try:
        r = json.loads(
            post({"inputs": {"tokens": prompt}, "max_new_tokens": 5}).read()
        )
        assert len(r["tokens"]) == 5
        assert r["weights_step"] == engine.weights_step
        lines = [
            json.loads(line)
            for line in post(
                {
                    "inputs": {"tokens": prompt},
                    "max_new_tokens": 5,
                    "stream": True,
                }
            ).read().splitlines()
        ]
        assert lines[-1]["done"] and lines[-1]["tokens"] == r["tokens"]
        assert [ln["token"] for ln in lines[:-1]] == r["tokens"]
        # /healthz carries the decode section
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as h:
            health = json.loads(h.read())
        assert health["decode"]["max_seqs"] == engine.max_seqs
        # bad prompt -> 400
        try:
            post({"inputs": {}})
            raise AssertionError("expected HTTP 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        server.stop()
        sb.stop()
        gb.stop()


def test_replica_token_batcher_owns_refresh(lm_decode):
    """Regression (found driving the live flow): a generate-only fleet
    gets refresh() from NOBODY unless the token batcher drives it —
    the single-shot worker only refreshes while ITS queue has traffic,
    so training's newer durable spills were never observed."""
    from edl_tpu.serving import ServingReplica

    _, _, engine = lm_decode
    replica = ServingReplica(engine, replica_id="serve-x")
    assert replica.gen_batcher is not None
    assert replica.gen_batcher.refresh  # the swap path for /generate


# -- autoscaler + kube glue --------------------------------------------------


def test_serving_lane_observes_ttft_and_decode_queue():
    """The lane reads the decode fleet's signals: TTFT p95 over the
    window delta actuates when ttft_high_s is set, and decode-queue
    depth folds into the queue-pressure band."""
    from edl_tpu.autoscaler.serving import ServingLane

    with telemetry.scoped():
        reg = telemetry.MetricsRegistry()
        h = reg.histogram("edl_serve_ttft_seconds")
        for _ in range(30):
            h.observe(1.2)

        class _Coord:
            target = 1
            calls = []

            def telemetry(self):
                return {
                    "merged": {
                        "counters": {},
                        "gauges": {
                            "edl_serve_decode_queue_depth": {"": 2},
                            "edl_serve_kv_occupancy": {"": 0.8},
                        },
                        "histograms": {
                            "edl_serve_ttft_seconds": reg.snapshot()[
                                "histograms"
                            ]["edl_serve_ttft_seconds"]
                        },
                    }
                }

            def metrics(self):
                return {"target_world": self.target}

            def set_prewarm(self, n, trace_id=""):
                pass

            def set_target_world(self, n, trace_id=""):
                self.target = n

        coord = _Coord()
        lane = ServingLane(
            coord, min_replicas=1, max_replicas=4, ttft_high_s=0.5
        )
        entry = lane.run_once()
        obs = entry["observed"]
        assert obs["ttft_p95_s"] > 0.5
        assert obs["decode_queue_depth"] == 2
        assert obs["kv_occupancy"] == 0.8
        assert entry["dry_run"]["proposed"] == 2 and entry["actuated"]
        assert "ttft" in entry["reason"]
        # without the threshold the same TTFT is observe-only: depth 2
        # is under the band and nothing else is hot, so no actuation
        lane2 = ServingLane(coord, min_replicas=1, max_replicas=4)
        e2 = lane2.run_once()
        assert e2["observed"]["ttft_p95_s"] is not None
        assert e2["reason"] == "within band" and not e2["actuated"]


def test_kube_replica_glue_moves_the_serving_deployment():
    """ISSUE 13 satellite: a ServingLane retarget pushes the decided
    replica count into the serving replica Deployment through the
    bounded-retry update_serving_replicas idiom (not just the
    coordinator target)."""
    from edl_tpu.autoscaler.serving import ServingLane, kube_replica_glue
    from edl_tpu.cluster.cluster import Cluster
    from edl_tpu.cluster.kube import FakeKube, NodeInfo
    from edl_tpu.controller.jobparser import parse_to_serving_manifests
    from edl_tpu.resource.training_job import TrainingJob

    with telemetry.scoped():
        job = TrainingJob.from_yaml(
            """
apiVersion: edl.tpu.dev/v1
kind: TrainingJob
metadata: {name: serve-glue}
spec:
  fault_tolerant: true
  global_batch_size: 64
  checkpoint_dir: /ckpts
  trainer:
    entrypoint: mnist
    min_instance: 1
    max_instance: 4
    slice_topology: cpu
  serving:
    min_replicas: 1
    max_replicas: 4
"""
        ).validate()
        kube = FakeKube(
            [NodeInfo(name="n0", cpu_milli=64000, memory_mega=262144,
                      tpu_chips=8)]
        )
        cluster = Cluster(kube)
        kube.apply_manifests(parse_to_serving_manifests(job))
        dep = kube.get_workload(job.serving_name(), kind="Deployment")
        assert dep is not None and dep.parallelism == 1

        class _Coord:
            target = 1

            def telemetry(self):
                return {
                    "merged": {
                        "counters": {},
                        "gauges": {"edl_serve_queue_depth": {"": 50}},
                        "histograms": {},
                    }
                }

            def metrics(self):
                return {"target_world": self.target}

            def set_prewarm(self, n, trace_id=""):
                pass

            def set_target_world(self, n, trace_id=""):
                self.target = n

        coord = _Coord()
        lane = ServingLane(
            coord,
            min_replicas=1,
            max_replicas=4,
            on_scale=kube_replica_glue(cluster, job),
        )
        entry = lane.run_once()
        assert entry["actuated"] and entry["dry_run"]["proposed"] == 2
        after = kube.get_workload(job.serving_name(), kind="Deployment")
        assert after.parallelism == 2  # the Deployment followed


# -- CLI ---------------------------------------------------------------------


def test_cli_metrics_prints_decode_stats(capsys):
    from edl_tpu.cli import main
    from edl_tpu.runtime.coord_service import CoordinatorServer
    from edl_tpu.runtime.coordinator import LocalCoordinator

    coord = LocalCoordinator(target_world=1, max_world=2)
    coord.register("serve-0")
    reg = telemetry.MetricsRegistry()
    reg.counter("edl_serve_tokens_total").inc(480)
    h = reg.histogram("edl_serve_ttft_seconds")
    for _ in range(10):
        h.observe(0.012)
    it = reg.histogram("edl_serve_intertoken_seconds")
    for _ in range(470):
        it.observe(0.002)
    reg.gauge("edl_serve_kv_occupancy").set(0.625)
    # chunked-prefill stats (ISSUE 14 satellite): queued tokens, chunk
    # iterations, and the stall the admission imposed
    reg.counter("edl_serve_prefill_chunks_total").inc(37)
    reg.counter("edl_serve_prefill_tokens_total").inc(1850)
    reg.gauge("edl_serve_prefill_queued_tokens").set(96)
    st = reg.histogram("edl_serve_prefill_stall_seconds")
    for _ in range(20):
        st.observe(0.004)
    coord.report_telemetry("serve-0", snapshot=reg.snapshot(), seq=1)
    server = CoordinatorServer(coord, host="127.0.0.1", port=0).start(
        evict=False
    )
    try:
        assert main(["metrics", f"127.0.0.1:{server.port}"]) == 0
        out = capsys.readouterr().out
        assert "tokens_total" in out and "480" in out
        assert "decode_tokens_per_s" in out
        assert "ttft_p50" in out and "ttft_p95" in out
        assert "intertoken_p95" in out
        assert "kv_slot_occupancy" in out and "0.625" in out
        assert "prefill_chunks_total" in out and "37" in out
        assert "prefill_tokens_total" in out and "1850" in out
        assert "queued_prefill_tokens" in out and "96" in out
        assert "prefill_stall_p95" in out
    finally:
        server.stop()
