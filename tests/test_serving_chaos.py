"""ISSUE 15 acceptance: the seeded multi-replica serving chaos soak.

One driver thread walks a ``FaultSchedule`` through a 4-replica
serving fleet under live traffic:

- a **replica kill** mid-flight (``serve.replica.die`` — the SIGKILL
  shape): its in-flight requests FAIL and are resolved by retry
  against survivors;
- a **torn swap** (``serve.swap.torn``): the shared store's newest
  candidate rots; every engine rejects it ONCE (the dedup contract)
  and keeps serving, then swaps cleanly to the next good save;
- a **wedged decode dispatch** (``serve.dispatch.wedged``): the
  dispatch watchdog trips into pool-rebuild + cache-epoch re-prefill;
  the wedged sequences COMPLETE with generation-pure tokens;
- two **graceful drains** (one decode replica with live generations,
  one single-shot replica under ``serve.drain.slow``): zero in-flight
  requests dropped, KV blocks freed, deregistered;
- a **coordinator restart** + a ``serve.coord.unreachable`` blackout:
  replicas keep serving last-verified weights and membership
  reconverges via the heartbeat rejoin path.

Determinism contract: run twice with the same seed, the flight
recorder's order-independent ``digest()`` AND the driver's structured
soak log are bit-identical.  Everything scheduling-dependent (drain
durations, in-flight counts at the drain moment) rides the recorder's
non-identity ``timing`` field or stays out of the log; the driver
advances the chaos clock and then WAITS for thread-consumed points to
pop before moving on, so every chaos event journals at its scheduled
step.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu import telemetry
from edl_tpu.chaos.membership import ChaosCoordinator
from edl_tpu.chaos.schedule import FaultEvent, FaultSchedule
from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.models.base import get_model
from edl_tpu.runtime.coordinator import LocalCoordinator
from edl_tpu.runtime.train import TrainState
from edl_tpu.serving import (
    DecodeEngine,
    InferenceEngine,
    RetryingClient,
    ServingReplica,
)
from tests.test_decode_serving import _reference_decode

_OPT = optax.adam(1e-3)


def _line_state(g: float) -> TrainState:
    params = {
        "w": jnp.full((13,), g, jnp.float32),
        "b": jnp.asarray(g, jnp.float32),
    }
    return TrainState(
        step=jnp.asarray(int(g), jnp.int32),
        params=params,
        opt_state=_OPT.init(params),
    )


def _lm_state(model, step: int, seed: int) -> TrainState:
    p = model.init_params(jax.random.key(seed))
    return TrainState(
        step=jnp.asarray(step, jnp.int32),
        params=p,
        opt_state=_OPT.init(p),
    )


def _soak_events():
    return [
        FaultEvent(3, "serve.swap.torn"),
        FaultEvent(5, "serve.replica.die", arg=1),
        FaultEvent(8, "serve.dispatch.wedged"),
        FaultEvent(11, "serve.drain.slow", arg=0.02),
        FaultEvent(14, "coord.restart"),
        FaultEvent(14, "serve.coord.unreachable", arg=1.0),
    ]


def _wait(cond, timeout=20.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.005)
    raise AssertionError(f"soak wait timed out: {what}")


def _run_serving_soak(seed: int):
    """One full soak.  Returns everything that must be bit-identical
    across same-seed runs (recorder digest + the structured log) plus
    the run's invariant evidence."""
    with telemetry.scoped() as (reg, rec):
        schedule = FaultSchedule(seed, _soak_events())
        log = []

        # -- the fleet -------------------------------------------------------
        store = HostDRAMStore(chaos=schedule)  # shared by the 3 liners
        store.save_async(_line_state(1.0), generation=0)
        store.wait()
        inner = LocalCoordinator(
            target_world=8, max_world=8, heartbeat_timeout=1e9
        )
        coord = ChaosCoordinator(inner, schedule)
        fit = []
        for i in range(3):
            engine = InferenceEngine(
                get_model("fit_a_line"),
                store,
                devices=jax.devices()[:1],
                max_batch=4,
                chaos=schedule,
            )
            fit.append(
                ServingReplica(
                    engine,
                    coordinator=coord,
                    replica_id=f"serve-{i}",
                    heartbeat_interval=0.05,
                    telemetry_interval=1e9,
                ).start()
            )
        lm = get_model("transformer_lm", tiny=True)
        dstore = HostDRAMStore()  # decode weights: own store, no chaos
        dstore.save_async(_lm_state(lm, 1, 1), generation=0)
        dstore.wait()
        dengine = DecodeEngine(
            lm,
            dstore,
            devices=jax.devices()[:1],
            max_batch=1,
            max_seqs=4,
            block_tokens=16,
        )
        # the wedge trip routes through the DISPATCH chaos seam only —
        # the shared schedule's swap-torn events stay with the liners
        dengine.dispatch_chaos = schedule
        drep = ServingReplica(
            dengine,
            coordinator=coord,
            replica_id="serve-d",
            heartbeat_interval=0.05,
            telemetry_interval=1e9,
        ).start()
        rng = np.random.RandomState(seed)
        x0 = np.ones((1, 13), np.float32)

        def call(order, x):
            """The client retry contract — the shared library now
            (ISSUE 20): queue-full backs off HERE, drain/kill
            failures route to the next replica."""
            return RetryingClient(
                list(order),
                submit=lambda b, req: (
                    b.batcher.submit(req).result(timeout=15)
                ),
                budget_s=15.0,
            ).call({"x": x})

        def check(out, x, g):
            np.testing.assert_allclose(
                out["pred"],
                g * (x.sum(axis=1) + 1.0),
                rtol=1e-4,
                atol=1e-3,
            )

        def wave(tag, order, n=3):
            """n validated requests; the log records (tag, i, step)."""
            for i in range(n):
                x = rng.randn(1, 13).astype(np.float32)
                out, meta = call(order, x)
                check(out, x, float(meta["weights_step"]))
                log.append((tag, i, meta["weights_step"]))

        def barrier(replicas, step):
            """Pump traffic until every engine serves ``step`` (workers
            only refresh when traffic flows).  Pump requests stay out
            of the log: their count is scheduling-dependent."""
            for r in replicas:
                _wait_swap(r, step)

        def _wait_swap(r, step):
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                if r.engine.weights_step == step:
                    return
                r.batcher.submit({"x": x0}).result(timeout=15)
                time.sleep(0.002)
            raise AssertionError(f"{r.replica_id} never reached {step}")

        try:
            # -- rounds 0-2: healthy traffic, one clean swap ---------------
            schedule.advance(0)
            wave("warm", fit)
            store.save_async(_line_state(3.0), generation=1)
            store.wait()
            barrier(fit, 3)
            wave("post-swap", fit)
            log.append(("swap", 3))

            # -- round 3: torn swap ----------------------------------------
            # A newer candidate (g=5) lands, then the chaos clock makes
            # the next refresh corrupt it: every engine must reject it
            # exactly once (dedup) and keep serving step 3.
            store.save_async(_line_state(5.0), generation=2)
            store.wait()
            schedule.advance(3)
            # Pump traffic round-robin: a parked worker never
            # refreshes, so the fleet needs live requests to observe
            # the torn candidate.  The FIRST refresh to see it pops
            # the chaos, fails CRC verification, and the store
            # DISCARDS it — exactly one rejection fleet-wide (whoever
            # wins the race journals the identical event: the shared
            # store serves the same steps to every engine).
            m_rejected = reg.counter("edl_serve_swap_rejected_total")
            deadline = time.monotonic() + 20
            while m_rejected.value() < 1:
                assert time.monotonic() < deadline, (
                    "torn candidate never rejected"
                )
                for r in fit:
                    r.batcher.submit({"x": x0}).result(timeout=15)
                time.sleep(0.002)
            assert not any(
                ev.point == "serve.swap.torn" for ev in schedule.pending()
            )
            assert int(store.latest().step) == 3  # torn 5 discarded
            wave("during-torn", fit)  # still serving step 3
            assert m_rejected.value() == 1
            log.append(("torn-rejected", 5))
            store.save_async(_line_state(7.0), generation=3)
            store.wait()
            barrier(fit, 7)
            log.append(("swap", 7))

            # -- round 5: replica kill mid-flight --------------------------
            schedule.advance(5)
            xs = [rng.randn(1, 13).astype(np.float32) for _ in range(4)]
            tickets = [
                fit[1].batcher.submit({"x": x}) for x in xs
            ]
            for ev in schedule.due("serve.replica.die"):
                fit[int(ev.arg)].die()
            survivors = [fit[0], fit[2]]
            for i, (t, x) in enumerate(zip(tickets, xs)):
                try:
                    out, meta = t.result(timeout=15)
                except BaseException:
                    # the retry contract: a killed replica's request is
                    # resolved against a survivor
                    out, meta = call(survivors, x)
                check(out, x, float(meta["weights_step"]))
                log.append(("kill-resolved", i, meta["weights_step"]))
            # a dead pod never deregisters — membership still lists it
            assert "serve-1" in coord.members()

            # -- round 8: wedged decode dispatch ---------------------------
            prompts = [
                lm.synth_batch(np.random.RandomState(41), 1)["tokens"][
                    0, :9
                ],
                lm.synth_batch(np.random.RandomState(42), 1)["tokens"][
                    0, :13
                ],
            ]
            gens = [
                drep.gen_batcher.submit_generate(
                    {"tokens": p}, max_new_tokens=40, deadline_s=60.0
                )
                for p in prompts
            ]
            _wait(
                lambda: drep.gen_batcher.active_count == 2
                and all(t.tokens for t in gens),
                what="2 active decode sequences",
            )
            schedule.advance(8)  # the next decode dispatch wedges
            _wait(
                lambda: not any(
                    ev.point == "serve.dispatch.wedged"
                    for ev in schedule.pending()
                ),
                what="wedge consumed",
            )
            w = dengine.current_weights()
            for i, (t, p) in enumerate(zip(gens, prompts)):
                tokens, meta = t.result(timeout=60)
                assert meta["restarts"] == 1, "wedge must re-prefill"
                ref = _reference_decode(lm, w.params, list(p), 40, dengine)
                assert tokens == ref, "post-wedge tokens impure"
                log.append(
                    ("wedge-recovered", i, len(tokens), meta["restarts"])
                )
            assert (
                reg.counter("edl_serve_dispatch_wedged_total").value()
                == 1
            )

            # -- rounds 11-12: graceful drains -----------------------------
            # (a) the decode replica with LIVE generations: every
            # in-flight sequence completes, KV frees, deregistered.
            gens = [
                drep.gen_batcher.submit_generate(
                    {"tokens": prompts[i]},
                    max_new_tokens=24,
                    deadline_s=60.0,
                )
                for i in range(2)
            ]
            _wait(
                lambda: drep.gen_batcher.active_count == 2,
                what="2 active pre-drain",
            )
            r = drep.drain(budget_s=60.0)
            assert r["drained"] and r["in_flight"] == 0
            for i, t in enumerate(gens):
                tokens, meta = t.result(timeout=1.0)
                assert len(tokens) == 24
                log.append(("drain-decode-completed", i, len(tokens)))
            assert dengine.pool.used_blocks == 0
            assert "serve-d" not in coord.members()
            # (b) a single-shot replica under serve.drain.slow: the
            # budget still bounds the drain; queued requests complete.
            schedule.advance(11)
            t2 = [fit[2].batcher.submit({"x": x0}) for _ in range(3)]
            r2 = fit[2].drain(budget_s=30.0)
            assert r2["drained"]
            for t in t2:
                out, _ = t.result(timeout=1.0)
            assert "serve-2" not in coord.members()
            log.append(("drains-acked", 2))

            # -- round 14: coordinator restart + blackout ------------------
            schedule.advance(14)
            for ev in schedule.due("serve.coord.unreachable"):
                fit[0].blackout(float(ev.arg))
            for ev in schedule.due("coord.restart"):
                coord.restart(
                    lambda: LocalCoordinator(
                        target_world=8,
                        max_world=8,
                        heartbeat_timeout=1e9,
                    )
                )
            log.append(("coord-restart", 14))
            # the coordinator vanished AND lost all state: the replica
            # keeps serving last-verified weights through the blackout
            wave("during-blackout", [fit[0]])
            # ...and membership reconverges once the blackout lifts:
            # the lone survivor re-registers via the heartbeat rejoin
            _wait(
                lambda: set(coord.members()) == {"serve-0"},
                timeout=30,
                what="membership reconvergence",
            )
            log.append(("reconverged", sorted(coord.members())))
            wave("final", [fit[0]])

            assert schedule.pending() == []
            ok = reg.counter("edl_serve_requests_total").value(
                status="ok"
            )
            return {
                "digest": rec.digest(),
                "log": list(log),
                "pending": schedule.pending(),
                "ok_requests": ok,
            }
        finally:
            for r in fit + [drep]:
                try:
                    r.stop()
                except Exception:
                    pass


@pytest.mark.chaos
def test_serving_chaos_soak_bit_reproducible():
    """Acceptance (ISSUE 15): kills + torn swap + wedged dispatch +
    drains + coordinator restart under live traffic — drained replicas
    drop ZERO in-flight requests, killed replicas' requests resolve by
    retry against survivors, wedged dispatches recover with pure
    tokens, and two same-seed runs journal BIT-IDENTICALLY (recorder
    digest + the driver log)."""
    r1 = _run_serving_soak(seed=2024)
    assert r1["pending"] == []
    assert r1["ok_requests"] > 0
    r2 = _run_serving_soak(seed=2024)
    assert r1["digest"] == r2["digest"], "journals diverged across reruns"
    assert r1["log"] == r2["log"]
