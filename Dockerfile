# Trainer/controller image: the manifests rendered by
# edl_tpu/controller/jobparser.py reference edl-tpu/trainer:latest
# (resource/training_job.py DEFAULT_IMAGE).  Build with:
#
#   docker build -t edl-tpu/trainer:latest .
#
# One image serves every role — trainer pods (launcher), coordinator
# Deployments (cli coordinator), and the controller daemon — selected
# by the command the manifest sets (ref analog: a single Go binary
# image, /root/reference/Dockerfile:1-9).
#
# Base: upstream JAX TPU image keeps libtpu/jax in lockstep; swap the
# tag to pin versions.
FROM python:3.12-slim

WORKDIR /opt/edl-tpu

# kubectl: the controller's cluster I/O layer (KubectlAPI) shells out
# to it; without this binary `edl controller` cannot run in-cluster.
# -f fails the build on HTTP errors (never bake an error page in as
# the binary); TARGETARCH keeps arm64 builds runnable.
ARG TARGETARCH=amd64
RUN apt-get update && apt-get install -y --no-install-recommends curl ca-certificates \
    && KVER="$(curl -fsSL https://dl.k8s.io/release/stable.txt)" \
    && curl -fsSL -o /usr/local/bin/kubectl \
        "https://dl.k8s.io/release/${KVER}/bin/linux/${TARGETARCH}/kubectl" \
    && chmod +x /usr/local/bin/kubectl \
    && apt-get purge -y curl && apt-get autoremove -y && rm -rf /var/lib/apt/lists/*

# TPU wheels live on the libtpu index; CPU-only builds (CI, controller
# nodes) work with the same install because jax[tpu] degrades to CPU
# when no TPU is attached.
RUN pip install --no-cache-dir "jax[tpu]" flax optax \
    -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

COPY pyproject.toml README.md ./
COPY edl_tpu ./edl_tpu
RUN pip install --no-cache-dir .

# Trainer pods override via the TrainingJob spec entrypoint; default is
# the CLI (controller/coordinator roles pass their subcommand).
ENTRYPOINT ["edl"]
CMD ["--help"]
