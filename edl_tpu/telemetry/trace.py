"""Cluster-wide causal tracing: one trace id from autoscaler decision
to first post-resize step, and one merged Perfetto timeline.

Six PRs built per-process journals (flight recorders, resize phase
breakdowns, consensus events) that every cross-process question —
"which member quiesced late?", "where did the resize regression come
from?" — had to answer by hand-merging inside individual tests.  This
module is the instrument that merges them:

- **Trace context**: the autoscaler mints a ``trace_id`` per actuation
  decision (``new_trace_id``); it rides the ``/prewarm`` hint and the
  retarget PUT into ``ElasticPlan.trace_id``, which every member's
  resize path installs as the flight recorder's ambient trace
  (``FlightRecorder.set_trace``) — so the decision, the plan rebuild,
  the consensus votes/stop/quiesce, the flush/transfer/restore, and
  the first post-resize step all journal under ONE id.  Plan rebuilds
  with no pending decision (joins, evictions) mint their own, so every
  resize is traceable.  Trace ids live in the events' NON-identity
  fields: chaos-soak journal digests stay bit-identical with tracing
  on.

- **Clock alignment**: ``ClockOffsetEstimator`` derives each member's
  wall-clock offset vs the coordinator NTP-style from heartbeat
  request/response pairs (client stamps t0/t1, server returns its
  time; ``offset = server - (t0+t1)/2``, min-RTT filtered so an
  asymmetric or congested sample cannot dominate).  Members report
  their estimate on the telemetry cadence; the merger shifts each
  member's events onto the coordinator timeline before ordering.

- **Merged timeline**: ``merge_events`` + ``chrome_trace`` turn the
  coordinator journal plus the member journals/spills into one
  Chrome-trace/Perfetto JSON — pid = member (lane per member), tid =
  subsystem (resize / consensus / checkpoint / ...), duration slices
  from events that carry ``timing`` (a resize's phase breakdown
  renders as nested slices), instants for everything else.  Open the
  file at ui.perfetto.dev or chrome://tracing.

Everything here is stdlib-only and jax-free: the merger must run in a
post-mortem CLI (``edl trace``) on a machine with nothing installed.
"""

from __future__ import annotations

import json
import uuid
from collections import deque
from typing import Dict, List, Optional, Tuple

# The serial resize-window phases, shared with the goodput ledger so
# the Perfetto child slices and the resizing:<phase> decomposition can
# never silently desync when a phase is added/renamed in elastic.py.
from edl_tpu.telemetry.ledger import RESIZE_PHASES as _SERIAL_PHASES

__all__ = [
    "ClockOffsetEstimator",
    "chrome_trace",
    "load_journal",
    "member_streams",
    "merge_events",
    "new_trace_id",
    "subsystem_of",
    "trace_chains",
]


def new_trace_id() -> str:
    """Mint a causal-trace correlation id (one per autoscaler decision
    / coordinator plan rebuild)."""
    return uuid.uuid4().hex[:16]


# ---------------------------------------------------------------------------
# clock alignment
# ---------------------------------------------------------------------------
class ClockOffsetEstimator:
    """NTP-style offset of a remote (server) clock vs the local one.

    Feed it request/response pairs: ``add(t0, server_time, t1)`` with
    t0/t1 the LOCAL wall clock around the round-trip and
    ``server_time`` the server's wall clock mid-handling.  The classic
    estimate ``offset = server_time - (t0 + t1) / 2`` is exact for
    symmetric network delay and off by at most RTT/2 otherwise, so
    ``offset()`` returns the estimate from the minimum-RTT sample in a
    sliding window — congestion spikes and asymmetric stragglers decay
    out instead of polluting the alignment."""

    def __init__(self, window: int = 32):
        #: (rtt, offset) samples, newest last
        self._samples: deque = deque(maxlen=max(2, window))

    def add(self, t0: float, server_time: float, t1: float) -> float:
        """Record one round-trip sample; returns its raw offset."""
        rtt = max(0.0, float(t1) - float(t0))
        offset = float(server_time) - (float(t0) + float(t1)) / 2.0
        self._samples.append((rtt, offset))
        return offset

    def offset(self) -> Optional[float]:
        """Best current estimate: the min-RTT sample's offset (add to
        LOCAL wall time to get server time).  None until a sample."""
        if not self._samples:
            return None
        return min(self._samples)[1]

    def rtt(self) -> Optional[float]:
        """The filter's minimum observed round-trip (= 2x the bound on
        the offset estimate's error)."""
        if not self._samples:
            return None
        return min(self._samples)[0]

    def sample_count(self) -> int:
        return len(self._samples)


# ---------------------------------------------------------------------------
# journal loading / splitting
# ---------------------------------------------------------------------------
def load_journal(path: str) -> List[dict]:
    """Read a flight-recorder JSONL spill (tolerates a torn final line
    — crashed pods tear their last write)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail line
    return out


def member_streams(
    events: List[dict], coordinator: str = "coordinator"
) -> Dict[str, List[dict]]:
    """Split a coordinator journal into per-member streams: ingested
    member tails carry ``data.origin`` (``FlightRecorder.ingest``);
    everything else is the coordinator's own lane."""
    streams: Dict[str, List[dict]] = {}
    for ev in events:
        origin = (ev.get("data") or {}).get("origin") or coordinator
        streams.setdefault(origin, []).append(ev)
    return streams


def subsystem_of(kind: str) -> str:
    """The timeline lane (tid) an event kind renders on: its first
    dotted segment (``consensus.vote`` -> ``consensus``); bare kinds
    map to themselves (``resize`` -> ``resize``)."""
    return kind.split(".", 1)[0] if kind else "event"


# ---------------------------------------------------------------------------
# the merger
# ---------------------------------------------------------------------------
def merge_events(
    streams: Dict[str, List[dict]],
    offsets: Optional[Dict[str, float]] = None,
) -> List[dict]:
    """Merge per-member event streams onto one causally-ordered
    timeline.  Each returned event is a copy with two added fields:
    ``member`` (its lane) and ``wall_aligned`` (its wall clock shifted
    by the member's estimated offset onto the coordinator timeline).
    Sorted by ``wall_aligned`` with (member, seq) as the tiebreak, so
    same-instant events order deterministically."""
    offsets = offsets or {}
    merged: List[dict] = []
    for member, evs in streams.items():
        off = float(offsets.get(member) or 0.0)
        for ev in evs:
            e = dict(ev)
            e["member"] = member
            e["wall_aligned"] = float(e.get("wall") or 0.0) + off
            merged.append(e)
    merged.sort(
        key=lambda e: (
            e["wall_aligned"],
            e["member"],
            int(e.get("seq") or 0),
        )
    )
    return merged


#: overlapped background phases: parallel slices from the window start
#: (the serial phases render back-to-back — see _SERIAL_PHASES above)
_OVERLAP_PHASES = ("compile", "flush_bg")


def _event_args(ev: dict) -> dict:
    args = {
        "step": ev.get("step"),
        "generation": ev.get("generation"),
    }
    if ev.get("trace"):
        args["trace"] = ev["trace"]
    for k, v in (ev.get("data") or {}).items():
        args[k] = v
    return args


def chrome_trace(
    events: List[dict], trace_id: str = ""
) -> dict:
    """Render merged events (``merge_events`` output) as a Chrome
    trace / Perfetto JSON document: pid = member, tid = subsystem,
    ``X`` duration slices for events carrying ``timing.seconds``
    (ending at the event's wall stamp — flight events journal at
    completion), nested phase slices for resizes, ``i`` instants for
    everything else.  ``trace_id`` filters to one causal chain."""
    if trace_id:
        events = [e for e in events if e.get("trace") == trace_id]
    out: List[dict] = []
    pids: Dict[str, int] = {}
    tids: Dict[Tuple[str, str], int] = {}
    if events:
        base = min(e["wall_aligned"] for e in events)
    else:
        base = 0.0

    def us(t: float) -> float:
        return round((t - base) * 1e6, 1)

    def pid(member: str) -> int:
        p = pids.get(member)
        if p is None:
            p = pids[member] = len(pids) + 1
            out.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": p,
                    "args": {"name": member},
                }
            )
        return p

    def tid(member: str, subsystem: str) -> int:
        key = (member, subsystem)
        t = tids.get(key)
        if t is None:
            t = tids[key] = (
                len([k for k in tids if k[0] == member]) + 1
            )
            out.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid(member),
                    "tid": t,
                    "args": {"name": subsystem},
                }
            )
        return t

    for ev in events:
        member = ev["member"]
        kind = ev.get("kind") or "event"
        sub = subsystem_of(kind)
        p, t = pid(member), tid(member, sub)
        end = ev["wall_aligned"]
        timing = ev.get("timing") or {}
        seconds = timing.get("seconds")
        args = _event_args(ev)
        if seconds:
            start = end - float(seconds)
            out.append(
                {
                    "name": kind,
                    "ph": "X",
                    "pid": p,
                    "tid": t,
                    "ts": us(start),
                    "dur": round(float(seconds) * 1e6, 1),
                    "args": args,
                }
            )
            phases = timing.get("phases") or {}
            cursor = start
            for ph_name in _SERIAL_PHASES:
                s = phases.get(ph_name)
                if not s:
                    continue
                out.append(
                    {
                        "name": f"{kind}/{ph_name}",
                        "ph": "X",
                        "pid": p,
                        "tid": t,
                        "ts": us(cursor),
                        "dur": round(float(s) * 1e6, 1),
                        "args": {"phase": ph_name},
                    }
                )
                cursor += float(s)
            for ph_name in _OVERLAP_PHASES:
                s = phases.get(ph_name)
                if not s:
                    continue
                # Overlapped background work: parallel slice on its
                # own lane so the overlap (join << duration) is the
                # visible shape, not a fabricated serialization.
                out.append(
                    {
                        "name": f"{kind}/{ph_name}",
                        "ph": "X",
                        "pid": p,
                        "tid": tid(member, f"{sub}/overlap"),
                        "ts": us(start),
                        "dur": round(float(s) * 1e6, 1),
                        "args": {"phase": ph_name, "overlapped": True},
                    }
                )
        else:
            out.append(
                {
                    "name": kind,
                    "ph": "i",
                    "pid": p,
                    "tid": t,
                    "ts": us(end),
                    "s": "t",  # thread-scoped instant
                    "args": args,
                }
            )
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def trace_chains(events: List[dict]) -> Dict[str, List[dict]]:
    """Group merged events by trace id (the causal chains); untraced
    events are dropped.  Each chain keeps the merged order."""
    chains: Dict[str, List[dict]] = {}
    for ev in events:
        t = ev.get("trace")
        if t:
            chains.setdefault(t, []).append(ev)
    return chains
