"""The flight recorder: a deterministic, stamped structured journal.

Every event that matters to a post-mortem — resizes, retries, chaos
injections, checkpoint saves, transfer summaries, world breaks,
autoscaler decisions — lands here as a ``FlightEvent`` stamped with
(seq, step, generation, kind, data).  The buffer is a bounded ring
(``capacity`` events) with an optional JSONL spill, so a crashed soak
leaves its last N events on disk even when the process dies.

Determinism contract: the *identity* of an event is (step, generation,
kind, canonical-JSON(data)) — ``digest()`` hashes exactly that, as an
order-independent multiset, so two same-seed chaos runs produce the
same digest even when background threads (async saves, heartbeats)
interleave their records differently.  Wall-clock timestamps and
duration measurements are carried in the separate ``wall`` / ``timing``
fields and excluded from the digest: they are diagnostics, not
identity.

Writers that only know *when* (not *where in training*) an event
happened inherit the step/generation from the recorder's context,
which the elastic step loop refreshes at every step boundary.

Trace context: events may carry a cluster-wide ``trace`` id (the
causal-tracing correlation key minted per autoscaler decision /
coordinator plan rebuild, ``edl_tpu.telemetry.trace``).  Like ``wall``
and ``timing`` it is a NON-identity field — trace ids are random, and
including them in ``identity()`` would break the chaos-soak digest
determinism contract.  ``set_trace`` installs an ambient trace id that
stamps every subsequent record until cleared.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

#: default JSONL spill rotation bound (``EDL_FLIGHT_RECORDER_MAX_MB``)
DEFAULT_SPILL_MAX_MB = 64.0

#: after a spill write failure, skip (and count) spill attempts for
#: this many seconds before retrying — a gone disk must not charge an
#: open() syscall to every recorded event
SPILL_RETRY_SECONDS = 5.0


def json_safe(v: Any) -> Any:
    """Coerce arbitrary payload values to something JSON-serializable
    (chaos event args can be rich objects; the journal stores their
    repr rather than failing the injection that carried them)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): json_safe(x) for k, x in v.items()}
    return repr(v)


@dataclass(frozen=True)
class FlightEvent:
    seq: int
    step: int
    generation: int
    kind: str
    data: Dict[str, Any] = field(default_factory=dict)
    #: wall-clock timestamp — diagnostics only, excluded from digest()
    wall: float = 0.0
    #: non-deterministic measurements (durations...), excluded too
    timing: Optional[Dict[str, Any]] = None
    #: causal-trace correlation id (autoscaler decision -> resize);
    #: random per decision, so excluded from identity/digest too
    trace: str = ""

    def identity(self) -> str:
        """The deterministic part, canonically serialized."""
        return json.dumps(
            [self.step, self.generation, self.kind, self.data],
            sort_keys=True,
            separators=(",", ":"),
        )

    def to_dict(self) -> dict:
        d = {
            "seq": self.seq,
            "step": self.step,
            "generation": self.generation,
            "kind": self.kind,
            "data": self.data,
            "wall": self.wall,
        }
        if self.timing:
            d["timing"] = self.timing
        if self.trace:
            d["trace"] = self.trace
        return d


class FlightRecorder:
    """Bounded, thread-safe event journal with optional JSONL spill."""

    def __init__(
        self,
        capacity: int = 2048,
        spill_path: str = "",
        clock=time.time,
        spill_max_mb: Optional[float] = None,
    ):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, capacity))
        self._seq = 0
        self._clock = clock
        self._spill_path = spill_path
        self._spill_f = None
        if spill_max_mb is None:
            spill_max_mb = float(
                os.environ.get(
                    "EDL_FLIGHT_RECORDER_MAX_MB", str(DEFAULT_SPILL_MAX_MB)
                )
            )
        #: rotation bound for the JSONL spill: at most ~2x this many
        #: bytes on disk (live file + one rotated predecessor)
        self._spill_max_bytes = max(1, int(spill_max_mb * (1 << 20)))
        self._spill_bytes = 0
        #: monotonic-ish deadline before which spill attempts are
        #: skipped (set after a write failure — see SPILL_RETRY_SECONDS)
        self._spill_retry_at = 0.0
        #: spill writes dropped (failed or skipped while disabled) —
        #: also published as edl_flight_spill_dropped_total
        self.spill_dropped = 0
        #: (step, generation) ambient context for writers that don't
        #: know their training position (updated by the step loop)
        self._context = (-1, -1)
        #: ambient causal-trace id (see module doc)
        self._trace = ""

    # -- context --------------------------------------------------------------
    def set_context(self, step: int, generation: int) -> None:
        self._context = (step, generation)

    def set_trace(self, trace_id: str) -> None:
        """Install (or clear, with "") the ambient trace id stamped on
        every subsequent record that doesn't pass its own."""
        self._trace = trace_id or ""

    def trace_context(self) -> str:
        return self._trace

    # -- spill ----------------------------------------------------------------
    def spill_to(self, path: str) -> None:
        """(Re)direct the JSONL spill.  Opened lazily on first record."""
        with self._lock:
            if self._spill_f is not None:
                try:
                    self._spill_f.close()
                except Exception:
                    pass
                self._spill_f = None
            self._spill_path = path
            self._spill_bytes = 0
            self._spill_retry_at = 0.0

    def _count_spill_drop(self) -> None:
        """Caller holds the lock.  Best-effort catalog counter — the
        registry import is lazy (this module must stay importable
        standalone) and a broken registry must not fail the record."""
        self.spill_dropped += 1
        try:
            from edl_tpu import telemetry

            telemetry.get_registry().counter(
                "edl_flight_spill_dropped_total"
            ).inc()
        except Exception:
            pass

    def _spill(self, ev: FlightEvent) -> None:
        """Caller holds the lock.  Best-effort: a full/gone disk must
        never fail the event that was being recorded.  A write failure
        no longer disables the spill forever — the drop is counted
        (``edl_flight_spill_dropped_total``) and the spill retries
        after ``SPILL_RETRY_SECONDS``; on success the file rotates at
        ``EDL_FLIGHT_RECORDER_MAX_MB`` (previous generation kept as
        ``<path>.1``) so a long healthy run stays size-bounded."""
        if not self._spill_path:
            return
        if self._spill_retry_at and self._clock() < self._spill_retry_at:
            self._count_spill_drop()
            return
        try:
            if self._spill_f is None:
                self._spill_f = open(self._spill_path, "a", buffering=1)
                try:
                    self._spill_bytes = os.fstat(
                        self._spill_f.fileno()
                    ).st_size
                except OSError:
                    self._spill_bytes = 0
            line = json.dumps(ev.to_dict()) + "\n"
            if self._spill_bytes + len(line) > self._spill_max_bytes:
                self._spill_f.close()
                self._spill_f = None
                os.replace(self._spill_path, self._spill_path + ".1")
                self._spill_f = open(self._spill_path, "a", buffering=1)
                self._spill_bytes = 0
            self._spill_f.write(line)
            self._spill_bytes += len(line)
            self._spill_retry_at = 0.0
        except Exception:
            if self._spill_f is not None:
                try:
                    self._spill_f.close()
                except Exception:
                    pass
                self._spill_f = None
            self._spill_retry_at = self._clock() + SPILL_RETRY_SECONDS
            self._count_spill_drop()

    # -- recording ------------------------------------------------------------
    def record(
        self,
        kind: str,
        data: Optional[Dict[str, Any]] = None,
        step: Optional[int] = None,
        generation: Optional[int] = None,
        timing: Optional[Dict[str, Any]] = None,
        trace: Optional[str] = None,
        wall: Optional[float] = None,
    ) -> FlightEvent:
        """``trace``: explicit causal-trace id (None = the ambient
        ``set_trace`` context).  ``wall``: preserve another recorder's
        original timestamp instead of stamping now (the ingest path —
        re-stamping member events with the coordinator's clock would
        destroy the merged timeline's causal ordering)."""
        ctx_step, ctx_gen = self._context
        with self._lock:
            self._seq += 1
            ev = FlightEvent(
                seq=self._seq,
                step=ctx_step if step is None else int(step),
                generation=ctx_gen if generation is None else int(generation),
                kind=kind,
                data=json_safe(data or {}),
                wall=self._clock() if wall is None else float(wall),
                timing=json_safe(timing) if timing else None,
                trace=self._trace if trace is None else str(trace),
            )
            self._ring.append(ev)
            self._spill(ev)
            return ev

    def ingest(self, events: List[dict], origin: str = "") -> None:
        """Merge already-serialized events from another recorder (the
        coordinator ingests trainer-reported tails).  Stamps fresh
        local seqs; the origin rides in the data, and the source's
        wall/trace are preserved verbatim (timeline + causal-chain
        fidelity)."""
        for d in events:
            data = dict(d.get("data") or {})
            if origin:
                data["origin"] = origin
            self.record(
                d.get("kind", "event"),
                data,
                step=d.get("step", -1),
                generation=d.get("generation", -1),
                timing=d.get("timing"),
                trace=d.get("trace", ""),
                wall=d.get("wall"),
            )

    # -- reads ----------------------------------------------------------------
    def events(self, last: Optional[int] = None) -> List[FlightEvent]:
        with self._lock:
            evs = list(self._ring)
        return evs if last is None else evs[-last:]

    def events_since(self, seq: int) -> List[FlightEvent]:
        with self._lock:
            return [e for e in self._ring if e.seq > seq]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def digest(self) -> int:
        """Order-independent crc32 over every buffered event's
        deterministic identity — the reproducibility check of the
        chaos soak (same seed, same digest)."""
        with self._lock:
            idents = sorted(e.identity() for e in self._ring)
        crc = 0
        for s in idents:
            crc = zlib.crc32(s.encode(), crc)
        return crc
