"""``edl_tpu.telemetry`` — cluster-wide metrics, flight recorder, and
goodput feedback (SURVEY §5.1: the reference had NO observability; our
own signals were scattered — ``ResizeEvent.phase_seconds``, a bare
coordinator ``metrics()`` dict, bench-private compile counters, chaos
events vanishing into logs).

Three pieces:

- ``registry``: process-local counters / gauges / bounded histograms
  with catalog-enforced names and bounded label cardinality, plus
  Prometheus text exposition and idempotently-mergeable snapshots.
- ``recorder``: the flight recorder — a deterministic (generation,
  step)-stamped structured event journal with an order-independent
  digest, fed by resizes, retries, chaos injections, transfers, and
  checkpoint saves.
- ``aggregate``: coordinator-side merge of cumulative per-trainer
  snapshots + the derived goodput signals (observed step rate, resize
  cost) the autoscaler's decision log records.

Process-global default instances live here; ``scoped()`` swaps them
for a ``with`` block so tests get hermetic telemetry without threading
registry arguments through every constructor.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from edl_tpu.telemetry.aggregate import (
    TelemetryAggregator,
    coord_snapshot_gauges,
)
from edl_tpu.telemetry.catalog import CATALOG, KNOWN_EVENT_KINDS
from edl_tpu.telemetry.ledger import GoodputLedger, goodput_decomposition
from edl_tpu.telemetry.recorder import FlightEvent, FlightRecorder
from edl_tpu.telemetry.registry import (
    MetricsRegistry,
    merge_snapshots,
    render_prometheus,
)
from edl_tpu.telemetry.spans import span
from edl_tpu.telemetry.trace import ClockOffsetEstimator, new_trace_id

__all__ = [
    "CATALOG",
    "ClockOffsetEstimator",
    "FlightEvent",
    "FlightRecorder",
    "GoodputLedger",
    "KNOWN_EVENT_KINDS",
    "MetricsRegistry",
    "TelemetryAggregator",
    "coord_snapshot_gauges",
    "get_recorder",
    "get_registry",
    "goodput_decomposition",
    "merge_snapshots",
    "new_trace_id",
    "render_prometheus",
    "scoped",
    "set_recorder",
    "set_registry",
    "span",
]

_lock = threading.Lock()
_registry = MetricsRegistry()
_recorder = FlightRecorder()


def get_registry() -> MetricsRegistry:
    return _registry


def get_recorder() -> FlightRecorder:
    return _recorder


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    global _registry
    with _lock:
        old, _registry = _registry, registry
    return old


def set_recorder(recorder: FlightRecorder) -> FlightRecorder:
    global _recorder
    with _lock:
        old, _recorder = _recorder, recorder
    return old


@contextmanager
def scoped(registry=None, recorder=None):
    """Swap the process-global registry/recorder for the block (tests,
    hermetic soaks).  Yields (registry, recorder)."""
    reg = registry if registry is not None else MetricsRegistry()
    rec = recorder if recorder is not None else FlightRecorder()
    old_reg = set_registry(reg)
    old_rec = set_recorder(rec)
    try:
        yield reg, rec
    finally:
        set_registry(old_reg)
        set_recorder(old_rec)
