"""Coordinator-side telemetry aggregation + goodput derivation.

Trainers ship CUMULATIVE registry snapshots (not deltas) keyed by
(trainer_id, seq): the aggregator keeps the latest snapshot per source
and merges on read, which makes delivery idempotent — a re-sent or
out-of-order report changes nothing, and a restarted coordinator
(empty aggregator) reconverges to the exact pre-restart merge as soon
as each live trainer's next report lands.  That is the same
crash-recovery shape the membership plane already has (trainers
re-register on heartbeat KeyError).

From the merged view the aggregator derives the two goodput signals
the autoscaler's decision log records:

- ``step_rate``: observed cluster steps/s, from a short ring of
  (clock, merged edl_steps_total) points — survives report jitter and
  needs no trainer-side clocks to agree.
- ``resize_cost_seconds``: mean observed resize-window seconds
  (``edl_resize_seconds`` sum/count).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional, Tuple

from edl_tpu.telemetry.registry import merge_snapshots

#: coordinator snapshot key (LocalCoordinator.metrics()) -> gauge name
COORD_GAUGES = {
    "generation": "edl_generation",
    "world_size": "edl_world_size",
    "members": "edl_members",
    "standby": "edl_standby_members",
    "target_world": "edl_target_world",
    "prewarm": "edl_prewarm_world",
    "target_steps": "edl_target_steps",
    "latest_checkpoint_step": "edl_latest_checkpoint_step",
    "resizes": "edl_plan_rebuilds",
    "completed": "edl_completed",
    "completed_step": "edl_completed_step",
}


def coord_snapshot_gauges(metrics: dict) -> dict:
    """Map the coordinator's JSON snapshot onto cataloged gauge series
    (a snapshot-shaped dict mergeable with trainer telemetry)."""
    gauges = {}
    for key, name in COORD_GAUGES.items():
        if key in metrics:
            gauges[name] = {"": float(metrics[key])}
    return {"counters": {}, "gauges": gauges, "histograms": {}}


def histogram_quantile(hist: Optional[dict], q: float) -> Optional[float]:
    """Prometheus-style quantile estimate from one snapshot-shaped
    histogram series ``{"buckets", "counts", "sum", "count"}`` (or a
    dict of label-keyed series, which are merged first — the serving
    latency histogram is unlabeled, but a merged snapshot may carry an
    overflow series).  Linear interpolation within the winning bucket;
    observations in the +Inf bucket clamp to the largest finite bound
    (the standard histogram_quantile() behavior).  None when empty."""
    if not hist:
        return None
    if "counts" not in hist:  # label-keyed dict of series: merge
        series = [h for h in hist.values() if h and h.get("count")]
        if not series:
            return None
        buckets = list(series[0]["buckets"])
        counts = [0.0] * (len(buckets) + 1)
        for h in series:
            if list(h["buckets"]) != buckets:
                continue  # bucket-schema skew: skip (rolling upgrade)
            for i, c in enumerate(h["counts"]):
                counts[i] += c
        hist = {"buckets": buckets, "counts": counts,
                "count": sum(counts)}
    total = hist.get("count") or sum(hist["counts"])
    if not total:
        return None
    rank = max(0.0, min(1.0, q)) * total
    cum = 0.0
    buckets = hist["buckets"]
    for i, c in enumerate(hist["counts"][: len(buckets)]):
        prev_cum = cum
        cum += c
        if cum >= rank and c > 0:
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i]
            return lo + (hi - lo) * ((rank - prev_cum) / c)
    return float(buckets[-1]) if buckets else None


class TelemetryAggregator:
    """Latest-cumulative-snapshot-per-source merge (see module doc)."""

    def __init__(self, clock=time.monotonic, rate_window: int = 32):
        self._clock = clock
        #: trainer_id -> (boot, seq, snapshot).  ``boot`` is a
        #: per-process nonce: a RESTARTED trainer restarts its seq at 1
        #: under a fresh boot, and must not be mistaken for a stale
        #: replay of the old incarnation (whose seq may be thousands).
        self._by_source: Dict[str, Tuple[str, int, dict]] = {}
        #: per-source (clock, steps_total) observations — rates are
        #: derived per source then summed, so a membership change (or
        #: a coordinator restart re-learning sources one by one) never
        #: attributes one source's whole history to a short window
        self._rate_window = max(2, rate_window)
        self._rate_points: Dict[str, deque] = {}
        #: source -> latest reported clock info ({"offset", "rtt"}) —
        #: the NTP-style wall-clock alignment the timeline merger uses
        #: (edl_tpu.telemetry.trace.ClockOffsetEstimator, client-side)
        self._clock_info: Dict[str, dict] = {}
        self.reports = 0

    def report(
        self,
        source: str,
        snapshot: dict,
        seq: int = 0,
        boot: str = "",
        clock: Optional[dict] = None,
    ) -> bool:
        """Store ``source``'s cumulative snapshot.  Returns False (and
        changes nothing) when ``seq`` is not newer than what's stored
        for the same boot — the idempotence half of the contract.  A
        DIFFERENT boot always wins: the process restarted, its new
        cumulative stream replaces the dead incarnation's.  ``clock``:
        the source's estimated wall-clock offset vs this coordinator
        (kept per source for the merged-timeline alignment)."""
        prev = self._by_source.get(source)
        if prev is not None and boot == prev[0] and seq <= prev[1]:
            return False
        if prev is not None and boot != prev[0]:
            # fresh incarnation: its counter stream restarts too
            self._rate_points.pop(source, None)
        self._by_source[source] = (boot, int(seq), snapshot or {})
        if clock:
            self._clock_info[source] = dict(clock)
        self.reports += 1
        self._rate_points.setdefault(
            source, deque(maxlen=self._rate_window)
        ).append((self._clock(), self._steps_of(source)))
        return True

    def _steps_of(self, source: str) -> float:
        snap = self._by_source[source][2]
        series = (snap.get("counters") or {}).get("edl_steps_total") or {}
        return sum(series.values())

    def drop_source(self, source: str) -> None:
        """Forget ``source``'s snapshot, rate window, and clock info —
        the heartbeat-lease eviction hook (ISSUE 15).  A dead
        (never-drained) serving replica's last report is frozen at its
        moment of death: its queue-depth gauge pins the merged max
        forever and its histogram sits in every quantile window — a
        ghost p95 that an autoscaling lane would keep scaling on.
        Eviction is the membership plane saying "this source is gone";
        the telemetry plane must agree.  A replica that was evicted
        while actually alive re-registers on its next heartbeat and
        re-reports its CUMULATIVE snapshot — the same reconvergence
        contract as a coordinator restart, so dropping is always
        safe."""
        self._by_source.pop(source, None)
        self._rate_points.pop(source, None)
        self._clock_info.pop(source, None)

    def merged(self) -> dict:
        return merge_snapshots(
            [snap for _, _, snap in self._by_source.values()]
        )

    def sources(self) -> Dict[str, int]:
        return {src: seq for src, (_, seq, _) in self._by_source.items()}

    # -- goodput signals ------------------------------------------------------
    def step_rate(self) -> Optional[float]:
        """Observed steps/s: the SUM of per-source rates over each
        source's report window (None until some source has two spaced
        reports).  Per-source on purpose — a global total would spike
        when a restarted coordinator/trainer re-learns history in one
        report."""
        rates = []
        for pts in self._rate_points.values():
            if len(pts) < 2:
                continue
            (t0, s0), (t1, s1) = pts[0], pts[-1]
            if t1 > t0:
                rates.append(max(0.0, (s1 - s0) / (t1 - t0)))
        return sum(rates) if rates else None

    def resize_cost_seconds(
        self, merged: Optional[dict] = None
    ) -> Optional[float]:
        """Mean observed resize seconds.  ``merged``: pass an
        already-computed ``merged()`` to avoid re-merging."""
        m = merged if merged is not None else self.merged()
        hist = (m.get("histograms") or {}).get("edl_resize_seconds") or {}
        total = sum(h["sum"] for h in hist.values())
        count = sum(h["count"] for h in hist.values())
        return (total / count) if count else None

    def goodput(self, merged: Optional[dict] = None) -> Optional[dict]:
        """Job-level goodput decomposition (per-state seconds + the
        stepping fraction) from the members' merged
        ``edl_goodput_seconds_total`` counters; None until some member
        reported a ledger."""
        from edl_tpu.telemetry.ledger import goodput_decomposition

        m = merged if merged is not None else self.merged()
        return goodput_decomposition(m)

    def clock_offsets(self) -> Dict[str, Optional[float]]:
        """Latest per-source wall-clock offset estimate (seconds to add
        to the member's wall to land on this coordinator's timeline)."""
        return {
            src: info.get("offset")
            for src, info in self._clock_info.items()
        }
