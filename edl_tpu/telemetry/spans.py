"""Spans: one name, two sinks — the device trace AND the metrics.

``span("resize/flush")`` wraps the body in
``utils.profiling.annotate`` (a named TraceAnnotation when a device
trace is live, free otherwise) and, on exit, observes the duration
into the ``edl_span_seconds{span=...}`` histogram.  The point is that
a phase seen in a TensorBoard trace and a phase seen on ``/metrics``
carry the SAME name, so a latency regression found in one is directly
searchable in the other — before this module the resize phases had a
trace name (``resize/flush``), a ResizeEvent dict key (``flush``), and
no metric at all.
"""

from __future__ import annotations

import time
from contextlib import contextmanager


@contextmanager
def span(name: str, registry=None):
    """Timed, trace-annotated region.  ``registry`` defaults to the
    process-global one at call time (so a test's scoped registry wins
    even for code that imported this module earlier)."""
    from edl_tpu.utils.profiling import annotate

    if registry is None:
        from edl_tpu.telemetry import get_registry

        registry = get_registry()
    hist = registry.histogram("edl_span_seconds")
    t0 = time.perf_counter()
    with annotate(name):
        try:
            yield
        finally:
            hist.observe(time.perf_counter() - t0, span=name)
