"""Process-local metrics registry: counters, gauges, bounded histograms.

Stdlib-only and jax-free on purpose — the registry is default-on in
the training hot loop, so its cost budget is "two dict lookups and a
lock" per operation (bench.py measures the realized per-step overhead
against the median step time; the acceptance bar is < 1%).

Design points:

- **Catalog-strict**: a metric must be declared in ``catalog.CATALOG``
  (name, type, allowed label keys) before it can be emitted.  This is
  what makes ``GET /metrics`` a stable exposition surface instead of
  an accretion of free-form strings; ``tools/lint.py`` enforces the
  same catalog statically at the call sites.
- **Bounded label cardinality**: each metric holds at most
  ``MAX_LABEL_SETS`` distinct label sets; further label sets collapse
  into one ``overflow="true"`` series so a label-value explosion (a
  bug, or an adversarial job name) degrades accounting precision
  instead of memory.
- **Bounded histograms**: fixed bucket bounds declared in the catalog
  (default ``DEFAULT_BUCKETS``), per-bucket counts + sum + count —
  constant memory per series regardless of observation volume.
- **Mergeable snapshots**: ``snapshot()`` returns a plain JSON-safe
  dict; ``merge_snapshots`` sums counters/histograms and maxes gauges,
  which is what the coordinator-side aggregator does with the
  cumulative per-trainer snapshots (cumulative + keyed by source =
  idempotent merge: re-delivering a snapshot changes nothing).
- **Prometheus text exposition** via ``render_prometheus``.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from edl_tpu.telemetry.catalog import CATALOG

#: distinct label sets a single metric may hold before folding new
#: ones into the overflow series
MAX_LABEL_SETS = 64

#: default histogram bucket upper bounds (seconds-flavored: the
#: catalog's histograms are all durations today)
DEFAULT_BUCKETS = (
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
    30.0,
    60.0,
    120.0,
)

_OVERFLOW_KEY = "overflow=true"


def _label_key(labels: Dict[str, object]) -> str:
    """Canonical series key: sorted ``k=v`` pairs joined by ``|``
    (empty string = the unlabeled series)."""
    if not labels:
        return ""
    return "|".join(f"{k}={labels[k]}" for k in sorted(labels))


def parse_label_key(key: str) -> List[Tuple[str, str]]:
    if not key:
        return []
    return [tuple(part.split("=", 1)) for part in key.split("|")]


class _Hist:
    """One histogram series: fixed buckets, per-bucket counts, sum."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float]):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        # smallest i with v <= buckets[i]; len(buckets) = the +Inf bucket
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.sum += v
        self.count += 1

    def to_dict(self) -> dict:
        return {
            "buckets": list(self.buckets),
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.count,
        }


class _Metric:
    __slots__ = ("name", "mtype", "labels_allowed", "buckets", "series")

    def __init__(self, name, mtype, labels_allowed, buckets):
        self.name = name
        self.mtype = mtype
        self.labels_allowed = labels_allowed
        self.buckets = buckets
        self.series: Dict[str, object] = {}


class _Handle:
    """Bound (registry, metric) pair — the object call sites cache so
    the hot loop pays zero name lookups."""

    __slots__ = ("_reg", "_m")

    def __init__(self, reg: "MetricsRegistry", m: _Metric):
        self._reg = reg
        self._m = m

    def _series_key(self, labels: Dict[str, object]) -> str:
        m = self._m
        if self._reg.strict and labels:
            for k in labels:
                if k not in m.labels_allowed:
                    raise ValueError(
                        f"metric {m.name!r} does not declare label "
                        f"{k!r} (allowed: {m.labels_allowed})"
                    )
        key = _label_key(labels)
        if key not in m.series and len(m.series) >= self._reg.max_label_sets:
            return _OVERFLOW_KEY
        return key


class Counter(_Handle):
    def inc(self, n: float = 1.0, **labels) -> None:
        with self._reg._lock:
            key = self._series_key(labels)
            self._m.series[key] = self._m.series.get(key, 0.0) + n

    def value(self, **labels) -> float:
        with self._reg._lock:
            return float(self._m.series.get(_label_key(labels), 0.0))


class Gauge(_Handle):
    def set(self, v: float, **labels) -> None:
        with self._reg._lock:
            self._m.series[self._series_key(labels)] = float(v)

    def value(self, **labels) -> float:
        with self._reg._lock:
            return float(self._m.series.get(_label_key(labels), 0.0))


class Histogram(_Handle):
    def observe(self, v: float, **labels) -> None:
        with self._reg._lock:
            key = self._series_key(labels)
            h = self._m.series.get(key)
            if h is None:
                h = self._m.series[key] = _Hist(self._m.buckets)
            h.observe(float(v))

    def series(self, **labels) -> Optional[dict]:
        with self._reg._lock:
            h = self._m.series.get(_label_key(labels))
            return h.to_dict() if h is not None else None


_HANDLE_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Thread-safe metric store.  ``strict`` (the default) admits only
    catalog-declared names/types/label keys — the lint gate enforces
    the same statically, so an unregistered name fails in CI twice."""

    def __init__(
        self, strict: bool = True, max_label_sets: int = MAX_LABEL_SETS
    ):
        self._lock = threading.Lock()
        self.strict = strict
        self.max_label_sets = max_label_sets
        self._metrics: Dict[str, _Metric] = {}
        self._handles: Dict[str, _Handle] = {}

    # -- declaration ---------------------------------------------------------
    def _metric(self, name: str, mtype: str, buckets=None) -> _Handle:
        with self._lock:
            h = self._handles.get(name)
            if h is not None:
                if self._metrics[name].mtype != mtype:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{self._metrics[name].mtype}, not {mtype}"
                    )
                return h
            spec = CATALOG.get(name)
            if self.strict:
                if spec is None:
                    raise ValueError(
                        f"metric {name!r} is not in the catalog "
                        "(edl_tpu/telemetry/catalog.py) — register it "
                        "there or use a non-strict registry"
                    )
                if spec["type"] != mtype:
                    raise ValueError(
                        f"metric {name!r} is cataloged as "
                        f"{spec['type']}, not {mtype}"
                    )
            labels_allowed = tuple(spec["labels"]) if spec else ()
            if buckets is None:
                buckets = (
                    tuple(spec["buckets"])
                    if spec and "buckets" in spec
                    else DEFAULT_BUCKETS
                )
            m = _Metric(name, mtype, labels_allowed, tuple(buckets))
            self._metrics[name] = m
            h = self._handles[name] = _HANDLE_TYPES[mtype](self, m)
            return h

    def counter(self, name: str) -> Counter:
        return self._metric(name, "counter")

    def gauge(self, name: str) -> Gauge:
        return self._metric(name, "gauge")

    def histogram(self, name: str, buckets=None) -> Histogram:
        return self._metric(name, "histogram", buckets=buckets)

    # -- snapshot / exposition ----------------------------------------------
    def snapshot(self) -> dict:
        """JSON-safe cumulative snapshot (the telemetry wire format)."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            for name, m in self._metrics.items():
                if m.mtype == "counter":
                    out["counters"][name] = dict(m.series)
                elif m.mtype == "gauge":
                    out["gauges"][name] = dict(m.series)
                else:
                    out["histograms"][name] = {
                        k: h.to_dict() for k, h in m.series.items()
                    }
        return out

    def render(self) -> str:
        return render_prometheus(self.snapshot())


def merge_snapshots(snaps: Sequence[dict]) -> dict:
    """Merge cumulative per-source snapshots into one cluster view:
    counters and histograms SUM (each source counted once — the caller
    keys sources and passes the latest snapshot per source, which is
    what makes re-delivery idempotent); gauges take the MAX (they are
    world-consistent values like the generation, where max = newest)."""
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for s in snaps:
        if not s:
            continue
        for name, series in (s.get("counters") or {}).items():
            dst = out["counters"].setdefault(name, {})
            for k, v in series.items():
                dst[k] = dst.get(k, 0.0) + v
        for name, series in (s.get("gauges") or {}).items():
            dst = out["gauges"].setdefault(name, {})
            for k, v in series.items():
                dst[k] = max(dst.get(k, float("-inf")), v)
        for name, series in (s.get("histograms") or {}).items():
            dst = out["histograms"].setdefault(name, {})
            for k, h in series.items():
                d = dst.get(k)
                if d is None:
                    dst[k] = {
                        "buckets": list(h["buckets"]),
                        "counts": list(h["counts"]),
                        "sum": h["sum"],
                        "count": h["count"],
                    }
                elif list(d["buckets"]) == list(h["buckets"]):
                    d["counts"] = [
                        a + b for a, b in zip(d["counts"], h["counts"])
                    ]
                    d["sum"] += h["sum"]
                    d["count"] += h["count"]
                else:  # bucket-schema skew (rolling upgrade): keep sums
                    d["sum"] += h["sum"]
                    d["count"] += h["count"]
    return out


def _fmt_labels(pairs: List[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    body = ",".join(
        '{}="{}"'.format(
            k, str(v).replace("\\", "\\\\").replace('"', '\\"')
        )
        for k, v in pairs
    )
    return "{" + body + "}"


def _fmt_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render_prometheus(snapshot: dict) -> str:
    """Prometheus text exposition (format 0.0.4) of a snapshot."""
    lines: List[str] = []

    def head(name: str, mtype: str) -> None:
        spec = CATALOG.get(name)
        if spec is not None:
            lines.append(f"# HELP {name} {spec['help']}")
        lines.append(f"# TYPE {name} {mtype}")

    for name in sorted(snapshot.get("counters") or {}):
        head(name, "counter")
        for k in sorted(snapshot["counters"][name]):
            lines.append(
                f"{name}{_fmt_labels(parse_label_key(k))} "
                f"{_fmt_num(snapshot['counters'][name][k])}"
            )
    for name in sorted(snapshot.get("gauges") or {}):
        head(name, "gauge")
        for k in sorted(snapshot["gauges"][name]):
            lines.append(
                f"{name}{_fmt_labels(parse_label_key(k))} "
                f"{_fmt_num(snapshot['gauges'][name][k])}"
            )
    for name in sorted(snapshot.get("histograms") or {}):
        head(name, "histogram")
        for k in sorted(snapshot["histograms"][name]):
            h = snapshot["histograms"][name][k]
            base = parse_label_key(k)
            cum = 0
            for le, c in zip(h["buckets"], h["counts"]):
                cum += c
                lines.append(
                    f"{name}_bucket"
                    f"{_fmt_labels(base + [('le', _fmt_num(le))])} {cum}"
                )
            cum += h["counts"][-1]
            lines.append(
                f"{name}_bucket{_fmt_labels(base + [('le', '+Inf')])} {cum}"
            )
            lines.append(
                f"{name}_sum{_fmt_labels(base)} {_fmt_num(h['sum'])}"
            )
            lines.append(f"{name}_count{_fmt_labels(base)} {h['count']}")
    return "\n".join(lines) + ("\n" if lines else "")
