"""The goodput ledger: an honest wall-clock decomposition of training.

Varuna-style elastic systems optimize *goodput* — the fraction of wall
clock actually spent stepping, as opposed to resizing, replaying lost
work, holding at an unformable barrier, or recovering a broken world.
Before this module the autoscaler's decision log saw a step RATE but
had no wall-clock decomposition to read: a job stepping fast 60% of
the time looked identical to one stepping fast 95% of the time.

``GoodputLedger`` is a per-member state machine fed by the elastic
runtime at its existing transition points:

- ``stepping``          dispatching/harvesting fresh steps
- ``staging_stalled``   host blocked assembling/placing a batch (the
                        slice of stepping the async stager exists to
                        hide — carved out via ``note_staging``)
- ``resizing``          inside the resize barrier; refined post-hoc to
                        ``resizing:<phase>`` from the measured
                        ``ResizeEvent.phase_seconds`` (serial phases)
- ``holding``           parked: no formable world / quiesced at the
                        agreed stop / standby
- ``replaying``         re-running steps already completed before a
                        non-graceful resize fell back to a checkpoint
- ``broken``            between a world break and its recovery resize

Time is attributed ONLY at transitions (plus a throttled ``touch`` so
long steady states stay fresh on the telemetry cadence), so the hot
loop pays one monotonic read and a comparison per iteration.  Totals
publish to ``edl_goodput_seconds_total{state=}`` and the rolling
fraction to ``edl_goodput_frac``; the coordinator aggregates members'
counters into the job-level decomposition (``/telemetry``'s
``goodput``) the autoscaler's decision log records.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from edl_tpu.telemetry.registry import parse_label_key

#: the stepping state — the numerator of the goodput fraction
STEPPING = "stepping"

#: resize phases refined out of the "resizing" bucket (the serial
#: window phases; overlapped background work is not window time)
RESIZE_PHASES = ("flush", "world_formation", "remesh", "restore")

#: how often (seconds) ``touch`` flushes the current state's elapsed
#: time into the counters without a transition
TOUCH_INTERVAL = 1.0


class GoodputLedger:
    """Transition-clocked wall-time attribution (see module doc)."""

    def __init__(self, registry=None, clock=time.monotonic):
        if registry is None:
            from edl_tpu import telemetry

            registry = telemetry.get_registry()
        self._m_seconds = registry.counter("edl_goodput_seconds_total")
        self._g_frac = registry.gauge("edl_goodput_frac")
        self._clock = clock
        self._state: Optional[str] = None
        self._t: Optional[float] = None
        self._last_touch = 0.0
        #: staging seconds accumulated during the CURRENT stepping
        #: stretch, carved out of it at the next attribution
        self._staged = 0.0
        self.totals: Dict[str, float] = {}

    # -- attribution ---------------------------------------------------------
    def _add(self, state: str, seconds: float) -> None:
        if seconds <= 0.0:
            return
        self.totals[state] = self.totals.get(state, 0.0) + seconds
        self._m_seconds.inc(seconds, state=state)

    def _flush(self, now: float) -> None:
        """Attribute the current stretch up to ``now`` (caller updates
        ``_t``)."""
        if self._state is None or self._t is None:
            return
        elapsed = now - self._t
        if self._state in (STEPPING, "replaying") and self._staged > 0.0:
            staged = min(self._staged, max(0.0, elapsed))
            self._add("staging_stalled", staged)
            self._add(self._state, elapsed - staged)
        else:
            self._add(self._state, elapsed)
        # Staging accumulated in a stretch never leaks into the next
        # one (a carve-out larger than its own stretch would silently
        # shrink a later, unrelated stepping bucket).
        self._staged = 0.0

    def transition(self, state: str) -> None:
        """Enter ``state``; attributes the elapsed stretch to the one
        being left.  Same-state calls are a cheap no-op."""
        if state == self._state:
            return
        now = self._clock()
        self._flush(now)
        self._state = state
        self._t = now
        self._update_frac()

    def note_staging(self, seconds: float) -> None:
        """Host time spent assembling/placing the next batch inside
        the current stepping stretch (carved into ``staging_stalled``
        at the next attribution, keeping totals = wall clock)."""
        if seconds > 0.0:
            self._staged += seconds

    def touch(self) -> None:
        """Throttled mid-state flush so a long stepping stretch keeps
        the published counters (and the telemetry reports riding the
        heartbeat cadence) fresh."""
        now = self._clock()
        if now - self._last_touch < TOUCH_INTERVAL:
            return
        self._last_touch = now
        self._flush(now)
        self._t = now
        self._update_frac()

    def split_resize(self, phases: Optional[Dict[str, float]]) -> None:
        """Refine the just-attributed ``resizing`` bucket into
        ``resizing:<phase>`` using the measured serial phase seconds
        (bounded by what the bucket actually holds — the remainder
        stays plain ``resizing``)."""
        if not phases:
            return
        # Attribute the in-flight stretch first: split_resize is called
        # at the END of the resize window, before the loop transitions
        # out of "resizing" — without a flush the bucket would still be
        # empty and the refinement would have no budget to draw on.
        now = self._clock()
        self._flush(now)
        self._t = now
        budget = self.totals.get("resizing", 0.0)
        for name in RESIZE_PHASES:
            s = float(phases.get(name) or 0.0)
            s = min(s, budget)
            if s <= 0.0:
                continue
            budget -= s
            self.totals["resizing"] = self.totals.get("resizing", 0.0) - s
            self._add(f"resizing:{name}", s)
            # the counter cannot decrement; the decomposition's source
            # of truth for "plain resizing" is total minus the phases
        self._update_frac()

    # -- reads ---------------------------------------------------------------
    def frac(self) -> Optional[float]:
        total = sum(self.totals.values())
        if total <= 0.0:
            return None
        return self.totals.get(STEPPING, 0.0) / total

    def _update_frac(self) -> None:
        f = self.frac()
        if f is not None:
            self._g_frac.set(f)


def goodput_decomposition(snapshot: dict) -> Optional[dict]:
    """Job-level goodput from a (merged) registry snapshot: per-state
    seconds + the stepping fraction.  ``resizing`` phase refinements
    sum INTO the plain ``resizing`` counter too (monotone counters
    can't move time between series), so the total counts the serial
    window once: phases are detail, plain-resizing = bucket - phases.
    None when no ledger ever reported."""
    series = (snapshot.get("counters") or {}).get(
        "edl_goodput_seconds_total"
    )
    if not series:
        return None
    seconds: Dict[str, float] = {}
    for key, v in series.items():
        labels = dict(parse_label_key(key))
        state = labels.get("state", "unknown")
        seconds[state] = seconds.get(state, 0.0) + float(v)
    phase_s = sum(
        v for k, v in seconds.items() if k.startswith("resizing:")
    )
    total = sum(
        v for k, v in seconds.items() if not k.startswith("resizing:")
    )
    if "resizing" in seconds:
        seconds["resizing"] = max(0.0, seconds["resizing"] - phase_s)
    if total <= 0.0:
        return None
    return {
        "seconds": {k: round(v, 6) for k, v in sorted(seconds.items())},
        "total_s": round(total, 6),
        "frac": round(seconds.get(STEPPING, 0.0) / total, 6),
    }
