"""The metric catalog: every metric name this repo may emit.

One dict literal, deliberately import-free and side-effect-free so
``tools/lint.py`` can ``ast.literal_eval`` it without importing the
package: the lint gate rejects any ``counter("...")`` / ``gauge`` /
``histogram`` call whose name literal is not registered here, which is
what keeps the exposition surface a *catalog* instead of an accretion
of free-form strings (the pre-telemetry state: ``phase_seconds`` dict
keys here, a bench-private compile counter there).

Schema per entry:

- ``type``: "counter" | "gauge" | "histogram" — the registry enforces
  that a name is only ever used as its declared type.
- ``help``: one-line description (rendered as Prometheus ``# HELP``).
- ``labels``: allowed label KEYS (a tuple).  The registry rejects
  undeclared keys in strict mode; label VALUE cardinality is bounded
  separately (``registry.MAX_LABEL_SETS``).
- ``buckets`` (histograms only, optional): upper bounds in seconds /
  units; omitted = ``registry.DEFAULT_BUCKETS``.

Naming follows Prometheus conventions: ``edl_`` prefix, ``_total``
suffix on counters, base units (seconds, bytes) spelled out.
"""

# NOTE: keep this a PURE LITERAL (no comprehensions, no names) —
# tools/lint.py reads it with ast.literal_eval.
CATALOG = {
    # -- training hot loop ---------------------------------------------------
    "edl_steps_total": {
        "type": "counter",
        "help": "Completed train steps (replayed steps count again).",
        "labels": (),
    },
    "edl_step_seconds": {
        "type": "histogram",
        "help": "Wall-clock seconds per train step.",
        "labels": (),
    },
    "edl_pipeline_depth": {
        "type": "gauge",
        "help": "Configured steady-state pipeline depth (max in-flight "
        "steps; 0 = synchronous per-step host<->device sync).",
        "labels": (),
    },
    "edl_batch_stage_seconds": {
        "type": "histogram",
        "help": "Seconds to assemble one global batch on the host and "
        "place it on device (the work the pipeline's background "
        "stager overlaps with compute).",
        "labels": (),
    },
    "edl_device_wait_seconds": {
        "type": "histogram",
        "help": "Seconds the host blocked waiting on a step's device "
        "metrics at harvest (lag-deferred float(loss) sync).",
        "labels": (),
    },
    # -- resize window -------------------------------------------------------
    "edl_resizes_total": {
        "type": "counter",
        "help": "Resize barriers completed, by gracefulness and how "
        "state was restored.",
        "labels": ("graceful", "source"),
    },
    "edl_resize_seconds": {
        "type": "histogram",
        "help": "End-to-end resize-window seconds.",
        "labels": (),
    },
    "edl_resize_phase_seconds": {
        "type": "histogram",
        "help": "Per-phase resize-window seconds (flush / remesh / "
        "restore / compile ... — the ResizeEvent.phase_seconds keys).",
        "labels": ("phase",),
    },
    "edl_replayed_steps_total": {
        "type": "counter",
        "help": "Steps re-run after a non-graceful resize fell back to "
        "the last interval checkpoint.",
        "labels": (),
    },
    "edl_world_breaks_total": {
        "type": "counter",
        "help": "Live process groups abandoned after a peer died "
        "mid-collective.",
        "labels": (),
    },
    "edl_span_seconds": {
        "type": "histogram",
        "help": "Named span durations; span names match the "
        "utils.profiling trace annotations so traces and metrics "
        "correlate by name.",
        "labels": ("span",),
    },
    # -- checkpoints ---------------------------------------------------------
    "edl_checkpoint_saves_total": {
        "type": "counter",
        "help": "Checkpoint saves by kind (async interval save vs "
        "synchronous resize flush).",
        "labels": ("kind",),
    },
    "edl_checkpoint_bytes_total": {
        "type": "counter",
        "help": "Bytes captured into host-DRAM checkpoints.",
        "labels": ("kind",),
    },
    "edl_checkpoint_save_seconds": {
        "type": "histogram",
        "help": "Seconds to materialize a checkpoint (async save "
        "thread / flush device-to-host phase).",
        "labels": ("kind",),
    },
    # -- streaming restore transfer ------------------------------------------
    "edl_transfer_bytes_sent_total": {
        "type": "counter",
        "help": "Restore-transfer bytes this process sent.",
        "labels": (),
    },
    "edl_transfer_bytes_received_total": {
        "type": "counter",
        "help": "Restore-transfer bytes this process received.",
        "labels": (),
    },
    "edl_transfer_chunks_total": {
        "type": "counter",
        "help": "Restore-transfer chunks received.",
        "labels": (),
    },
    "edl_transfer_leaves_skipped_total": {
        "type": "counter",
        "help": "Leaves skipped because local bytes already matched "
        "the source digest (the delta-restore win).",
        "labels": (),
    },
    "edl_transfer_seconds": {
        "type": "histogram",
        "help": "Restore-transfer engine seconds (agreement + wire).",
        "labels": (),
    },
    # -- sharded peer-to-peer checkpoint fabric ------------------------------
    "edl_fabric_bytes_sent_total": {
        "type": "counter",
        "help": "Checkpoint-fabric payload bytes this process served "
        "to pulling peers.",
        "labels": (),
    },
    "edl_fabric_bytes_received_total": {
        "type": "counter",
        "help": "Checkpoint-fabric payload bytes this process pulled "
        "from peers (across all parallel streams).",
        "labels": (),
    },
    "edl_fabric_shard_fallbacks_total": {
        "type": "counter",
        "help": "Shards re-pulled from another replica holder after "
        "their preferred peer died or served torn bytes.",
        "labels": (),
    },
    "edl_fabric_pull_peers": {
        "type": "gauge",
        "help": "Distinct source peers of the last parallel fabric "
        "pull (>= 2 is the no-single-NIC claim).",
        "labels": (),
    },
    "edl_fabric_pull_seconds": {
        "type": "histogram",
        "help": "Fabric restore engine seconds (agreement + parallel "
        "pull + confirmation).",
        "labels": (),
    },
    "edl_fabric_replicas_total": {
        "type": "counter",
        "help": "Replica shards accepted into this process's shard "
        "replica store (buddy pushes / inheritance).",
        "labels": (),
    },
    "edl_fabric_replica_bytes_total": {
        "type": "counter",
        "help": "Payload bytes accepted into the shard replica store "
        "(offer/accept pushes; declined offers move no bytes).",
        "labels": (),
    },
    "edl_fabric_underreplicated_total": {
        "type": "counter",
        "help": "Flushes whose owned shards did NOT reach every ring "
        "buddy (EDL_FABRIC_K enforcement: an unreachable or dropped "
        "buddy leaves the window journaled + counted, never silent).",
        "labels": (),
    },
    "edl_fabric_resident_bytes": {
        "type": "gauge",
        "help": "Host bytes resident in this member's shard store "
        "(own GSPMD slice + K buddy shards under shard-only "
        "checkpoints — the (1+K)/world memory contract, vs 1.0x "
        "state for a full host copy).",
        "labels": (),
    },
    # -- control plane -------------------------------------------------------
    "edl_retry_attempts_total": {
        "type": "counter",
        "help": "Transient failures absorbed by RetryPolicy (one per "
        "failed attempt that was retried).",
        "labels": ("op",),
    },
    "edl_retry_giveups_total": {
        "type": "counter",
        "help": "RetryPolicy exhaustions (GiveUpError raised).",
        "labels": ("op",),
    },
    "edl_chaos_injections_total": {
        "type": "counter",
        "help": "Chaos faults actually delivered, by injection point.",
        "labels": ("point",),
    },
    "edl_telemetry_reports_total": {
        "type": "counter",
        "help": "Telemetry snapshots shipped to the coordinator.",
        "labels": (),
    },
    "edl_autoscaler_ticks_total": {
        "type": "counter",
        "help": "Autoscaler decision cycles (run_once with jobs).",
        "labels": (),
    },
    "edl_autoscaler_actuations_total": {
        "type": "counter",
        "help": "Autoscaler actuations applied, by direction.",
        "labels": ("direction",),
    },
    "edl_observed_step_rate": {
        "type": "gauge",
        "help": "Observed cluster step rate (steps/s) from merged "
        "trainer telemetry — the goodput signal the autoscaler logs "
        "into its decision trace.",
        "labels": ("job",),
    },
    "edl_observed_resize_cost_seconds": {
        "type": "gauge",
        "help": "Mean observed resize cost (seconds) from merged "
        "trainer telemetry.",
        "labels": ("job",),
    },
    # -- coordinator snapshot (GET /metrics exposition) ----------------------
    "edl_generation": {
        "type": "gauge",
        "help": "Coordinator plan generation.",
        "labels": (),
    },
    "edl_world_size": {
        "type": "gauge",
        "help": "Active world size of the current plan.",
        "labels": (),
    },
    "edl_members": {
        "type": "gauge",
        "help": "Registered live members (active + standby).",
        "labels": (),
    },
    "edl_standby_members": {
        "type": "gauge",
        "help": "Registered members beyond the active world.",
        "labels": (),
    },
    "edl_target_world": {
        "type": "gauge",
        "help": "Actuation target world size.",
        "labels": (),
    },
    "edl_prewarm_world": {
        "type": "gauge",
        "help": "Advisory prewarm hint (0 = none).",
        "labels": (),
    },
    "edl_target_steps": {
        "type": "gauge",
        "help": "Steps after which the job completes (0 = open-ended).",
        "labels": (),
    },
    "edl_latest_checkpoint_step": {
        "type": "gauge",
        "help": "Latest durable checkpoint step the coordinator knows.",
        "labels": (),
    },
    "edl_plan_rebuilds": {
        "type": "gauge",
        "help": "Plan rebuilds (generation bumps) since coordinator "
        "start.",
        "labels": (),
    },
    "edl_completed": {
        "type": "gauge",
        "help": "1 once a trainer reported the job complete.",
        "labels": (),
    },
    "edl_completed_step": {
        "type": "gauge",
        "help": "Step at which completion was reported (-1 = none).",
        "labels": (),
    },
    # -- consensus: data-plane step agreement (edl_tpu.consensus) ------------
    "edl_consensus_words_total": {
        "type": "counter",
        "help": "Step-bus control words harvested (one per train step "
        "on multi-member worlds).",
        "labels": (),
    },
    "edl_consensus_votes_total": {
        "type": "counter",
        "help": "Stop votes this member cast on the step bus (one per "
        "observed retarget).",
        "labels": (),
    },
    "edl_consensus_stop_step": {
        "type": "gauge",
        "help": "Last data-plane-agreed stop step (the boundary every "
        "member leaves the old world at).",
        "labels": (),
    },
    "edl_consensus_step_skew_buckets": {
        "type": "gauge",
        "help": "Timing-lane bucket spread between the slowest and "
        "fastest member in the last harvested word (log2 buckets).",
        "labels": (),
    },
    "edl_consensus_stragglers_total": {
        "type": "counter",
        "help": "Words where one member's timing bucket exceeded the "
        "fastest by the straggler spread, by process rank.",
        "labels": ("rank",),
    },
    "edl_consensus_watchdog_trips_total": {
        "type": "counter",
        "help": "Collective-watchdog deadline expiries (wedged "
        "step/control futures buried via broken-world recovery).",
        "labels": (),
    },
    "edl_consensus_quiesce_seconds": {
        "type": "histogram",
        "help": "Seconds from observing a retarget to quiescing at the "
        "agreed stop step (drain complete, ready to leave the world).",
        "labels": (),
    },
    # -- compile accounting (bench + AOT warmers) ----------------------------
    "edl_xla_compiles_total": {
        "type": "counter",
        "help": "True XLA backend compiles observed (bench.py counts "
        "them at the backend_compile seam).",
        "labels": (),
    },
    "edl_compile_seconds": {
        "type": "histogram",
        "help": "AOT step-warm compile seconds (Trainer.warm_step).",
        "labels": (),
    },
    # -- goodput ledger (edl_tpu.telemetry.ledger) ---------------------------
    "edl_goodput_seconds_total": {
        "type": "counter",
        "help": "Wall-clock seconds this process spent in each "
        "training state (stepping / staging_stalled / resizing[:phase] "
        "/ holding / replaying / broken) — the honest decomposition "
        "behind the goodput fraction the autoscaler reads back.",
        "labels": ("state",),
    },
    "edl_goodput_frac": {
        "type": "gauge",
        "help": "Fraction of attributed wall-clock this process spent "
        "actually stepping (stepping / total ledger seconds).",
        "labels": (),
    },
    # -- elastic inference serving (edl_tpu.serving) -------------------------
    "edl_serve_requests_total": {
        "type": "counter",
        "help": "Serving requests by terminal status (ok / rejected "
        "on backpressure / expired past deadline / error).",
        "labels": ("status",),
    },
    "edl_serve_batches_total": {
        "type": "counter",
        "help": "Micro-batches the continuous batcher dispatched.",
        "labels": (),
    },
    "edl_serve_examples_total": {
        "type": "counter",
        "help": "Examples served (request rows, padding excluded).",
        "labels": (),
    },
    "edl_serve_queue_depth": {
        "type": "gauge",
        "help": "Requests waiting in the admission queue (the "
        "backpressure / autoscaling signal).",
        "labels": (),
    },
    "edl_serve_latency_seconds": {
        "type": "histogram",
        "help": "End-to-end request latency (admission to response; "
        "the serving lane reads its p95 from the merged telemetry).",
        "labels": (),
    },
    "edl_serve_batch_occupancy": {
        "type": "histogram",
        "help": "Real rows / padded bucket rows per dispatched "
        "micro-batch (1.0 = no padding waste).",
        "buckets": (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
        "labels": (),
    },
    "edl_serve_hot_swaps_total": {
        "type": "counter",
        "help": "Checkpoint hot-swaps installed between batches "
        "(generation-keyed; an in-flight batch never sees torn "
        "weights).",
        "labels": (),
    },
    "edl_serve_swap_rejected_total": {
        "type": "counter",
        "help": "Candidate checkpoints rejected at a hot-swap attempt "
        "(CRC verification failed / unreadable durable spill) — the "
        "engine keeps serving the old weights.",
        "labels": (),
    },
    "edl_serve_weights_step": {
        "type": "gauge",
        "help": "Training step of the checkpoint currently serving.",
        "labels": (),
    },
    # -- serving mesh shape + per-device footprint (tensor-parallel
    # decode): the fleet view must distinguish a replicated engine from
    # a sharded one, and per-device bytes are what an HBM budget gates.
    "edl_serve_mesh_dp": {
        "type": "gauge",
        "help": "Serving mesh dp extent (weight replicas; single-shot "
        "batches shard over it).",
        "labels": (),
    },
    "edl_serve_mesh_tp": {
        "type": "gauge",
        "help": "Serving mesh tp extent (attention heads / FFN hidden "
        "dims and the KV pools' head axis shard over it).",
        "labels": (),
    },
    "edl_serve_weight_shard_bytes_per_device": {
        "type": "gauge",
        "help": "Weight bytes ONE device holds under the model's "
        "partition rules (tp-sharded kernels count at 1/tp) — also the "
        "hot-swap staging traffic per device.",
        "labels": (),
    },
    "edl_serve_kv_pool_bytes_per_device": {
        "type": "gauge",
        "help": "KV pool bytes ONE device holds (both planes; the head "
        "axis shards over tp).",
        "labels": (),
    },
    "edl_serve_kv_used_bytes_per_device": {
        "type": "gauge",
        "help": "KV bytes ONE device holds for blocks owned by live "
        "sequences: the host-side block count times the per-device "
        "per-block footprint (1/tp of the full-head block).",
        "labels": (),
    },
    # -- serving-plane fault tolerance (graceful drain + watchdog) -----------
    "edl_serve_draining": {
        "type": "gauge",
        "help": "Per-replica drain state: 0 serving, 1 draining "
        "(admission closed, in-flight finishing), 2 drained "
        "(deregistered, ready to exit).",
        "labels": ("replica",),
    },
    "edl_serve_drains_total": {
        "type": "counter",
        "help": "Graceful drains started on this replica (POST /drain, "
        "SIGTERM, or a scale-down victim drain).",
        "labels": (),
    },
    "edl_serve_drain_seconds": {
        "type": "histogram",
        "help": "Seconds from admission close to drained (every "
        "in-flight single-shot request and decode sequence finished, "
        "KV blocks freed, replica deregistered).",
        "labels": (),
    },
    "edl_serve_dispatch_wedged_total": {
        "type": "counter",
        "help": "Serving dispatches (prefill / chunk / decode) that "
        "missed the dispatch watchdog deadline and were recovered via "
        "pool rebuild + cache-epoch re-prefill instead of hanging the "
        "worker thread.",
        "labels": (),
    },
    # -- live KV sequence migration (drain/preempt without waiting) ----------
    "edl_serve_migrations_total": {
        "type": "counter",
        "help": "Sequences handed to a survivor at drain/preemption, "
        "by outcome: ok (KV blocks moved, decode resumed mid-"
        "generation), fallback (push failed somewhere on the ladder, "
        "re-prefilled cold on the survivor), cold (half-prefilled or "
        "queued sequence requeued as a cold prompt), failed (survivor "
        "unusable, sequence readmitted locally and drained by "
        "waiting).",
        "labels": ("outcome",),
    },
    "edl_serve_migrations_bytes_total": {
        "type": "counter",
        "help": "KV-cache bytes pushed to survivors over the chunked "
        "migration stream (filled blocks only, K and V planes).",
        "labels": (),
    },
    "edl_serve_migrate_seconds": {
        "type": "histogram",
        "help": "Seconds from sequence freeze to the survivor's import "
        "ack (device->host gather + chunked TCP push + dest pool "
        "scatter) — the per-sequence unit of O(KV bytes) drain "
        "latency.",
        "labels": (),
    },
    # -- autoregressive decode serving (DecodeEngine + token batcher) --------
    "edl_serve_tokens_total": {
        "type": "counter",
        "help": "Generated tokens emitted by the decode path (prefill "
        "first tokens + decode-iteration tokens).",
        "labels": (),
    },
    "edl_serve_prefills_total": {
        "type": "counter",
        "help": "Sequences prefilled (one bucketed prompt forward per "
        "admitted request; swap re-prefills count again).",
        "labels": (),
    },
    "edl_serve_decode_iterations_total": {
        "type": "counter",
        "help": "Per-token decode iterations dispatched (one batched "
        "decode executable call each).",
        "labels": (),
    },
    "edl_serve_restarts_total": {
        "type": "counter",
        "help": "In-flight sequences re-prefilled because a checkpoint "
        "hot-swap landed mid-generation (their partial output is void "
        "- one sequence never mixes weight generations).",
        "labels": (),
    },
    "edl_serve_decode_queue_depth": {
        "type": "gauge",
        "help": "Generate requests waiting for a decode slot/KV blocks "
        "(the decode-path backpressure / autoscaling signal).",
        "labels": (),
    },
    "edl_serve_active_sequences": {
        "type": "gauge",
        "help": "Sequences currently in the running decode batch.",
        "labels": (),
    },
    "edl_serve_kv_occupancy": {
        "type": "gauge",
        "help": "Fraction of the paged KV pool's usable blocks "
        "currently owned by live sequences.",
        "labels": (),
    },
    "edl_serve_ttft_seconds": {
        "type": "histogram",
        "help": "Time to first token: request ENQUEUE to the first "
        "generated token — across every prefill chunk for chunked "
        "admission, never from the last chunk's dispatch (the serving "
        "lane's decode overload signal).",
        "labels": (),
    },
    "edl_serve_prefill_chunks_total": {
        "type": "counter",
        "help": "Prefill chunk dispatches (ISSUE 14): block-aligned "
        "prompt slices fed beside the decode step under the "
        "per-iteration token budget.",
        "labels": (),
    },
    "edl_serve_prefill_tokens_total": {
        "type": "counter",
        "help": "Prompt tokens prefilled through chunk dispatches "
        "(true tokens, bucket padding excluded).",
        "labels": (),
    },
    "edl_serve_prefill_queued_tokens": {
        "type": "gauge",
        "help": "Prompt tokens still awaiting prefill (queued prompts "
        "+ the chunk FIFO's remaining work) — the chunked-admission "
        "backpressure signal.",
        "labels": (),
    },
    "edl_serve_prefill_stall_seconds": {
        "type": "histogram",
        "help": "Time one scheduler iteration's admission/prefill work "
        "held up an already-running decode batch (the prefill/decode "
        "interference quantum the chunked scheduler bounds; observed "
        "only on iterations where both sides were live).",
        "buckets": (
            0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
            0.25, 0.5, 1.0, 2.5,
        ),
        "labels": (),
    },
    "edl_serve_prefix_hits_total": {
        "type": "counter",
        "help": "Admissions whose prompt matched a published prefix "
        "run in the content-addressed KV prefix cache (ISSUE 17) — "
        "the sequence skipped straight to its first cold block.",
        "labels": (),
    },
    "edl_serve_prefix_misses_total": {
        "type": "counter",
        "help": "Admissions that walked the prefix chain and matched "
        "nothing (prompts too short to span one block are not "
        "counted — they are uncacheable, not missed).",
        "labels": (),
    },
    "edl_serve_prefix_blocks_reused_total": {
        "type": "counter",
        "help": "KV blocks claimed by refcount bump instead of being "
        "allocated and prefilled (each is one block of prompt "
        "compute the replica never paid).",
        "labels": (),
    },
    "edl_serve_prefix_evictions_total": {
        "type": "counter",
        "help": "Refcount-0 cached prefix blocks evicted back to the "
        "free list (LRU, under allocation pressure or a chaos "
        "serve.prefix.evicted trip).",
        "labels": (),
    },
    "edl_serve_prefix_hit_ratio": {
        "type": "gauge",
        "help": "Running hits / (hits + misses) of the prefix cache "
        "since the batcher started (invalidations do not reset it).",
        "labels": (),
    },
    "edl_serve_intertoken_seconds": {
        "type": "histogram",
        "help": "Gap between consecutive tokens of one sequence "
        "(decode-iteration cadence as the client experiences it).",
        "buckets": (
            0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
            0.25, 0.5, 1.0, 2.5,
        ),
        "labels": (),
    },
    # -- fleet front door: fault-masking request router (ISSUE 20) ----------
    "edl_route_requests_total": {
        "type": "counter",
        "help": "Requests the router resolved, by outcome: ok (served, "
        "possibly after absorbed retries), exhausted (per-request "
        "retry budget spent -> typed RetryBudgetExhausted), error "
        "(non-retryable upstream reply passed through).",
        "labels": ("outcome",),
    },
    "edl_route_retries_total": {
        "type": "counter",
        "help": "Per-attempt failures the router absorbed invisibly "
        "(the client never saw them), by what the backend said: "
        "queue_full (429 back-off-here), draining (503 go-elsewhere), "
        "refused (connection refused/reset = dead replica), error "
        "(other 5xx/transport failure).",
        "labels": ("reason",),
    },
    "edl_route_steers_total": {
        "type": "counter",
        "help": "Admissions steered off a draining replica BEFORE it "
        "could 503 them (the router consumed the drain intent / "
        "healthz draining bit first).",
        "labels": (),
    },
    "edl_route_ejections_total": {
        "type": "counter",
        "help": "Replicas ejected from rotation on consecutive-failure "
        "passive health (re-admission is by active probe only).",
        "labels": (),
    },
    "edl_route_readmits_total": {
        "type": "counter",
        "help": "Ejected replicas re-admitted after an active /healthz "
        "probe came back ok and not draining.",
        "labels": (),
    },
    "edl_route_redrives_total": {
        "type": "counter",
        "help": "In-flight /generate streams cut by a replica failure "
        "and re-driven against a survivor: resume (same weights step "
        "-> greedy continuation from the emitted prefix, no token "
        "duplicated or dropped) or restart (weights skew -> restart "
        "event voids prior tokens, the batcher's hot-swap contract).",
        "labels": ("outcome",),
    },
    "edl_route_affinity_total": {
        "type": "counter",
        "help": "Prefix-affinity consults on /generate admissions: hit "
        "(routed to the replica already holding the prompt's cached "
        "prefix blocks) or miss (no affinity known, or the affine "
        "replica was unroutable/overloaded — advisory only, never "
        "correctness-bearing).",
        "labels": ("outcome",),
    },
    "edl_route_backends": {
        "type": "gauge",
        "help": "Routable-backend census by health state (healthy / "
        "draining / ejected), from the router's last plan sync.",
        "labels": ("state",),
    },
    # -- multi-job fleet market (edl_tpu.fleet) ------------------------------
    "edl_fleet_chips_total": {
        "type": "gauge",
        "help": "TPU chips in the fleet arbiter's inventory.",
        "labels": (),
    },
    "edl_fleet_chips_free": {
        "type": "gauge",
        "help": "Chips left unallocated after the last market fixed "
        "point.",
        "labels": (),
    },
    "edl_fleet_chips_allocated": {
        "type": "gauge",
        "help": "Chips the market currently allocates to each bidder "
        "(training job or serving fleet).",
        "labels": ("job",),
    },
    "edl_fleet_target_units": {
        "type": "gauge",
        "help": "Decided unit count (trainer replicas / serving "
        "replicas) per bidder after the last fixed point.",
        "labels": ("job",),
    },
    "edl_fleet_decisions_total": {
        "type": "counter",
        "help": "Per-job fleet decision entries journaled (one per "
        "bidder per arbiter tick).",
        "labels": (),
    },
    "edl_fleet_preemptions_total": {
        "type": "counter",
        "help": "Preemption steps the arbiter took (a trainer shed "
        "one legal size to cover a serving SLO requirement), by "
        "victim job.",
        "labels": ("job",),
    },
    "edl_fleet_unmet_demand_chips": {
        "type": "gauge",
        "help": "Chips a serving fleet's SLO requirement is short "
        "even after exhausting every preemptible trainer (0 = SLO "
        "demand covered).",
        "labels": ("job",),
    },
    # -- tracing / flight-recorder plumbing ----------------------------------
    "edl_flight_spill_dropped_total": {
        "type": "counter",
        "help": "Flight-recorder JSONL spill writes dropped (write "
        "failed, or spill temporarily disabled after a failure).",
        "labels": (),
    },
    "edl_clock_offset_seconds": {
        "type": "gauge",
        "help": "NTP-style estimated offset of this process's wall "
        "clock vs the coordinator's (add to local wall to get "
        "coordinator time), from heartbeat request/response pairs.",
        "labels": (),
    },
}

# Every flight-recorder event kind the stack may journal (outside
# tests/), mirrored by a tools/lint.py gate exactly like the metric
# catalog and the chaos-point registry: free-form kinds are what make
# merged cluster timelines unreadable.  PURE LITERAL — the lint gate
# reads it with ast.literal_eval.
KNOWN_EVENT_KINDS = {
    # training / resize lifecycle (runtime.elastic)
    "resize": "a resize barrier completed on this member",
    "step.first": "first harvested step of a fresh generation",
    "world.broken": "live process group abandoned mid-collective",
    "prewarm.hint": "background AOT warm spawned for a hinted size",
    "profile.window": "a bounded device-trace window opened/closed",
    # checkpoints / transfer
    "checkpoint.save": "checkpoint materialization submitted",
    "transfer": "streaming restore-transfer summary",
    # sharded peer-to-peer checkpoint fabric (checkpoint.fabric)
    "fabric.pull": "one parallel multi-peer fabric restore summary",
    "fabric.replicate": "stage-B buddy replica offer/push summary",
    "fabric.inherit": "scale-down victim pushed its shard inheritance",
    "fabric.degrade": "agreement dropped an under-covered step world-wide",
    "fabric.underreplicated": "a flush's shards did not reach K buddies",
    # control plane (runtime.coordinator)
    "coord.plan": "coordinator plan rebuild (generation bump)",
    "coord.evict": "heartbeat-lease eviction",
    "coord.telemetry": "trainer telemetry report ingested",
    "coord.world_acked": "every planned member acked the generation",
    # consensus (edl_tpu.consensus)
    "consensus.vote": "stop vote cast on the step bus",
    "consensus.stop": "stop agreement learned from a harvested word",
    "consensus.quiesce": "member drained at the agreed stop boundary",
    "consensus.straggler": "timing-lane straggler transition",
    "consensus.watchdog": "collective watchdog deadline expired",
    # resilience plumbing
    "retry": "transient failure absorbed by RetryPolicy",
    "retry.giveup": "RetryPolicy exhausted (GiveUpError)",
    "chaos": "a scheduled fault was actually delivered",
    # autoscaler
    "autoscaler.decision": "one goodput-annotated decision-log entry",
    # multi-job fleet market (edl_tpu.fleet)
    "fleet.decision": "one per-job fleet-arbiter decision entry",
    "fleet.preempt": "a trainer stepped down to cover a serving SLO",
    # elastic inference serving (edl_tpu.serving)
    "serve.warm": "a padded-bucket forward executable AOT-compiled",
    "serve.swap": "a newer verified checkpoint hot-swapped in",
    "serve.swap.rejected": "a hot-swap candidate failed verification",
    "serve.replica": "a serving replica registered / took traffic",
    "serve.restart": "a hot swap re-prefilled in-flight sequences",
    "serve.drain": "a replica drain started / completed",
    "serve.watchdog": "a serving dispatch missed the watchdog deadline",
    "serve.migrate": "a live KV sequence moved (or fell back) at drain",
    "serve.prefix": "the KV prefix cache invalidated / rejected / evicted",
    # fleet front door: fault-masking request router (ISSUE 20)
    "route.steer": "new work steered off a draining replica pre-503",
    "route.eject": "a replica left rotation on passive health",
    "route.readmit": "an active probe re-admitted an ejected replica",
    "route.redrive": "a cut /generate stream re-driven on a survivor",
    "route.exhausted": "a request spent its whole retry budget",
    # recorder-internal default for ingested events missing a kind
    "event": "unclassified ingested event",
}
