"""Live KV sequence migration: drains and preemptions that never wait
on a generation.

PR 15 made drains graceful but WAITING — drain latency was the longest
in-flight generation, and a budget miss left the replica stuck
registered.  This module closes ROADMAP item 2(b): at a token boundary
the token batcher freezes, a sequence's filled KV blocks are gathered
device->host through the pool's block table, and a fabric-style
chunked-TCP push (the PR 12 shard-transfer wire: per-chunk crc32,
advertised per-leaf digests, typed errors) lands them on a survivor
whose engine imports the blocks into freshly granted pool slots and
resumes decode MID-GENERATION.  Greedy decode is a pure function of
(weights, written K/V, cursor), so the survivor's remaining tokens are
bit-identical to an unmigrated same-seed run — asserted by tests and
the gated bench section.  Drain latency becomes O(KV bytes / NIC),
independent of generation length (the Pathways posture PAPERS.md
credits: one control plane MOVING work, not killing it, at the
paged-KV block granularity the Orca/vLLM entries established).

Mixed weights generations are forbidden end to end: the offer carries
the source checkpoint's ``(step, digest)`` content key (engine-local
generation counters don't travel), the dest refuses skew at the offer,
and the batcher re-checks at adoption — a hot swap landing between
grant and adoption routes the sequence to a cold re-prefill, never to
a token computed under different weights than its prefix.

Every failure mode degrades down a ladder, never to a hang:

1. **KV push** — blocks + cursor move, decode resumes mid-generation.
2. **Cold re-prefill on the survivor** — torn push, refused offer,
   KV-exhausted dest, generation skew: the sequence restarts as a
   fresh prompt on the dest (streamed tokens voided via a restart
   event, exactly the hot-swap contract).
3. **Readmit locally** — the survivor is unusable entirely: the
   sequence re-enters the local queue and PR 15's bounded drain wait
   covers it.

After a successful handoff the source keeps the client connection: a
relay thread forwards the survivor's token/done events back through
the original ticket, so callers streaming from the draining replica
never observe the move.

Chaos points (seeded, journal bit-identically): ``serve.migrate.kill``
(source dies mid-push), ``serve.migrate.torn`` (corrupt chunk),
``serve.migrate.exhausted`` (dest pool refuses the grant),
``serve.migrate.swap`` (hot swap between grant and adoption).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from edl_tpu.checkpoint.transfer import tune_socket
from edl_tpu.serving.batcher import (
    _DECODING,
    DrainingError,
    GenerateTicket,
    QueueFullError,
)

#: distinct from the checkpoint fabric's magic — a migration socket
#: accidentally pointed at a shard receiver must fail loudly, not parse
_MAGIC = 0xED16_0A11
#: JSON control frame: magic, payload length
_FRAME = struct.Struct("<II")
#: KV chunk header: magic, leaf index, offset, length, crc32(payload)
_CHUNK_HDR = struct.Struct("<IIQQI")
_DONE_LEAF = 0xFFFF_FFFF
_CHUNK_BYTES = 1 << 20


class MigrationError(RuntimeError):
    """A live KV migration failed (peer unreachable, torn stream,
    protocol violation).  Recoverable by construction: the caller
    walks the fallback ladder — cold re-prefill on the survivor, then
    readmit-and-wait locally."""


class TornMigrationError(MigrationError):
    """A received KV chunk failed its crc (or a leaf its chained
    digest): the dest refused the import and freed its grant."""


class MigrationRefusedError(MigrationError):
    """The dest refused the offer before any KV bytes moved (draining,
    not ready, generation skew, KV pool exhausted, no decode slot)."""


def _recv_exact(sock: socket.socket, view: memoryview) -> None:
    got = 0
    while got < len(view):
        n = sock.recv_into(view[got:], len(view) - got)
        if n == 0:
            raise MigrationError("migration peer closed mid-stream")
        got += n


def _send_frame(sock: socket.socket, obj: Dict[str, Any]) -> None:
    data = json.dumps(obj, sort_keys=True).encode()
    sock.sendall(_FRAME.pack(_MAGIC, len(data)) + data)


def _recv_frame(sock: socket.socket) -> Dict[str, Any]:
    hdr = bytearray(_FRAME.size)
    _recv_exact(sock, memoryview(hdr))
    magic, length = _FRAME.unpack(hdr)
    if magic != _MAGIC:
        raise MigrationError(f"bad migration frame magic {magic:#x}")
    if length > (64 << 20):
        raise MigrationError(f"oversized migration frame ({length} bytes)")
    body = bytearray(length)
    _recv_exact(sock, memoryview(body))
    return json.loads(bytes(body).decode())


def _seq_meta(t: GenerateTicket) -> Dict[str, Any]:
    """The cursor + sampled tokens + budget — everything the survivor
    needs to resume (or restart) the generation.  The remaining
    deadline travels as a relative budget: monotonic clocks don't
    compare across hosts."""
    now = time.monotonic()
    return {
        "prompt": [int(x) for x in t.prompt],
        "tokens": [int(x) for x in t.tokens],
        "length": int(t.length),
        "last_token": int(t.last_token),
        "max_new": int(t.max_new),
        "eos_id": t.eos_id,
        "deadline_left_s": round(max(0.001, t.deadline - now), 6),
        "restarts": int(t.restarts),
        "chunks": int(t.chunks),
        "reused_blocks": int(t.reused_blocks),
        "ttft_s": (
            round(t.first_time - t.enqueued, 6)
            if t.first_time is not None
            else None
        ),
    }


def snapshot_sequence(engine, t: GenerateTicket, weights) -> Dict[str, Any]:
    """Export one decoding sequence's migration image: filled KV
    blocks gathered device->host (leaf j = K block j, leaf n+j = V
    block j, each contiguous) plus the offer frame with per-leaf sizes
    and crc32 digests.  MUST run with the batcher frozen — the next
    donated dispatch invalidates the buffers the gather reads.

    Prefix-cache interplay (ISSUE 17): a sequence may hold SHARED
    (refcount > 1) prefix blocks — the export is a host COPY, so
    other claimants on the source are untouched, and the eventual
    ``detach`` only DECREMENTS refcounts (``KVBlockPool.free``); the
    destination's grant lands the copies in PRIVATE refcount-1 blocks
    that are never published into its prefix index."""
    bt = engine.block_tokens
    nblk = max(1, -(-int(t.length) // bt))
    ids = list(t.blocks[:nblk])
    k, v = engine.export_kv(ids)
    leaves: List[bytes] = []
    for plane in (k, v):
        for j in range(nblk):
            leaves.append(np.ascontiguousarray(plane[:, j]).tobytes())
    hello = {
        "mode": "kv",
        "blocks": nblk,
        "weights_step": int(weights.step),
        "weights_digest": int(weights.digest),
        "leaf_sizes": [len(b) for b in leaves],
        "leaf_crcs": [zlib.crc32(b) for b in leaves],
        "seq": _seq_meta(t),
    }
    return {"hello": hello, "leaves": leaves}


def _relay(sock: socket.socket, t: GenerateTicket) -> None:
    """Source-side relay: forward the survivor's stream back through
    the original ticket so the caller never observes the move.  Runs
    until the survivor resolves the sequence (done/error) or the
    socket dies (then the caller's future fails — the request was
    already off this replica's books)."""
    try:
        while True:
            fr = _recv_frame(sock)
            if "token" in fr:
                t.tokens.append(int(fr["token"]))
                t._event(fr)
            elif fr.get("restart"):
                t.tokens = []
                t.restarts += 1
                t._event(fr)
            elif fr.get("done"):
                t.tokens = [int(x) for x in fr.get("tokens", [])]
                meta = {
                    k: v for k, v in fr.items() if k not in ("done", "tokens")
                }
                meta["migrated"] = True
                t._result = (list(t.tokens), meta)
                t._event({"done": True, "tokens": list(t.tokens), **meta})
                t._done.set()
                return
            elif "error" in fr:
                t._reject(MigrationError(str(fr["error"])))
                return
    except Exception as e:
        if not t._done.is_set():
            t._reject(MigrationError(f"migration relay lost: {e}"))
    finally:
        try:
            sock.close()
        except OSError:
            pass


def _start_relay(sock: socket.socket, t: GenerateTicket) -> None:
    sock.settimeout(None)
    threading.Thread(
        target=_relay, args=(sock, t), daemon=True,
        name="edl-migrate-relay",
    ).start()


def _open(host: str, port: int, timeout: float) -> socket.socket:
    try:
        sock = socket.create_connection((host, port), timeout=timeout)
    except OSError as e:
        raise MigrationRefusedError(
            f"migration dest {host}:{port} unreachable: {e}"
        )
    tune_socket(sock)
    sock.settimeout(timeout)
    return sock


def _finish_handoff(
    sock: socket.socket, t: GenerateTicket
) -> Dict[str, Any]:
    """Read the dest's result frame; on acceptance hand the socket to
    the relay thread (the caller keeps streaming from us)."""
    res = _recv_frame(sock)
    if not res.get("ok"):
        reason = str(res.get("reason", "unknown"))
        if reason == "torn":
            raise TornMigrationError(
                f"dest refused import: torn chunks ({res.get('torn', '?')})"
            )
        raise MigrationError(f"dest refused import: {reason}")
    _start_relay(sock, t)
    return res


def push_kv(
    host: str,
    port: int,
    snap: Dict[str, Any],
    t: GenerateTicket,
    chaos=None,
    timeout: float = 10.0,
) -> int:
    """Rung 1: stream a snapshotted sequence's KV blocks to the
    survivor and leave the socket relaying.  Returns bytes pushed.
    Raises ``MigrationRefusedError`` (nothing moved),
    ``TornMigrationError`` / ``MigrationError`` (push failed; the
    sequence is still intact host-side for the next rung)."""
    sock = _open(host, port, timeout)
    handed = False
    try:
        try:
            _send_frame(sock, snap["hello"])
            acc = _recv_frame(sock)
            if not acc.get("accept"):
                raise MigrationRefusedError(
                    f"dest refused offer: {acc.get('reason', 'unknown')}"
                )
            pushed = 0
            for i, leaf in enumerate(snap["leaves"]):
                mv = memoryview(leaf)
                off = 0
                while off < len(mv):
                    part = mv[off : off + _CHUNK_BYTES]
                    if chaos is not None and chaos.due("serve.migrate.kill"):
                        # chaos[serve.migrate.kill]: the source dies
                        # mid-push — the dest sees the peer vanish
                        # before DONE and frees its grant; we walk the
                        # fallback ladder.
                        raise MigrationError(
                            "migration push killed mid-stream (chaos)"
                        )
                    sock.sendall(
                        _CHUNK_HDR.pack(
                            _MAGIC, i, off, len(part), zlib.crc32(part)
                        )
                    )
                    sock.sendall(part)
                    off += len(part)
                    pushed += len(part)
            sock.sendall(_CHUNK_HDR.pack(_MAGIC, _DONE_LEAF, 0, 0, 0))
            _finish_handoff(sock, t)
            handed = True
            return pushed
        except OSError as e:
            raise MigrationError(f"migration push failed: {e}")
    finally:
        if not handed:
            try:
                sock.close()
            except OSError:
                pass


def push_cold(
    host: str,
    port: int,
    t: GenerateTicket,
    timeout: float = 10.0,
) -> None:
    """Rung 2: requeue the sequence on the survivor as a COLD prompt
    (no KV bytes — the dest re-prefills under its own weights).  The
    socket stays open as the relay.  Raises ``MigrationRefusedError``
    / ``MigrationError``; the ticket is untouched on failure."""
    sock = _open(host, port, timeout)
    handed = False
    try:
        try:
            _send_frame(sock, {"mode": "cold", "seq": _seq_meta(t)})
            acc = _recv_frame(sock)
            if not acc.get("accept"):
                raise MigrationRefusedError(
                    f"dest refused cold requeue: {acc.get('reason', 'unknown')}"
                )
            _finish_handoff(sock, t)
            handed = True
        except OSError as e:
            raise MigrationError(f"cold requeue failed: {e}")
    finally:
        if not handed:
            try:
                sock.close()
            except OSError:
                pass


def resolve_endpoint(address: str, timeout: float = 5.0) -> Tuple[str, int]:
    """Resolve a survivor's migration endpoint.  ``tcp://host:port``
    addresses a receiver directly; anything else is the replica's HTTP
    address — GET /migrate advertises the port (and whether it's
    accepting).  Raises ``MigrationRefusedError`` when the survivor is
    dark or not accepting — the caller falls back to waiting."""
    if address.startswith("tcp://"):
        host, _, port = address[6:].rpartition(":")
        try:
            return host or "127.0.0.1", int(port)
        except ValueError:
            raise MigrationRefusedError(f"bad migration address {address!r}")
    import urllib.error
    import urllib.parse
    import urllib.request

    url = address if "://" in address else f"http://{address}"
    try:
        with urllib.request.urlopen(
            f"{url.rstrip('/')}/migrate", timeout=timeout
        ) as r:
            info = json.loads(r.read().decode())
        if not info.get("accepting", False):
            raise MigrationRefusedError(f"dest {address} not accepting")
        host = urllib.parse.urlparse(url).hostname or "127.0.0.1"
        return host, int(info["migrate_port"])
    except MigrationRefusedError:
        raise
    except Exception as e:
        raise MigrationRefusedError(
            f"migration endpoint lookup at {address} failed: {e}"
        )


def migrate_out(
    engine,
    batcher,
    dest_address: str,
    replica_id: str = "",
    chaos=None,
    timeout: float = 10.0,
) -> Dict[str, Any]:
    """Drain-side orchestration: freeze the batcher at a token
    boundary, snapshot every decoding sequence host-side and detach
    it, take every queued/half-prefilled sequence cold, resume the
    worker, then walk each sequence down the ladder toward the
    survivor.  Returns the summary the drain result reports.  Raises
    ``MigrationRefusedError`` only when the survivor itself is
    unreachable BEFORE anything was detached — the caller then waits
    (PR 15) with every sequence still local."""
    from edl_tpu import telemetry

    reg = telemetry.get_registry()
    rec = telemetry.get_recorder()
    m_out = reg.counter("edl_serve_migrations_total")
    m_bytes = reg.counter("edl_serve_migrations_bytes_total")
    h_sec = reg.histogram("edl_serve_migrate_seconds")

    host, port = resolve_endpoint(dest_address, timeout=timeout)
    summary = {
        "dest": dest_address, "attempted": 0, "migrated": 0,
        "cold": 0, "fallback": 0, "failed": 0, "bytes": 0,
    }
    weights = engine.current_weights()
    if weights is None:
        raise MigrationRefusedError("source has no verified weights")
    hot: List[Tuple[GenerateTicket, Optional[Dict[str, Any]]]] = []
    with batcher.frozen():
        for t in list(batcher._active):
            if t.state != _DECODING:
                continue
            # A swap that raced the drain (worker hasn't rebound yet)
            # makes the cached K/V stale — snapshot nothing and let
            # the ladder re-prefill the sequence cold.
            snap = (
                snapshot_sequence(engine, t, weights)
                if batcher._bound_gen == weights.generation
                else None
            )
            hot.append((t, snap))
            batcher.detach(t)
        cold = batcher.take_cold()
    t_all = time.monotonic()
    for t, snap in hot:
        summary["attempted"] += 1
        t0 = time.monotonic()
        outcome = "failed"
        try:
            if snap is None:
                raise MigrationError("weights swapped under the drain")
            pushed = push_kv(host, port, snap, t, chaos=chaos,
                             timeout=timeout)
            summary["migrated"] += 1
            summary["bytes"] += pushed
            m_bytes.inc(pushed)
            h_sec.observe(time.monotonic() - t0)
            outcome = "ok"
        except MigrationError:
            # Rung 2: the KV image is unusable somewhere on the wire
            # or the dest — re-prefill COLD on the survivor.  Streamed
            # tokens are void (the hot-swap restart contract).
            if t.tokens:
                t.tokens = []
                t.restarts += 1
                t._event({"restart": True, "reason": "migration fallback"})
            try:
                push_cold(host, port, t, timeout=timeout)
                summary["fallback"] += 1
                outcome = "fallback"
            except MigrationError:
                # Rung 3: survivor unusable — back on the local books;
                # the PR 15 bounded wait covers it.
                batcher.readmit(t)
        m_out.inc(outcome=outcome)
    for t in cold:
        summary["attempted"] += 1
        outcome = "failed"
        try:
            # Cold candidates streamed nothing: requeue-to-survivor
            # with NO restart event (there is nothing to void).
            push_cold(host, port, t, timeout=timeout)
            summary["cold"] += 1
            outcome = "cold"
        except MigrationError:
            batcher.readmit(t)
        m_out.inc(outcome=outcome)
    summary["failed"] = (
        summary["attempted"]
        - summary["migrated"] - summary["fallback"] - summary["cold"]
    )
    rec.record(
        "serve.migrate",
        {
            "phase": "out",
            "replica": replica_id,
            "attempted": summary["attempted"],
            "migrated": summary["migrated"],
            "cold": summary["cold"],
            "fallback": summary["fallback"],
            "failed": summary["failed"],
        },
        # bytes ride the non-identity timing field: the KV volume
        # depends on how many tokens streamed before the freeze — a
        # scheduling accident the same-seed soak digest must not see.
        timing={
            "seconds": round(time.monotonic() - t_all, 6),
            "bytes": summary["bytes"],
        },
    )
    return summary


class MigrationReceiver:
    """Survivor-side TCP listener: one connection per migrated
    sequence.  KV offers are admission-checked (draining / weights
    key / decode slot / block grant) BEFORE any bytes move; accepted
    imports are crc-verified chunk by chunk, scattered into the
    granted blocks, and handed to the batcher for token-boundary
    adoption.  Cold offers go straight through ``submit_generate``.
    Either way the connection stays open as the event relay back to
    the source."""

    def __init__(
        self,
        engine,
        batcher,
        replica_id: str = "",
        chaos=None,
        host: str = "127.0.0.1",
        timeout: float = 30.0,
    ):
        self.engine = engine
        self.batcher = batcher
        self.replica_id = replica_id
        self.chaos = chaos if chaos is not None else engine.chaos
        self.timeout = float(timeout)
        self.accepting = True
        self._stop = False
        self._threads: List[threading.Thread] = []
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, 0))
        self._srv.listen(16)
        self._srv.settimeout(0.5)
        self.port = self._srv.getsockname()[1]
        self._accept_thread: Optional[threading.Thread] = None

        from edl_tpu import telemetry

        self.recorder = telemetry.get_recorder()

    def start(self) -> "MigrationReceiver":
        if self._accept_thread is not None and self._accept_thread.is_alive():
            return self
        self._stop = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="edl-migrate-recv"
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            th = threading.Thread(
                target=self._handle, args=(conn,), daemon=True,
                name="edl-migrate-import",
            )
            th.start()
            self._threads.append(th)

    # -- per-connection import ----------------------------------------------
    def _refusal(self, hello: Dict[str, Any]) -> Optional[str]:
        eng, bat = self.engine, self.batcher
        if self._stop or not self.accepting or bat.draining:
            return "draining"
        w = eng.current_weights()
        if w is None:
            return "not_ready"
        if hello.get("mode") == "kv":
            if (
                int(hello.get("weights_step", -1)) != w.step
                or int(hello.get("weights_digest", -1)) != w.digest
            ):
                return "generation_skew"
            if (
                bat.active_count + bat.prefilling_count + bat.adopted_count
                >= eng.max_seqs
            ):
                return "no_slot"
            if self.chaos is not None and self.chaos.due(
                "serve.migrate.exhausted"
            ):
                # chaos[serve.migrate.exhausted]: the dest pool
                # reports exhaustion at the offer — the source must
                # fall back to a cold re-prefill, not hang.
                return "kv_exhausted"
        return None

    def _handle(self, conn: socket.socket) -> None:
        granted: Optional[List[int]] = None
        handed = False
        try:
            tune_socket(conn)
            conn.settimeout(self.timeout)
            hello = _recv_frame(conn)
            mode = str(hello.get("mode", ""))
            if mode not in ("kv", "cold"):
                _send_frame(conn, {"accept": False, "reason": "bad_mode"})
                return
            refuse = self._refusal(hello)
            if refuse is None and mode == "kv":
                nblk = int(hello["blocks"])
                if nblk < 1 or nblk > self.engine.blocks_per_seq:
                    refuse = "bad_blocks"
                else:
                    granted = self.engine.pool.alloc(nblk)
                    if granted is None:
                        refuse = "kv_exhausted"
            if refuse is not None:
                self._record(mode, "refused", reason=refuse)
                _send_frame(conn, {"accept": False, "reason": refuse})
                return
            _send_frame(conn, {"accept": True})
            # The forwarder must not write an event frame before the
            # RESULT frame is on the wire (the worker can adopt and
            # emit within microseconds) — the gate orders the socket.
            gate = threading.Event()
            try:
                if mode == "cold":
                    handed = self._import_cold(conn, hello, gate)
                else:
                    handed = self._import_kv(conn, hello, granted, gate)
                    if handed:
                        granted = None  # ownership passed to the ticket
            finally:
                gate.set()
        except (MigrationError, OSError, ValueError, KeyError):
            # Torn peer / protocol violation: nothing was adopted, the
            # grant (if any) goes back to the pool below.
            self._record("kv", "aborted")
        finally:
            if granted is not None:
                self.engine.pool.free(granted)
            if not handed:
                try:
                    conn.close()
                except OSError:
                    pass

    def _forwarder(self, conn: socket.socket, gate: threading.Event):
        lock = threading.Lock()

        def fwd(ev: Dict[str, Any]) -> None:
            # Adoption can start streaming within microseconds of
            # batcher.adopt()/submit_generate() — before _handle has
            # sent its RESULT frame.  Event frames must queue behind
            # it or the source misreads an event as the result.
            gate.wait(timeout=60.0)
            with lock:
                _send_frame(conn, ev)
            if ev.get("done") or "error" in ev:
                try:
                    conn.close()
                except OSError:
                    pass

        return fwd

    def _ticket_from(
        self,
        seq: Dict[str, Any],
        conn: socket.socket,
        gate: threading.Event,
    ) -> GenerateTicket:
        return GenerateTicket(
            np.asarray(seq["prompt"], np.int32),
            int(seq["max_new"]),
            time.monotonic() + float(seq["deadline_left_s"]),
            seq.get("eos_id"),
            on_event=self._forwarder(conn, gate),
        )

    def _import_cold(
        self,
        conn: socket.socket,
        hello: Dict[str, Any],
        gate: threading.Event,
    ) -> bool:
        seq = hello["seq"]
        try:
            self.batcher.submit_generate(
                {"tokens": seq["prompt"]},
                max_new_tokens=int(seq["max_new"]),
                deadline_s=float(seq["deadline_left_s"]),
                eos_id=seq.get("eos_id"),
                on_event=self._forwarder(conn, gate),
            )
        except (DrainingError, QueueFullError) as e:
            _send_frame(conn, {"ok": False, "reason": type(e).__name__})
            self._record("cold", "refused", reason=type(e).__name__)
            return False
        _send_frame(conn, {"ok": True})
        self._record("cold", "adopted")
        return True

    def _import_kv(
        self,
        conn: socket.socket,
        hello: Dict[str, Any],
        granted: List[int],
        gate: threading.Event,
    ) -> bool:
        eng = self.engine
        nblk = int(hello["blocks"])
        sizes = [int(s) for s in hello["leaf_sizes"]]
        crcs = [int(c) for c in hello["leaf_crcs"]]
        shape = eng.pool._shape  # (layers, num_blocks, bt, heads, hd)
        block_shape = (shape[0], shape[2], shape[3], shape[4])
        expect = int(np.prod(block_shape)) * np.dtype(eng.pool._dtype).itemsize
        if len(sizes) != 2 * nblk or any(s != expect for s in sizes):
            _send_frame(conn, {"ok": False, "reason": "shape_mismatch"})
            self._record("kv", "refused", reason="shape_mismatch")
            return False
        bufs = [bytearray(s) for s in sizes]
        got = [0] * len(bufs)
        leaf_crc = [0] * len(bufs)
        torn: set = set()
        hdr = bytearray(_CHUNK_HDR.size)
        while True:
            _recv_exact(conn, memoryview(hdr))
            magic, leaf, off, length, crc = _CHUNK_HDR.unpack(hdr)
            if magic != _MAGIC:
                raise MigrationError(f"bad chunk magic {magic:#x}")
            if leaf == _DONE_LEAF:
                break
            if leaf >= len(bufs) or off + length > len(bufs[leaf]):
                raise MigrationError(
                    f"chunk out of bounds (leaf {leaf}, off {off})"
                )
            if off != got[leaf]:
                raise MigrationError(
                    f"out-of-order chunk for leaf {leaf} "
                    f"(expected {got[leaf]}, got {off})"
                )
            region = memoryview(bufs[leaf])[off : off + length]
            _recv_exact(conn, region)
            if self.chaos is not None and self.chaos.due("serve.migrate.torn"):
                # chaos[serve.migrate.torn]: one chunk corrupted in
                # flight — the per-chunk crc must catch it and the
                # import refuse, never scatter poisoned K/V.
                region[0] ^= 0xFF
            if zlib.crc32(region) != crc:
                torn.add(leaf)
            leaf_crc[leaf] = zlib.crc32(region, leaf_crc[leaf])
            got[leaf] += length
        for i in range(len(bufs)):
            if got[i] != sizes[i]:
                torn.add(i)
            elif leaf_crc[i] != crcs[i]:
                torn.add(i)
        if torn:
            _send_frame(conn, {"ok": False, "reason": "torn",
                               "torn": len(torn)})
            self._record("kv", "refused", reason="torn")
            return False
        dtype = eng.pool._dtype
        k = np.stack(
            [
                np.frombuffer(bytes(bufs[j]), dtype).reshape(block_shape)
                for j in range(nblk)
            ],
            axis=1,
        )
        v = np.stack(
            [
                np.frombuffer(bytes(bufs[nblk + j]), dtype).reshape(block_shape)
                for j in range(nblk)
            ],
            axis=1,
        )
        # The worker's donated decode dispatches rebind the pool
        # arrays every iteration; the import's read-modify-write must
        # not interleave with one or an update is silently lost.
        # Freeze parks the worker at a token boundary for the scatter.
        with self.batcher.frozen():
            eng.import_kv(granted, k, v)
            epoch = getattr(eng, "cache_epoch", 0)
        seq = hello["seq"]
        t = self._ticket_from(seq, conn, gate)
        t.state = _DECODING
        t.blocks = list(granted)
        t.table = np.zeros(eng.blocks_per_seq, np.int32)
        t.table[: len(granted)] = granted
        t.length = int(seq["length"])
        t.last_token = int(seq["last_token"])
        t.tokens = [int(x) for x in seq["tokens"]]
        t.restarts = int(seq.get("restarts", 0))
        t.chunks = int(seq.get("chunks", 0))
        # Source-side prefix reuse is part of the client-visible meta;
        # it must survive the hop (the granted blocks themselves land
        # PRIVATE here — never published into the dest's prefix index).
        t.reused_blocks = int(seq.get("reused_blocks", 0))
        if seq.get("ttft_s") is not None:
            # TTFT was already observed at the source; pin first_time
            # so adoption never re-samples it AND the finish meta
            # reports the source's enqueue->first-token span.
            t.first_time = t.enqueued + float(seq["ttft_s"])
        step = int(hello["weights_step"])
        digest = int(hello["weights_digest"])
        if self.chaos is not None and self.chaos.due("serve.migrate.swap"):
            # chaos[serve.migrate.swap]: a hot swap lands between the
            # block grant and batcher adoption — poison the adoption
            # key so the worker's generation check routes the sequence
            # down the re-prefill rung instead of mixing generations.
            digest ^= 1
        try:
            self.batcher.adopt(t, step, digest, epoch)
        except RuntimeError as e:
            _send_frame(conn, {"ok": False, "reason": str(e)})
            self._record("kv", "refused", reason="stopped")
            return False
        _send_frame(conn, {"ok": True, "blocks": nblk})
        # block count is scheduling-dependent (tokens streamed before
        # the source froze) — journal it as timing, not identity
        self._record("kv", "adopted", _timing={"blocks": nblk})
        return True

    def _record(self, mode: str, outcome: str, _timing=None, **data) -> None:
        payload = {
            "phase": "in", "replica": self.replica_id,
            "mode": mode, "outcome": outcome,
        }
        payload.update(data)
        self.recorder.record("serve.migrate", payload, timing=_timing)
