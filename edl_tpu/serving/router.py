"""``edl_tpu.serving.router`` — the fleet front door (ISSUE 20).

A coordinator-fed routing tier that makes replica churn invisible to
clients.  The serving plane already survives everything the cluster
throws at it — drains migrate live KV, hot swaps re-prefill, watchdogs
rebuild pools, leases evict the dead — but every one of those
mechanisms was visible to CALLERS as a 503/429/connection-refused they
had to hand-roll retries around.  ``RequestRouter`` owns that loop
once, fleet-side:

- **spread** — `/predict` and `/generate` admissions go to the
  least-loaded routable replica, scored by live queue depth, admission
  saturation, KV occupancy and in-flight count (per-replica ``/healthz``
  probes merged with the telemetry aggregator's labeled gauges; the
  fleet TTFT p95 rides the journal + the saturation Retry-After);
- **steer** — drain intents published by the scale-down actuator,
  drain flight events from the coordinator's merged journal, and
  ``/healthz`` draining bits all mark a replica DRAINING, and new work
  stops landing on it BEFORE it would 503;
- **absorb** — per-attempt failures (429 back-off-here, 503
  go-elsewhere, refused = dead) are retried against the live candidate
  order under a per-request budget (``edl_tpu.serving.client``); the
  typed ``RetryBudgetExhausted`` reaches the client as 503 +
  Retry-After ONLY when the whole fleet is saturated — a busy fleet
  advertises when to come back, a broken one must not pretend to;
- **eject** — consecutive passive failures take a replica out of
  rotation; ONLY a successful active ``/healthz`` probe re-admits it
  (flap damping: one good request must not resurrect a dying box);
- **re-drive** — a `/generate` stream cut mid-flight by a replica kill
  is resumed on a survivor without duplicating or dropping a token:
  greedy decode is a pure function of (weights step, prefix), so if
  the survivor serves the SAME weights step that produced the emitted
  prefix (each leg's first token line carries its purity stamp), the
  router re-submits prompt+prefix and splices the continuation;
  any skew and it RESTARTS — a ``{"restart": true}`` line voids the
  prefix, exactly the batcher's own hot-swap contract;
- **affinity** — prefix-sharing `/generate` sessions are steered to
  the replica already holding their cached KV blocks (PR 17's chain
  hash computed router-side).  Advisory ONLY: the prefix cache is
  correct on any replica, affinity just converts misses into hits.

``RouterServer`` puts the coord_service-idiom HTTP front on it and
``python -m edl_tpu.serving.router`` (routerd) runs it against a
serving coordinator, configured by the ``EDL_ROUTE_*`` env contract
(edl_tpu.controller.jobparser renders the Deployment + Service).
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import threading
import time
import urllib.error
import urllib.request
import uuid
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from edl_tpu import telemetry
from edl_tpu.serving.batcher import DrainingError, QueueFullError
from edl_tpu.serving.client import (
    DRAINING,
    ERROR,
    OK,
    REFUSED,
    RetryBudgetExhausted,
    RetryingClient,
    UpstreamClientError,
    http_call,
)
from edl_tpu.serving.prefix import chain_hashes
from edl_tpu.telemetry.aggregate import histogram_quantile

HEALTHY = "healthy"
DRAINING_STATE = "draining"
EJECTED = "ejected"

#: scoring weights: queue entries and in-flight requests are work
#: units; KV occupancy and admission saturation are [0,1] fractions
#: scaled to compete (a 90%-full KV pool outweighs a few queued items)
_W_IN_FLIGHT = 0.5
_W_KV = 4.0
_W_SATURATION = 2.0
#: affinity is advisory: follow it only while the affine replica's
#: queue is within this many work units of the best candidate
_AFFINITY_MAX_EXTRA = 4.0


class ReplicaView:
    """The router's book on one replica: identity from the plan,
    vitals from the last /healthz probe (merged with the aggregator's
    labeled gauges), health from passive + active signals."""

    __slots__ = (
        "replica_id", "address", "health", "fails", "probes_failed",
        "queue_depth", "queue_limit", "saturation", "in_flight",
        "kv_occupancy", "decode_depth", "weights_step",
        "weights_generation", "can_generate", "last_probe_s", "ready",
    )

    def __init__(self, replica_id: str, address: str):
        self.replica_id = replica_id
        self.address = address
        self.health = HEALTHY
        self.fails = 0
        self.probes_failed = 0
        self.queue_depth = 0.0
        self.queue_limit = 0
        self.saturation = 0.0
        self.in_flight = 0.0
        self.kv_occupancy = 0.0
        self.decode_depth = 0.0
        self.weights_step: Optional[int] = None
        self.weights_generation: Optional[int] = None
        #: optimistic until a probe or a 404 says otherwise
        self.can_generate = True
        self.last_probe_s = 0.0
        self.ready = True

    def score(self) -> float:
        return (
            self.queue_depth
            + self.decode_depth
            + _W_IN_FLIGHT * self.in_flight
            + _W_KV * self.kv_occupancy
            + _W_SATURATION * self.saturation
        )

    def to_dict(self) -> dict:
        return {
            "replica": self.replica_id,
            "address": self.address,
            "health": self.health,
            "score": round(self.score(), 4),
            "queue_depth": self.queue_depth,
            "queue_limit": self.queue_limit,
            "saturation": self.saturation,
            "in_flight": self.in_flight,
            "kv_occupancy": self.kv_occupancy,
            "decode_queue_depth": self.decode_depth,
            "weights_step": self.weights_step,
            "can_generate": self.can_generate,
            "consecutive_failures": self.fails,
        }


class RequestRouter:
    """The routing core.  Thread-safe; transport is plain urllib so a
    routerd is deployable anywhere the coordinator is reachable."""

    def __init__(
        self,
        coordinator,
        eject_after: int = 3,
        retry_budget_s: float = 10.0,
        attempts: int = 32,
        base_backoff_s: float = 0.02,
        max_backoff_s: float = 0.5,
        probe_timeout_s: float = 5.0,
        request_timeout_s: float = 30.0,
        max_redrives: int = 3,
        affinity_capacity: int = 4096,
        chaos=None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.coordinator = coordinator
        self.eject_after = int(eject_after)
        self.retry_budget_s = float(retry_budget_s)
        self.attempts = int(attempts)
        self.base_backoff_s = float(base_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        self.max_redrives = int(max_redrives)
        self.chaos = chaos
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.RLock()
        self._replicas: "OrderedDict[str, ReplicaView]" = OrderedDict()
        self.plan_generation = -1
        #: fleet-level TTFT p95 from the aggregator's merged histogram
        #: (journaled with saturation replies; per-replica spread uses
        #: the labeled gauges, histograms are fleet-wide)
        self.ttft_p95_s: Optional[float] = None
        #: chain hash -> replica_id holding those cached blocks (LRU,
        #: advisory — a wrong entry costs a prefix MISS, never tokens)
        self._affinity: "OrderedDict[int, str]" = OrderedDict()
        self._affinity_capacity = int(affinity_capacity)
        #: coordinator-journal watermark for drain-event consumption
        self._seen_event_seq = -1
        #: fleet-uniform KV block size, learned from any decode
        #: replica's healthz (0 = not learned yet; affinity disabled)
        self._block_tokens = 0

        reg = telemetry.get_registry()
        self.recorder = telemetry.get_recorder()
        self._m_requests = reg.counter("edl_route_requests_total")
        self._m_retries = reg.counter("edl_route_retries_total")
        self._m_steers = reg.counter("edl_route_steers_total")
        self._m_ejections = reg.counter("edl_route_ejections_total")
        self._m_readmits = reg.counter("edl_route_readmits_total")
        self._m_redrives = reg.counter("edl_route_redrives_total")
        self._m_affinity = reg.counter("edl_route_affinity_total")
        self._g_backends = reg.gauge("edl_route_backends")

    # -- plan / telemetry sync ------------------------------------------------
    def sync(self) -> None:
        """One pull of the serving coordinator's plan + telemetry:
        reconcile the replica set, fold labeled per-replica gauges
        into the views, consume drain flight events (steer-before-503
        signal #1), refresh the fleet TTFT p95."""
        try:
            plan = self.coordinator.plan()
        except Exception:
            return  # coordinator dark: keep routing on the last view
        if plan is not None:
            members = list(plan.members)
            addresses = list(plan.addresses)
            with self._lock:
                current = set(self._replicas)
                planned = set(members)
                for gone in current - planned:
                    del self._replicas[gone]
                for rid, addr in zip(members, addresses):
                    v = self._replicas.get(rid)
                    if v is None:
                        self._replicas[rid] = ReplicaView(rid, addr)
                    elif v.address != addr:
                        # restarted under a new port: it earned a
                        # fresh passive-health slate
                        v.address = addr
                        v.fails = 0
                        if v.health == DRAINING_STATE:
                            v.health = HEALTHY
                self.plan_generation = int(plan.generation)
        try:
            tel = self.coordinator.telemetry() or {}
        except Exception:
            tel = {}
        self._fold_telemetry(tel)
        self._consume_drain_events(tel.get("events") or ())
        self._update_census()

    def _fold_telemetry(self, tel: dict) -> None:
        merged = tel.get("merged") or {}
        hists = merged.get("histograms") or {}
        self.ttft_p95_s = histogram_quantile(
            hists.get("edl_serve_ttft_seconds"), 0.95
        )
        gauges = merged.get("gauges") or {}

        def by_replica(name: str) -> Dict[str, float]:
            out = {}
            for labels, val in (gauges.get(name) or {}).items():
                for part in str(labels).split(","):
                    if part.startswith("replica="):
                        out[part[len("replica="):]] = float(val)
            return out

        depth = by_replica("edl_serve_queue_depth")
        decode = by_replica("edl_serve_decode_queue_depth")
        kv = by_replica("edl_serve_kv_occupancy")
        with self._lock:
            for rid, v in self._replicas.items():
                # probes are fresher than report-cadence telemetry;
                # only fill gaps a probe hasn't covered recently
                if self._clock() - v.last_probe_s < 1.0:
                    continue
                if rid in depth:
                    v.queue_depth = depth[rid]
                if rid in decode:
                    v.decode_depth = decode[rid]
                if rid in kv:
                    v.kv_occupancy = kv[rid]

    def _consume_drain_events(self, events: Sequence[dict]) -> None:
        """serve.drain flight events in the coordinator's merged
        journal mark the victim DRAINING here even when nobody told
        the router directly (kubelet preStop drains, manual POST
        /drain) — the router reads the fleet's own evidence."""
        newest = self._seen_event_seq
        for ev in events:
            seq = int(ev.get("seq", -1))
            if seq <= self._seen_event_seq:
                continue
            newest = max(newest, seq)
            if ev.get("kind") != "serve.drain":
                continue
            data = ev.get("data") or {}
            rid = data.get("replica")
            with self._lock:
                v = self._replicas.get(rid)
                if v is not None and v.health == HEALTHY:
                    self._mark_draining_locked(
                        v, trace=ev.get("trace") or None,
                        source="journal",
                    )
        self._seen_event_seq = newest

    def _update_census(self) -> None:
        with self._lock:
            counts = {HEALTHY: 0, DRAINING_STATE: 0, EJECTED: 0}
            for v in self._replicas.values():
                counts[v.health] += 1
        for state, n in counts.items():
            self._g_backends.set(n, state=state)

    # -- health ---------------------------------------------------------------
    def _mark_draining_locked(self, v: ReplicaView, trace=None,
                              source: str = "intent") -> None:
        v.health = DRAINING_STATE
        self.recorder.record(
            "route.steer",
            {"replica": v.replica_id, "source": source},
            trace=trace,
        )

    def mark_draining(self, replica_ids: Sequence[str],
                      trace: Optional[str] = None) -> None:
        """Drain-intent publication (the scale-down actuator calls
        this BEFORE POSTing /drain to the victims): new admissions
        steer off the victims from this moment, so the drain ack
        implies the router already stopped sending work."""
        with self._lock:
            for rid in replica_ids:
                v = self._replicas.get(rid)
                if v is not None and v.health != DRAINING_STATE:
                    self._mark_draining_locked(v, trace=trace)
        self._update_census()

    def probe(self, replica_id: str) -> bool:
        """Active /healthz probe: refresh one replica's vitals; an
        EJECTED replica that answers ok-and-not-draining is re-admitted
        HERE and only here."""
        with self._lock:
            v = self._replicas.get(replica_id)
        if v is None:
            return False
        health: Optional[dict] = None
        if self.chaos is not None and self.chaos.due("route.probe.fail"):
            health = None
        else:
            try:
                with urllib.request.urlopen(
                    f"http://{v.address}/healthz",
                    timeout=self.probe_timeout_s,
                ) as resp:
                    health = json.loads(resp.read())
            except urllib.error.HTTPError as e:
                try:
                    health = json.loads(e.read() or b"{}")
                except ValueError:
                    health = None
                if e.code != 503:
                    health = None
                elif health is not None and not health.get("ok", False):
                    # 503 healthz: alive but not ready — vitals are
                    # real, the replica is just not routable yet
                    pass
            except Exception:
                health = None
        with self._lock:
            if self._replicas.get(replica_id) is not v:
                return False
            if health is None:
                v.probes_failed += 1
                self._passive_failure_locked(v)
                return False
            v.last_probe_s = self._clock()
            v.probes_failed = 0
            v.queue_depth = float(health.get("queue_depth", 0))
            v.queue_limit = int(health.get("queue_limit", 0))
            v.saturation = float(health.get("saturation", 0.0))
            v.in_flight = float(health.get("in_flight", 0))
            v.weights_step = health.get("weights_step")
            v.weights_generation = health.get("weights_generation")
            v.ready = bool(health.get("ok", False))
            decode = health.get("decode")
            v.can_generate = decode is not None
            if decode:
                v.decode_depth = float(
                    decode.get("decode_queue_depth", 0)
                )
                v.kv_occupancy = float(decode.get("kv_occupancy", 0.0))
                if decode.get("block_tokens"):
                    self._block_tokens = int(decode["block_tokens"])
            draining = bool(health.get("draining", False))
            if draining and v.health == HEALTHY:
                self._mark_draining_locked(v, source="healthz")
            elif not draining and v.ready:
                if v.health == EJECTED:
                    v.health = HEALTHY
                    v.fails = 0
                    self._m_readmits.inc()
                    self.recorder.record(
                        "route.readmit", {"replica": v.replica_id}
                    )
                elif v.health == DRAINING_STATE and v.fails == 0:
                    # a drained-then-restarted replica reports clean:
                    # back in rotation
                    v.health = HEALTHY
        self._update_census()
        return health is not None and bool(health.get("ok", False))

    def probe_all(self) -> None:
        with self._lock:
            ids = list(self._replicas)
        for rid in ids:
            self.probe(rid)

    def _passive_failure_locked(self, v: ReplicaView) -> None:
        v.fails += 1
        if v.health != EJECTED and v.fails >= self.eject_after:
            v.health = EJECTED
            self._m_ejections.inc()
            self.recorder.record(
                "route.eject",
                {"replica": v.replica_id, "consecutive_failures": v.fails},
            )

    def _on_attempt(self, view: ReplicaView, outcome: str, exc) -> None:
        """RetryingClient's per-attempt observer: retry accounting +
        passive health."""
        if outcome == OK:
            with self._lock:
                view.fails = 0
            return
        self._m_retries.inc(reason=outcome)
        with self._lock:
            if outcome == DRAINING:
                # the 503 told us what the intent/journal should have:
                # it is leaving — steer everyone else off it
                if view.health == HEALTHY:
                    self._mark_draining_locked(view, source="503")
            elif outcome in (REFUSED, ERROR):
                self._passive_failure_locked(view)
        self._update_census()

    # -- candidate selection --------------------------------------------------
    def _routable(self, generate: bool = False) -> List[ReplicaView]:
        with self._lock:
            views = [
                v for v in self._replicas.values()
                if v.health == HEALTHY and (v.can_generate or not generate)
            ]
        return sorted(views, key=lambda v: (v.score(), v.replica_id))

    def _order(self, generate: bool = False,
               hashes: Optional[List[int]] = None,
               count_steer: bool = False) -> List[ReplicaView]:
        """The live candidate order for one retry pass: least-loaded
        first, prefix affinity promoted to the front while it stays
        advisory-cheap."""
        order = self._routable(generate=generate)
        if count_steer and order:
            with self._lock:
                any_draining = any(
                    v.health == DRAINING_STATE
                    for v in self._replicas.values()
                )
            if any_draining:
                # this admission would have been eligible for a
                # draining replica and went elsewhere instead
                self._m_steers.inc()
        if hashes:
            affine = None
            with self._lock:
                for h in reversed(hashes):  # deepest block first
                    rid = self._affinity.get(h)
                    if rid is not None:
                        affine = rid
                        break
            hit = False
            if affine is not None and order:
                best = order[0].score()
                for i, v in enumerate(order):
                    if v.replica_id == affine:
                        if v.score() <= best + _AFFINITY_MAX_EXTRA:
                            order.insert(0, order.pop(i))
                            hit = True
                        break
            self._m_affinity.inc(outcome="hit" if hit else "miss")
        return order

    def _remember_affinity(self, hashes: Sequence[int], rid: str) -> None:
        if not hashes:
            return
        with self._lock:
            for h in hashes:
                self._affinity.pop(h, None)
                self._affinity[h] = rid
            while len(self._affinity) > self._affinity_capacity:
                self._affinity.popitem(last=False)

    def _chain_hashes(self, req: dict) -> List[int]:
        tokens = (req.get("inputs") or {}).get("tokens")
        if not tokens:
            return []
        bt = self._probe_block_tokens()
        if not bt:
            return []
        try:
            return chain_hashes(np.asarray(tokens, np.int32), bt)
        except Exception:
            return []

    def _probe_block_tokens(self) -> int:
        # block size is fleet-uniform; learn it once from any healthz
        if self._block_tokens:
            return self._block_tokens
        with self._lock:
            addrs = [v.address for v in self._replicas.values()]
        for addr in addrs:
            try:
                with urllib.request.urlopen(
                    f"http://{addr}/healthz", timeout=self.probe_timeout_s
                ) as resp:
                    h = json.loads(resp.read())
                bt = int((h.get("decode") or {}).get("block_tokens", 0))
                if bt:
                    self._block_tokens = bt
                    return bt
            except Exception:
                continue
        return 0

    # -- request paths --------------------------------------------------------
    def _client(self, order: Callable[[], List[ReplicaView]],
                submit) -> RetryingClient:
        return RetryingClient(
            order,
            submit=submit,
            budget_s=self.retry_budget_s,
            attempts=self.attempts,
            base_backoff_s=self.base_backoff_s,
            max_backoff_s=self.max_backoff_s,
            sleep=self._sleep,
            clock=self._clock,
            on_attempt=self._on_attempt,
        )

    def _chaos_refused(self) -> None:
        if self.chaos is not None and self.chaos.due(
            "route.backend.refused"
        ):
            raise ConnectionError("chaos: backend refused")

    def _resolve(self, call: Callable[[], Any]) -> Any:
        try:
            result = call()
        except RetryBudgetExhausted as e:
            self._m_requests.inc(outcome="exhausted")
            self.recorder.record(
                "route.exhausted",
                {"saturated": e.saturated},
                timing={
                    "attempts": e.attempts,
                    "ttft_p95_s": self.ttft_p95_s,
                },
            )
            raise
        except UpstreamClientError:
            self._m_requests.inc(outcome="error")
            raise
        self._m_requests.inc(outcome="ok")
        return result

    def predict(self, req: dict) -> dict:
        def submit(view: ReplicaView, request: dict) -> dict:
            self._chaos_refused()
            return http_call(
                view.address, "/predict", request,
                timeout=self.request_timeout_s,
            )

        client = self._client(
            lambda: self._order(count_steer=True), submit
        )
        return self._resolve(lambda: client.call(req))

    def generate(self, req: dict) -> dict:
        """Non-streaming /generate (stream=false): spread + absorb,
        with prefix affinity."""
        hashes = self._chain_hashes(req)

        def submit(view: ReplicaView, request: dict) -> dict:
            self._chaos_refused()
            try:
                out = http_call(
                    view.address, "/generate", request,
                    timeout=self.request_timeout_s,
                )
            except UpstreamClientError as e:
                if e.status == 404:
                    # no decode path on this replica: remember and
                    # let the retry walk on
                    with self._lock:
                        view.can_generate = False
                    raise RuntimeError("no decode path") from None
                raise
            self._remember_affinity(hashes, view.replica_id)
            return out

        client = self._client(
            lambda: self._order(generate=True, hashes=hashes,
                                count_steer=True),
            submit,
        )
        return self._resolve(lambda: client.call(req))

    # -- streaming /generate with re-drive ------------------------------------
    def _open_stream(self, view: ReplicaView, payload: dict):
        """POST /generate stream=true; returns the live HTTPResponse.
        Raises the typed admission errors exactly like http_call."""
        self._chaos_refused()
        req = urllib.request.Request(
            f"http://{view.address}/generate",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            return urllib.request.urlopen(
                req, timeout=self.request_timeout_s
            )
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except ValueError:
                body = {}
            if e.code == 429:
                raise QueueFullError(
                    body.get("error", "queue full"),
                    retry_after=float(body.get("retry_after_s", 0.05)),
                ) from None
            if e.code == 503:
                raise DrainingError(
                    body.get("error", "unavailable"),
                    retry_after=float(body.get("retry_after_s", 0.5)),
                ) from None
            if 400 <= e.code < 500:
                raise UpstreamClientError(e.code, body) from None
            raise RuntimeError(body.get("error") or f"upstream {e.code}")
        except urllib.error.URLError as e:
            raise ConnectionError(str(e.reason)) from None
        except (ConnectionError, TimeoutError, OSError) as e:
            raise ConnectionError(str(e)) from None

    def generate_stream(self, req: dict, emit: Callable[[dict], None]):
        """Relay a streaming generation, surviving replica loss.

        The client sees ONE coherent ndjson stream: token lines with
        globally consistent indices, at most the batcher's own restart
        semantics (a ``restart`` line voids prior tokens), and exactly
        one terminal done/error line.  A mid-stream cut re-drives on a
        survivor: RESUME when the survivor's first-token purity stamp
        matches the step that produced the emitted prefix (greedy
        decode continues the prefix exactly — nothing duplicated,
        nothing dropped), RESTART otherwise."""
        prompt = list((req.get("inputs") or {}).get("tokens") or [])
        max_new = req.get("max_new_tokens")
        hashes = self._chain_hashes(req)
        emitted: List[int] = []
        leg_step: Optional[int] = None  # stamp of the emitted prefix
        redrives = 0
        deadline = self._clock() + self.retry_budget_s + (
            float(req.get("deadline_ms", 0) or 0) / 1000.0
        )
        resuming = False

        while True:
            if resuming and max_new is not None:
                remaining = int(max_new) - len(emitted)
                if remaining <= 0:
                    emit({"done": True, "tokens": list(emitted),
                          "redriven": redrives})
                    return
                payload = dict(req)
                payload["inputs"] = {"tokens": prompt + emitted}
                payload["max_new_tokens"] = remaining
            else:
                payload = dict(req)
            payload["stream"] = True

            def submit(view: ReplicaView, _p=payload):
                resp = self._open_stream(view, _p)
                return view, resp

            client = self._client(
                lambda: self._order(
                    generate=True, hashes=hashes, count_steer=True
                ),
                submit,
            )
            try:
                view, resp = client.call(payload)
            except RetryBudgetExhausted as e:
                self._m_requests.inc(outcome="exhausted")
                self.recorder.record(
                    "route.exhausted", {"saturated": e.saturated}
                )
                raise
            except UpstreamClientError as e:
                if resuming:
                    # e.g. prompt+prefix outgrew the context window:
                    # fall back to a clean restart of the original
                    resuming = False
                    emitted = []
                    leg_step = None
                    emit({"restart": True, "redrive": True})
                    self._m_redrives.inc(outcome="restart")
                    self.recorder.record(
                        "route.redrive", {"outcome": "restart"}
                    )
                    continue
                self._m_requests.inc(outcome="error")
                raise

            cut = False
            leg_tokens = 0
            abandon_restart = False
            try:
                while True:
                    line = resp.readline()
                    if not line:
                        cut = True  # ended without a terminal line
                        break
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        cut = True  # torn mid-line: the kill shape
                        break
                    if "token" in ev:
                        if int(ev.get("i", -1)) == 0:
                            step = ev.get("weights_step")
                            if resuming and leg_step is not None and (
                                step is None or step != leg_step
                            ):
                                # the survivor swapped between probe
                                # and prefill: resuming would mix
                                # weight generations — abandon the
                                # leg BEFORE forwarding anything
                                abandon_restart = True
                                break
                            leg_step = step
                        leg_tokens += 1
                        tok = int(ev["token"])
                        out = {"token": tok, "i": len(emitted)}
                        if "weights_step" in ev and not emitted:
                            out["weights_step"] = ev["weights_step"]
                        emitted.append(tok)
                        emit(out)
                        if self.chaos is not None and self.chaos.due(
                            "route.stream.cut"
                        ):
                            cut = True
                            self._on_attempt(view, REFUSED,
                                             ConnectionError("cut"))
                            break
                    elif ev.get("restart"):
                        # the replica's own hot-swap restart: prior
                        # tokens are void for the client too
                        emitted = []
                        leg_step = ev.get("weights_step")
                        resuming = False
                        emit(ev)
                    elif "done" in ev:
                        done = dict(ev)
                        done["tokens"] = list(emitted)
                        if redrives:
                            done["redriven"] = redrives
                        emit(done)
                        self._m_requests.inc(outcome="ok")
                        return
                    elif "error" in ev:
                        emit(ev)
                        self._m_requests.inc(outcome="error")
                        return
                    else:
                        emit(ev)
            except (ConnectionError, OSError,
                    http.client.HTTPException) as e:
                cut = True
                self._on_attempt(view, REFUSED, e)
            finally:
                try:
                    resp.close()
                except Exception:
                    pass

            if abandon_restart:
                resuming = False
                emitted = []
                leg_step = None
                emit({"restart": True, "redrive": True})
                self._m_redrives.inc(outcome="restart")
                self.recorder.record(
                    "route.redrive", {"outcome": "restart"}
                )
                continue
            if not cut:
                continue  # defensive: loop re-admits
            redrives += 1
            if redrives > self.max_redrives or self._clock() >= deadline:
                self._m_requests.inc(outcome="exhausted")
                self.recorder.record(
                    "route.exhausted", {"saturated": False}
                )
                raise RetryBudgetExhausted(
                    f"stream cut {redrives}x, budget spent",
                    saturated=False,
                )
            # resume-or-restart: purity decides.  We can only resume
            # when we KNOW the step that produced the emitted prefix
            # and a token budget to subtract from.
            if emitted and leg_step is not None and max_new is not None:
                resuming = True
                self._m_redrives.inc(outcome="resume")
                self.recorder.record(
                    "route.redrive",
                    {"outcome": "resume"},
                    timing={"at_token": len(emitted)},
                )
            else:
                resuming = False
                if emitted or leg_tokens:
                    emit({"restart": True, "redrive": True})
                emitted = []
                leg_step = None
                self._m_redrives.inc(outcome="restart")
                self.recorder.record(
                    "route.redrive", {"outcome": "restart"}
                )

    # -- introspection --------------------------------------------------------
    def routing_table(self) -> dict:
        with self._lock:
            replicas = [v.to_dict() for v in self._replicas.values()]
        return {
            "plan_generation": self.plan_generation,
            "ttft_p95_s": self.ttft_p95_s,
            "replicas": replicas,
            "affinity_entries": len(self._affinity),
        }


class RouterServer:
    """The routerd HTTP front (coord_service idiom): /predict and
    /generate proxied through a ``RequestRouter``, /routes for
    operators (``edl route``), /drain_intent for the scale-down
    actuator, /healthz + /metrics for the platform."""

    def __init__(self, router: RequestRouter, host: str = "0.0.0.0",
                 port: int = 0, sync_interval_s: float = 0.5):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.router = router
        self.sync_interval_s = float(sync_interval_s)
        self._stop = threading.Event()
        self._boot = uuid.uuid4().hex[:12]
        self._telemetry_seq = 0
        self._started = False
        registry = telemetry.get_registry()
        self_server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _reply(self, obj, code=200, headers=()):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _read_json(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):
                r = self_server.router
                if self.path == "/healthz":
                    table = r.routing_table()
                    healthy = sum(
                        1 for x in table["replicas"]
                        if x["health"] == HEALTHY
                    )
                    self._reply(
                        {
                            "ok": healthy > 0,
                            "role": "router",
                            "plan_generation": table["plan_generation"],
                            "backends": len(table["replicas"]),
                            "healthy": healthy,
                        },
                        200 if healthy > 0 else 503,
                    )
                elif self.path == "/routes":
                    self._reply(self_server.router.routing_table())
                elif self.path == "/metrics":
                    body = registry.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._reply({"error": "not found"}, 404)

            def _proxy(self, call):
                try:
                    self._reply(call())
                except RetryBudgetExhausted as e:
                    if e.saturated:
                        # the fleet is BUSY: tell the client when to
                        # come back
                        self._reply(
                            {
                                "error": str(e),
                                "saturated": True,
                                "retry_after_s": e.retry_after,
                            },
                            503,
                            headers=(
                                ("Retry-After", f"{e.retry_after:.3f}"),
                            ),
                        )
                    else:
                        # the fleet is GONE: no Retry-After promises
                        self._reply({"error": str(e)}, 502)
                except UpstreamClientError as e:
                    self._reply(e.body or {"error": str(e)}, e.status)
                except ValueError as e:
                    self._reply({"error": str(e)}, 400)
                except Exception as e:
                    self._reply({"error": str(e)}, 500)

            def do_POST(self):
                r = self_server.router
                if self.path == "/predict":
                    try:
                        req = self._read_json()
                    except ValueError:
                        self._reply({"error": "bad json"}, 400)
                        return
                    self._proxy(lambda: r.predict(req))
                elif self.path == "/generate":
                    try:
                        req = self._read_json()
                    except ValueError:
                        self._reply({"error": "bad json"}, 400)
                        return
                    if not req.get("stream"):
                        self._proxy(lambda: r.generate(req))
                        return
                    self._do_generate_stream(r, req)
                elif self.path == "/drain_intent":
                    try:
                        req = self._read_json()
                    except ValueError:
                        self._reply({"error": "bad json"}, 400)
                        return
                    r.mark_draining(
                        req.get("replicas") or (),
                        trace=req.get("trace") or None,
                    )
                    self._reply({"ok": True})
                else:
                    self._reply({"error": "not found"}, 404)

            def _do_generate_stream(self, r, req):
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(obj):
                    data = (json.dumps(obj) + "\n").encode()
                    self.wfile.write(
                        f"{len(data):x}\r\n".encode() + data + b"\r\n"
                    )
                    self.wfile.flush()

                try:
                    try:
                        r.generate_stream(req, chunk)
                    except RetryBudgetExhausted as e:
                        chunk({"error": str(e),
                               "saturated": e.saturated})
                    except UpstreamClientError as e:
                        chunk(e.body or {"error": str(e)})
                    except Exception as e:
                        chunk({"error": str(e)})
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionError):
                    pass  # client went away

        class _Server(ThreadingHTTPServer):
            daemon_threads = True

        self._httpd = _Server((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._threads: List[threading.Thread] = []

    def start(self) -> "RouterServer":
        self._started = True
        t = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True,
            name="edl-routerd",
        )
        t.start()
        self._threads.append(t)
        m = threading.Thread(
            target=self._maintain, daemon=True, name="edl-routerd-sync"
        )
        m.start()
        self._threads.append(m)
        return self

    def _maintain(self) -> None:
        while not self._stop.is_set():
            try:
                self.router.sync()
                self.router.probe_all()
                self._report_telemetry()
            except Exception:
                pass
            self._stop.wait(self.sync_interval_s)

    def _report_telemetry(self) -> None:
        """Ship the router's own registry to the serving coordinator as
        source \"router\" (same cumulative-snapshot wire as replicas),
        so ``edl metrics`` shows steers/retries/ejections next to the
        fleet it fronts.  Best-effort: a dark coordinator costs one
        report, never a route."""
        report = getattr(self.router.coordinator, "report_telemetry", None)
        if report is None:
            return
        self._telemetry_seq += 1
        try:
            report(
                "router",
                snapshot=telemetry.get_registry().snapshot(),
                seq=self._telemetry_seq,
                boot=self._boot,
            )
        except Exception:
            pass

    def stop(self) -> None:
        self._stop.set()
        try:
            if self._started:
                # shutdown() blocks on serve_forever's ack — it would
                # hang forever on a constructed-but-never-started server
                self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass


def route_run(
    coordinator_addr: str,
    port: int = 0,
    host: str = "0.0.0.0",
    retry_budget_s: float = 10.0,
    probe_interval_s: float = 0.5,
    eject_after: int = 3,
) -> RouterServer:
    """Build-and-start from the EDL_ROUTE_* env contract (the routerd
    pod entrypoint)."""
    from edl_tpu.runtime.coord_service import HTTPCoordinator

    coord = HTTPCoordinator(coordinator_addr)
    router = RequestRouter(
        coord,
        retry_budget_s=retry_budget_s,
        eject_after=eject_after,
    )
    server = RouterServer(
        router, host=host, port=port, sync_interval_s=probe_interval_s
    )
    return server.start()


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="routerd",
        description="EDL serving fleet front door (request router)",
    )
    p.add_argument(
        "--coordinator",
        default=os.environ.get("EDL_COORDINATOR_ADDR", "127.0.0.1:7077"),
    )
    p.add_argument(
        "--port",
        type=int,
        default=int(os.environ.get("EDL_ROUTE_PORT", "7190")),
    )
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument(
        "--retry-budget-ms",
        type=float,
        default=float(os.environ.get("EDL_ROUTE_RETRY_BUDGET_MS", "10000")),
    )
    p.add_argument(
        "--probe-interval-ms",
        type=float,
        default=float(os.environ.get("EDL_ROUTE_PROBE_MS", "500")),
    )
    p.add_argument(
        "--eject-after",
        type=int,
        default=int(os.environ.get("EDL_ROUTE_EJECT_AFTER", "3")),
    )
    args = p.parse_args(argv)
    server = route_run(
        args.coordinator,
        port=args.port,
        host=args.host,
        retry_budget_s=args.retry_budget_ms / 1000.0,
        probe_interval_s=args.probe_interval_ms / 1000.0,
        eject_after=args.eject_after,
    )
    print(
        f"routerd listening on :{server.port} "
        f"(coordinator {args.coordinator})",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
